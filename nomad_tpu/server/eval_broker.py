"""Eval broker: leader-only, in-memory, at-least-once evaluation queue.

Fresh implementation with the semantics of the reference broker
(/root/reference/nomad/eval_broker.go:33-633):

- priority queues per scheduler type; highest priority dequeued first,
  ties broken by create index (eval_broker.go:597-605)
- per-job serialization: one outstanding eval per JobID, later ones block
  (eval_broker.go:173-183)
- unack tracking with Nack timers; missing Ack within nack_timeout
  redelivers (eval_broker.go:318-328)
- delivery limit: after N deliveries the eval lands in the ``_failed``
  queue for the leader to reap (eval_broker.go:19, 489-495)
- wait/time-delay evals for rolling updates (eval_broker.go:143-151)
- blocking Dequeue with timeout (eval_broker.go:214-246)

Additionally, ``dequeue_batch`` implements the TPU north-star extension
(SURVEY.md §7 "Batched evals"): drain up to B compatible ready evals in one
call so the worker can coalesce them into a single device dispatch.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nomad_tpu import faults, prng, telemetry, trace
from nomad_tpu.structs import Evaluation, generate_uuid

FAILED_QUEUE = "_failed"


class BrokerError(Exception):
    pass


class BrokerFullError(BrokerError):
    """Typed NACK for an enqueue past the broker's pending cap: the eval
    stays durable in the state store (it was committed through raft) and
    is NOT tracked by the broker — the server's readmission loop
    re-enqueues it when capacity frees. Never silent growth."""


ERR_NOT_OUTSTANDING = "evaluation is not outstanding"
ERR_TOKEN_MISMATCH = "evaluation token does not match"
ERR_NACK_TIMEOUT_REACHED = "evaluation nack timeout reached"
ERR_DISABLED = "eval broker disabled"
ERR_QUEUE_FULL = "eval broker pending cap reached"


@dataclass
class SchedulerStats:
    ready: int = 0
    unacked: int = 0


@dataclass
class BrokerStats:
    total_ready: int = 0
    total_unacked: int = 0
    total_blocked: int = 0
    total_waiting: int = 0
    by_scheduler: Dict[str, SchedulerStats] = field(default_factory=dict)

    def sched(self, queue: str) -> SchedulerStats:
        if queue not in self.by_scheduler:
            self.by_scheduler[queue] = SchedulerStats()
        return self.by_scheduler[queue]


class _PriorityQueue:
    """Max-priority heap of evaluations: highest priority first, then oldest
    create index (eval_broker.go:597-605)."""

    _counter = itertools.count()

    def __init__(self) -> None:
        self._heap: List[Tuple[int, int, int, Evaluation]] = []

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(
            self._heap, (-ev.priority, ev.create_index, next(self._counter), ev)
        )

    def pop(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[3]

    def peek(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return self._heap[0][3]

    def __len__(self) -> int:
        return len(self._heap)


class _UnackEval:
    __slots__ = ("eval", "token", "nack_timer")

    def __init__(self, ev: Evaluation, token: str, nack_timer: threading.Timer):
        self.eval = ev
        self.token = token
        self.nack_timer = nack_timer


class EvalBroker:
    """At-least-once evaluation broker (reference: eval_broker.go:43-111)."""

    def __init__(self, nack_timeout: float = 60.0, delivery_limit: int = 3,
                 seed: int = 0, pending_cap: int = 0):
        if nack_timeout < 0:
            raise ValueError("timeout cannot be negative")
        import logging as _logging

        self.logger = _logging.getLogger("nomad_tpu.eval_broker")
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit
        # Enforced bound on pending work (ready + blocked + waiting).
        # 0 = unbounded (the historical posture). An enqueue past the cap
        # raises BrokerFullError — typed NACK, counted as
        # broker.depth_limit_breach — and sets the spill flag the
        # server's readmission loop polls (spilled evals stay durable in
        # state; the broker never silently grows past the cap).
        self.pending_cap = int(pending_cap)
        self._spilled = False
        # Scheduler-queue tie-break stream: seeded per broker (name-salted,
        # the faults.py pattern) so the choice among equal-priority queues
        # never couples to the process-global random cursor.
        self._rng = prng.stream(seed, "broker.scheduler_choice")
        self._enabled = False
        self._lock = threading.Lock()
        self._work_available = threading.Condition(self._lock)
        self.stats = BrokerStats()

        # eval ID -> delivery attempts
        self._evals: Dict[str, int] = {}
        # JobID -> outstanding eval ID (serialization)
        self._job_evals: Dict[str, str] = {}
        # JobID -> blocked evals
        self._blocked: Dict[str, _PriorityQueue] = {}
        # scheduler type -> ready evals
        self._ready: Dict[str, _PriorityQueue] = {}
        # eval ID -> unacked delivery
        self._unack: Dict[str, _UnackEval] = {}
        # eval ID -> wait timer
        self._time_wait: Dict[str, threading.Timer] = {}
        # eval ID -> count of token-verified plans currently in the
        # applier (redelivery deferred while nonzero; see plan_inflight).
        self._inflight_plans: Dict[str, int] = {}
        # Trace spans (nomad_tpu.trace): the root 'eval' span opened at
        # enqueue (finished at ack/flush) and the current 'broker.wait'
        # span (enqueue/nack -> dequeue). The broker is the trace's
        # birthplace: trace_id IS the eval id.
        self._trace_root: Dict[str, object] = {}
        self._trace_wait: Dict[str, object] = {}
        # eval ID -> raft index the processing worker must observe in ITS
        # local FSM before snapshotting. For a freshly-created eval this is
        # the eval's own apply index (same as modify_index); for an eval
        # re-enqueued after a leadership change it is the new leader's
        # post-barrier applied index — which covers any plan an earlier
        # delivery committed right before the old leader died. Without it
        # a redelivered eval can be scheduled against a snapshot that
        # predates its own first plan and be placed TWICE (the failover
        # exactly-once hole; newer reference releases carry the same
        # mechanism as Dequeue's WaitIndex).
        self._wait_index: Dict[str, int] = {}

    # -- enable/disable ----------------------------------------------------

    @property
    def enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
        if not enabled:
            self.flush()

    # -- enqueue -----------------------------------------------------------

    def enqueue(self, ev: Evaluation, wait_index: int = 0) -> None:
        """eval_broker.go:131-155. Raises BrokerFullError past the
        pending cap (the eval stays durable in state; see pending_cap)."""
        with self._lock:
            self._enqueue_one_locked(ev, wait_index)

    def enqueue_many(self, evals, wait_index: int = 0) -> int:
        """Atomic multi-enqueue: every eval of one raft entry becomes
        ready under a single lock hold. Without this, the first eval's
        notify races the rest into the queue and a coalescing batch
        dequeuer (dequeue_batch) wakes to a fragment — the burst then
        solves as several small dispatches instead of one stacked one.

        The FSM path: a committed entry cannot fail, so over-cap evals
        SPILL (counted, flag set for the readmission loop) instead of
        raising; returns how many spilled."""
        spilled = 0
        with self._lock:
            for ev in evals:
                try:
                    self._enqueue_one_locked(ev, wait_index)
                except BrokerFullError:
                    spilled += 1
        if spilled:
            self.logger.debug(
                "broker %x: SPILL %d evals past pending cap %d",
                id(self), spilled, self.pending_cap)
        return spilled

    def pending_total(self) -> int:
        """Current pending depth (ready + blocked + waiting) — the
        quantity pending_cap bounds; the admission front door's
        acceptance-queue probe."""
        with self._lock:
            return self._pending_total_locked()

    def _pending_total_locked(self) -> int:
        return (self.stats.total_ready + self.stats.total_blocked
                + self.stats.total_waiting)

    def reclaim_spilled(self) -> bool:
        """The readmission handshake: True exactly once per spill episode
        once capacity has freed (the server then re-enqueues pending
        evals from state). The flag re-arms on the next over-cap
        enqueue."""
        with self._lock:
            if not self._spilled:
                return False
            if (self.pending_cap
                    and self._pending_total_locked() >= self.pending_cap):
                return False
            self._spilled = False
            return True

    def _enqueue_one_locked(self, ev: Evaluation, wait_index: int) -> None:
        if ev.id in self._evals:
            # Already tracked (redelivery bookkeeping): only refresh the
            # wait index — never counts against the cap.
            if wait_index:
                self._wait_index[ev.id] = max(
                    wait_index, self._wait_index.get(ev.id, 0)
                )
            return
        if (self._enabled and self.pending_cap
                and self._pending_total_locked() >= self.pending_cap):
            # Typed NACK before ANY tracking state mutates: a spilled
            # eval leaves zero residue here (its wait-index floor is
            # re-derived from the leader's applied index at readmission).
            self._spilled = True
            telemetry.incr_counter(("broker", "depth_limit_breach"))
            raise BrokerFullError(ERR_QUEUE_FULL)
        if wait_index:
            self._wait_index[ev.id] = max(
                wait_index, self._wait_index.get(ev.id, 0)
            )
        if self._enabled:
            self._evals[ev.id] = 0
            telemetry.incr_counter(("broker", "enqueue"))
            if ev.id not in self._trace_root:
                root = trace.get_tracer().start_span(
                    ev.id, "eval", root=True,
                    annotations={
                        "job_id": ev.job_id, "type": ev.type,
                        "priority": ev.priority,
                        "triggered_by": ev.triggered_by,
                    },
                )
                if root is not trace.NULL_SPAN:
                    self._trace_root[ev.id] = root

        if ev.wait > 0:
            timer = threading.Timer(ev.wait, self._enqueue_waiting, args=(ev,))
            timer.daemon = True
            timer.start()
            self._time_wait[ev.id] = timer
            self.stats.total_waiting += 1
            return

        self._enqueue_locked(ev, ev.type)

    def _enqueue_waiting(self, ev: Evaluation) -> None:
        with self._lock:
            self._time_wait.pop(ev.id, None)
            self.stats.total_waiting -= 1
            self._enqueue_locked(ev, ev.type)

    def _enqueue_locked(self, ev: Evaluation, queue: str) -> None:
        """eval_broker.go:166-212 (lock held)"""
        if not self._enabled:
            return

        # The ready/blocked wait starts here (redeliveries and
        # blocked->ready promotions restart it); finished at dequeue so
        # the span covers the full queue wait. A still-open prior wait
        # span (the eval transited the blocked queue) is finished first —
        # overwriting it would leak an open span into the trace forever.
        root = self._trace_root.get(ev.id)
        if root is not None:
            prior = self._trace_wait.pop(ev.id, None)
            if prior is not None:
                prior.finish()
            self._trace_wait[ev.id] = trace.get_tracer().start_span(
                ev.id, "broker.wait", parent=root,
                annotations={"queue": queue},
            )

        pending_eval = self._job_evals.get(ev.job_id, "")
        if pending_eval == "":
            self._job_evals[ev.job_id] = ev.id
        elif pending_eval != ev.id:
            blocked = self._blocked.setdefault(ev.job_id, _PriorityQueue())
            blocked.push(ev)
            self.stats.total_blocked += 1
            wait = self._trace_wait.get(ev.id)
            if wait is not None:
                wait.annotate("blocked", True)
            return

        ready = self._ready.setdefault(queue, _PriorityQueue())
        ready.push(ev)
        self.stats.total_ready += 1
        self.stats.sched(queue).ready += 1
        self._work_available.notify_all()

    # -- dequeue -----------------------------------------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[Evaluation], str]:
        """Blocking dequeue of the highest-priority ready eval for any of the
        given scheduler types (eval_broker.go:214-246). Returns (None, "")
        on timeout."""
        # Injected dequeue failure/stall BEFORE the lock: the worker's
        # dequeue loop sees exactly what a leader-transition blip looks
        # like (BrokerError -> backoff + retry), and a delay never holds
        # the broker lock against acks/nacks.
        fault = faults.fire("broker.dequeue", target=",".join(schedulers))
        if fault is not None and fault.mode in ("error", "drop"):
            raise BrokerError("injected fault: broker.dequeue")
        deadline = None
        with self._lock:
            while True:
                if not self._enabled:
                    raise BrokerError(ERR_DISABLED)
                out = self._scan_for_schedulers(schedulers)
                if out is not None:
                    return out
                if timeout is not None:
                    import time as _time

                    if deadline is None:
                        deadline = _time.monotonic() + timeout
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return None, ""
                    self._work_available.wait(remaining)
                else:
                    self._work_available.wait()

    def dequeue_batch(
        self,
        schedulers: List[str],
        max_batch: int,
        timeout: Optional[float] = None,
    ) -> List[Tuple[Evaluation, str]]:
        """Coalescing dequeue: blocks for the first eval, then drains up to
        ``max_batch - 1`` more ready evals without blocking. Every returned
        eval has its own token + nack timer; each must be Ack'd/Nack'd
        individually. Per-job serialization still holds (distinct jobs only).
        """
        first = self.dequeue(schedulers, timeout)
        if first[0] is None:
            return []
        batch = [first]
        with self._lock:
            while len(batch) < max_batch:
                out = self._scan_for_schedulers(schedulers)
                if out is None:
                    break
                batch.append(out)
        return batch

    def wait_index(self, eval_id: str) -> int:
        """The raft index a worker must observe locally before snapshotting
        for this eval (0 when none was recorded)."""
        with self._lock:
            return self._wait_index.get(eval_id, 0)

    def _scan_for_schedulers(
        self, schedulers: List[str]
    ) -> Optional[Tuple[Evaluation, str]]:
        """Pick the highest-priority eval across queues (lock held)
        (eval_broker.go:248-304)."""
        eligible: List[str] = []
        eligible_priority = 0
        for sched in schedulers:
            pending = self._ready.get(sched)
            if pending is None:
                continue
            ready = pending.peek()
            if ready is None:
                continue
            if not eligible or ready.priority > eligible_priority:
                eligible = [sched]
                eligible_priority = ready.priority
            elif eligible_priority == ready.priority:
                eligible.append(sched)

        if not eligible:
            return None
        sched = eligible[0] if len(eligible) == 1 else self._rng.choice(eligible)
        return self._dequeue_for_sched(sched)

    def _dequeue_for_sched(self, sched: str) -> Tuple[Evaluation, str]:
        """eval_broker.go:306-341 (lock held)"""
        ev = self._ready[sched].pop()
        token = generate_uuid()

        nack_timer = threading.Timer(
            self.nack_timeout, self._nack_from_timer, args=(ev.id, token)
        )
        nack_timer.daemon = True
        nack_timer.start()

        self._unack[ev.id] = _UnackEval(ev, token, nack_timer)
        self._evals[ev.id] = self._evals.get(ev.id, 0) + 1
        self.logger.debug(
            "broker %x: DELIVER eval=%s token=%s attempt=%d wait_index=%d",
            id(self), ev.id[:8], token[:8], self._evals[ev.id],
            self._wait_index.get(ev.id, 0),
        )

        self.stats.total_ready -= 1
        self.stats.total_unacked += 1
        by_sched = self.stats.sched(sched)
        by_sched.ready -= 1
        by_sched.unacked += 1

        telemetry.incr_counter(("broker", "dequeue"))
        wait_span = self._trace_wait.pop(ev.id, None)
        if wait_span is not None:
            wait_span.annotate("attempt", self._evals[ev.id])
            wait_span.finish()
            if wait_span.end is not None:
                telemetry.add_sample(
                    ("broker", "wait"),
                    (wait_span.end - wait_span.start) * 1000.0,
                )
        return ev, token

    def _nack_from_timer(self, eval_id: str, token: str,
                         from_timer: bool = True) -> None:
        # ``from_timer`` rides deferral re-arms so a deferred WORKER nack
        # retried through this callback is not miscounted as a timeout.
        # Defer redelivery while a plan for this delivery sits in the
        # applier: nacking now would hand the eval to a second worker whose
        # snapshot races the in-flight plan's commit — the duplicate-
        # placement window the exactly-once chaos test caught. The applier
        # bounds the deferral by clearing the inflight mark (and re-arming
        # the timer via outstanding_reset) when the commit finishes.
        try:
            # nack() itself defers (short re-check) while a plan from this
            # delivery is mid-commit in the applier.
            self.nack(eval_id, token, _from_timer=from_timer)
        except BrokerError:
            pass

    def outstanding_reset_and_mark(self, eval_id: str, token: str) -> None:
        """Atomic token verification + inflight mark for the plan applier
        (one lock hold). Two separate calls leave a window where the nack
        timer fires between the reset and the mark — redelivering the
        eval while its plan is about to commit, which is exactly the
        double-placement race the mark exists to close. Raises
        BrokerError like outstanding_reset."""
        with self._lock:
            self._outstanding_reset_locked(eval_id, token)
            self._inflight_plans[eval_id] = \
                self._inflight_plans.get(eval_id, 0) + 1
            self.logger.debug(
                "broker %x: PLAN-MARK eval=%s token=%s",
                id(self), eval_id[:8], token[:8])

    def plan_done(self, eval_id: str, commit_index: int = 0) -> None:
        """Clear the inflight mark; bump the eval's wait_index to the
        plan's commit index FIRST (same lock), so any deferred redelivery
        that proceeds next forces the worker's snapshot past the plan."""
        with self._lock:
            # Only bump while the eval is still tracked: ack may have won
            # the race with this finally-block and already dropped the
            # eval — re-inserting would leak an entry until flush.
            if commit_index and (eval_id in self._unack
                                 or eval_id in self._evals):
                self._wait_index[eval_id] = max(
                    commit_index, self._wait_index.get(eval_id, 0)
                )
            n = self._inflight_plans.get(eval_id, 0) - 1
            if n <= 0:
                self._inflight_plans.pop(eval_id, None)
            else:
                self._inflight_plans[eval_id] = n

    # -- outstanding/ack/nack ---------------------------------------------

    def outstanding(self, eval_id: str) -> Tuple[str, bool]:
        """eval_broker.go:384-394"""
        with self._lock:
            unack = self._unack.get(eval_id)
            if unack is None:
                return "", False
            return unack.token, True

    def outstanding_reset(self, eval_id: str, token: str) -> None:
        """Reset the Nack timer if the token matches
        (eval_broker.go:396-412); raises BrokerError otherwise."""
        with self._lock:
            self._outstanding_reset_locked(eval_id, token)

    def _outstanding_reset_locked(self, eval_id: str, token: str) -> None:
        unack = self._unack.get(eval_id)
        if unack is None:
            raise BrokerError(ERR_NOT_OUTSTANDING)
        if unack.token != token:
            raise BrokerError(ERR_TOKEN_MISMATCH)
        unack.nack_timer.cancel()
        new_timer = threading.Timer(
            self.nack_timeout, self._nack_from_timer, args=(eval_id, token)
        )
        new_timer.daemon = True
        new_timer.start()
        unack.nack_timer = new_timer

    def ack(self, eval_id: str, token: str) -> None:
        """Positive acknowledgment; unblocks the next eval for the job
        (eval_broker.go:414-462)."""
        with self._lock:
            unack = self._unack.get(eval_id)
            if unack is None:
                raise BrokerError("Evaluation ID not found")
            if unack.token != token:
                raise BrokerError("Token does not match for Evaluation ID")
            job_id = unack.eval.job_id
            unack.nack_timer.cancel()

            self.stats.total_unacked -= 1
            queue = unack.eval.type
            if self._evals.get(eval_id, 0) >= self.delivery_limit:
                queue = FAILED_QUEUE
            self.stats.sched(queue).unacked -= 1

            del self._unack[eval_id]
            self._evals.pop(eval_id, None)
            self._job_evals.pop(job_id, None)
            self._wait_index.pop(eval_id, None)
            self.logger.debug("broker %x: ACK eval=%s token=%s",
                              id(self), eval_id[:8], token[:8])

            telemetry.incr_counter(("broker", "ack"))
            wait = self._trace_wait.pop(eval_id, None)
            if wait is not None:
                wait.finish()
            root = self._trace_root.pop(eval_id, None)
            if root is not None:
                root.annotate("outcome", "ack").finish()
                trace.get_tracer().mark_done(eval_id)

            blocked = self._blocked.get(job_id)
            if blocked is not None and len(blocked) > 0:
                ev = blocked.pop()
                if len(blocked) == 0:
                    del self._blocked[job_id]
                self.stats.total_blocked -= 1
                self._enqueue_locked(ev, ev.type)

    def nack(self, eval_id: str, token: str, _from_timer: bool = False) -> None:
        """Negative acknowledgment: redeliver or fail
        (eval_broker.go:464-497). ``_from_timer`` marks the nack-timeout
        path so the broker.nack_timeout counter counts only ACTUAL
        timeout redeliveries — not deferral retries or stale timer fires."""
        with self._lock:
            unack = self._unack.get(eval_id)
            if unack is None:
                raise BrokerError("Evaluation ID not found")
            if unack.token != token:
                raise BrokerError("Token does not match for Evaluation ID")
            if eval_id in self._inflight_plans:
                # A plan from THIS delivery is mid-commit in the applier
                # (e.g. the worker lost the submit response and gave up):
                # redelivering now hands the eval to a worker whose
                # snapshot races the commit — double placement. Defer: a
                # short re-check timer retries the nack after plan_done
                # has bumped wait_index past the commit.
                unack.nack_timer.cancel()
                # Propagate the ORIGIN of this nack into the retry: a
                # deferred worker nack must not count as a timeout when
                # the retry lands.
                retry = threading.Timer(
                    0.25, self._nack_from_timer,
                    args=(eval_id, token, _from_timer),
                )
                retry.daemon = True
                unack.nack_timer = retry
                retry.start()
                self.logger.debug(
                    "broker %x: NACK-DEFER eval=%s token=%s (plan inflight)",
                    id(self), eval_id[:8], token[:8])
                return
            unack.nack_timer.cancel()
            del self._unack[eval_id]
            self.logger.debug("broker %x: NACK eval=%s token=%s",
                              id(self), eval_id[:8], token[:8])

            telemetry.incr_counter(("broker", "nack"))
            if _from_timer:
                telemetry.incr_counter(("broker", "nack_timeout"))
            self.stats.total_unacked -= 1
            self.stats.sched(unack.eval.type).unacked -= 1

            if self._evals.get(eval_id, 0) >= self.delivery_limit:
                self._enqueue_locked(unack.eval, FAILED_QUEUE)
            else:
                self._enqueue_locked(unack.eval, unack.eval.type)

    # -- flush/stats -------------------------------------------------------

    def flush(self) -> None:
        """eval_broker.go:499-532"""
        with self._lock:
            for unack in self._unack.values():
                unack.nack_timer.cancel()
            for timer in self._time_wait.values():
                timer.cancel()
            for wait in self._trace_wait.values():
                wait.finish()
            for root in self._trace_root.values():
                root.annotate("outcome", "flush").finish()
            self._trace_root = {}
            self._trace_wait = {}
            self.stats = BrokerStats()
            self._evals = {}
            self._job_evals = {}
            self._blocked = {}
            self._ready = {}
            self._unack = {}
            self._time_wait = {}
            self._wait_index = {}
            self._inflight_plans = {}
            self._spilled = False
            self.logger.debug("broker %x: FLUSH", id(self))
            self._work_available.notify_all()

    def snapshot_stats(self) -> BrokerStats:
        with self._lock:
            out = BrokerStats(
                total_ready=self.stats.total_ready,
                total_unacked=self.stats.total_unacked,
                total_blocked=self.stats.total_blocked,
                total_waiting=self.stats.total_waiting,
            )
            for sched, sub in self.stats.by_scheduler.items():
                out.by_scheduler[sched] = SchedulerStats(sub.ready, sub.unacked)
            return out
