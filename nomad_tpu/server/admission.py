"""Admission control & backpressure: the cluster's bounded front door.

ROADMAP item 5's second half. PR 8 made submit→placed latency and the
250ms SLO a live, burn-rate-monitored metric; nothing yet BOUNDED what
hits the broker — burst-100k only worked because the injector was polite.
Borg's front door admits by quota and sheds rather than queues
unboundedly, and Sparrow's framing is exactly task latency under overload
(PAPERS.md): serving millions of users means rejecting fast and cheap so
admitted work keeps its latency promise, instead of degrading for
everyone. This module is that front door, checked at the job-registration
/ eval-ingress RPC boundaries BEFORE any raft apply — a rejection
provably had zero side effects, which is what makes the typed retry
contract (structs.RejectError) safe to honor blindly.

Three gates, in order (token-free capacity gates first, so a rejection
they issue never burns the client's rate token — a consumed token always
corresponds to an actual admission):

1. **Acceptance-queue bound.** When the broker's pending total (ready +
   blocked + waiting) is at ``eval_pending_cap``, reject ``QUEUE_FULL``
   — the front-door twin of the broker's own enforced cap
   (eval_broker.py), which remains as defense in depth for internally
   generated evals.
2. **SLO-coupled load shedding.** When the placed-latency error budget
   burns hot (slo.SLOMonitor burn rate for ``submit_to_placed``), shed
   the batch lane first with probability ramping from 0 at
   ``shed_start_burn`` to 1 at ``shed_full_burn`` — service lanes keep
   flowing (Borg's priority posture: batch yields). Shed draws come from
   a name-salted seeded stream (nomad_tpu/prng.py), so given the same
   decision sequence the shed pattern replays — and nomadlint DET001
   stays clean.
3. **Per-client token-bucket rate lanes.** Each (client, lane) pair owns
   a bucket of ``client_burst`` tokens refilling at ``client_rate``/s
   (lane = "batch" for batch jobs, "service" otherwise). An empty bucket
   rejects ``RATE_LIMITED`` with a deterministic retry-after hint
   ((deficit)/rate — exactly when the next token lands). The client
   table is bounded (``max_clients``, oldest-client eviction).

Every decision is counted (``admission.*`` telemetry), every rejection is
an event-stream-visible action (``Admission`` topic, one
``AdmissionRejected`` type whose payload carries the reason — a single
type keeps the canonical event digest stable across reason mixes) and a
row in a bounded decision ring served at ``/v1/agent/admission`` and in
the debug bundle's ``admission`` section.

Default-permissive: with no caps and no rate configured the controller
admits on a no-lock fast path, draws nothing, and publishes nothing —
decision-invariance the banked steady-10k / burst-100k digests pin.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from nomad_tpu import prng, structs, telemetry
from nomad_tpu.structs import (
    REJECT_QUEUE_FULL,
    REJECT_RATE_LIMITED,
    REJECT_SHED,
    RejectError,
)

LANE_SERVICE = "service"
LANE_BATCH = "batch"
# Express submissions (Job.express, nomad_tpu/server/express.py) ride
# their OWN rate lane — a client's express traffic and its bulk batch
# traffic meter independently — but the SLO-coupled shedder treats the
# lane as batch-yielding: express is a latency lane, not a rate-limit
# (or shed) bypass.
LANE_EXPRESS = "express"

# Lanes the SLO-coupled shedder turns away when the placed-latency
# budget burns hot; service keeps flowing (Borg's priority posture).
SHED_LANES = (LANE_BATCH, LANE_EXPRESS)

# Decision-ring depth: enough to see a rejection storm's shape, bounded
# so the controller can never become its own unbounded queue.
DECISION_RING = 256


def lane_for(job_type: str) -> str:
    """Rate/shed lane for a job: batch yields first (Borg posture);
    service and system ride the protected lane."""
    return LANE_BATCH if job_type == structs.JOB_TYPE_BATCH else LANE_SERVICE


def lane_for_job(job) -> str:
    """Lane classification off the job model: express-flagged batch work
    gets the express lane; everything else classifies by type."""
    if getattr(job, "express", False) \
            and job.type == structs.JOB_TYPE_BATCH:
        return LANE_EXPRESS
    return lane_for(job.type)


@dataclass
class AdmissionConfig:
    """Front-door tunables. The defaults are PERMISSIVE (admit
    everything, no draws, no events): admission only bites where the
    operator configured it — the decision-invariance contract the banked
    pre-admission SIMLOAD digests pin."""

    enabled: bool = True
    # Per-(client, lane) token bucket: rate in admissions/s, burst =
    # bucket size. 0 rate = unlimited (the permissive default).
    client_rate: float = 0.0
    client_burst: float = 0.0
    # Bound on distinct (client, lane) buckets tracked; oldest-touched
    # eviction past it (a client flood must not grow the table forever).
    max_clients: int = 4096
    # SLO-coupled shedding of the batch lane: shed probability ramps 0→1
    # as the submit_to_placed burn rate crosses start→full. 0 start
    # disables shedding entirely (the default).
    shed_start_burn: float = 0.0
    shed_full_burn: float = 4.0
    # Retry-after hints for reasons with no natural schedule.
    queue_full_retry_after: float = 1.0
    shed_retry_after: float = 2.0

    @classmethod
    def parse(cls, spec: Optional[Dict[str, Any]]) -> "AdmissionConfig":
        """Validated construction from a config mapping (the agent-config
        ``server { admission { ... } }`` block / ServerConfig.admission).
        Typos and out-of-range values fail at parse time, like
        scheduler_workers."""
        if spec is None:
            return cls()
        if not isinstance(spec, dict):
            raise ValueError("admission config must be a mapping")
        known = {f for f in cls.__dataclass_fields__}
        unknown = [k for k in spec if k not in known]
        if unknown:
            raise ValueError(
                f"unknown admission config key(s): {sorted(unknown)} "
                f"(have: {sorted(known)})"
            )
        out = cls(**{
            k: (bool(v) if k == "enabled"
                else int(v) if k == "max_clients"
                else float(v))
            for k, v in spec.items()
        })
        if out.client_rate < 0:
            raise ValueError("admission.client_rate must be >= 0")
        if out.client_burst < 0:
            raise ValueError("admission.client_burst must be >= 0")
        if not 1 <= out.max_clients <= 1_000_000:
            raise ValueError(
                "admission.max_clients must be in [1, 1000000], got "
                f"{out.max_clients}"
            )
        if out.shed_start_burn < 0:
            raise ValueError("admission.shed_start_burn must be >= 0")
        if (out.shed_start_burn
                and out.shed_full_burn <= out.shed_start_burn):
            raise ValueError(
                "admission.shed_full_burn must exceed shed_start_burn"
            )
        return out

    @property
    def burst(self) -> float:
        """Effective bucket size: an unset burst with a set rate defaults
        to one second's worth of tokens (floor 1 — a bucket that can
        never hold a whole token admits nothing)."""
        if self.client_burst > 0:
            return self.client_burst
        return max(1.0, self.client_rate)


class _TokenBucket:
    """One (client, lane) rate lane. Mutated under the controller lock;
    monotonic-clock refill (wall clock would let an NTP step mint or
    burn tokens)."""

    __slots__ = ("tokens", "last")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.last = now

    def take(self, rate: float, burst: float, now: float) -> float:
        """Try to consume one token. Returns 0.0 on success, else the
        retry-after hint (seconds until a whole token accrues)."""
        elapsed = max(0.0, now - self.last)
        self.last = now
        self.tokens = min(burst, self.tokens + elapsed * rate)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / rate


class AdmissionController:
    """The bounded front door. One per server; consulted by
    ``Server.job_register`` / ``Server.job_evaluate`` before any raft
    apply. ``admit`` either returns (admitted) or raises a typed
    ``RejectError`` — cheap by construction: the reject path touches one
    bucket, two counters, and a deque.

    Collaborators are injected as callables so the controller stays
    import-light and trivially testable:

    - ``queue_depth``: current broker pending total (the acceptance
      queue the ``eval_pending_cap`` bounds).
    - ``burn_rate``: the live submit_to_placed error-budget burn rate
      (slo.SLOMonitor.burn_rate; 0.0 when no monitor runs).
    - ``events``: an EventBroker for the ``Admission`` topic (None in
      bare tests).
    """

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 seed: int = 0,
                 queue_depth: Optional[Callable[[], int]] = None,
                 queue_cap: int = 0,
                 burn_rate: Optional[Callable[[], float]] = None,
                 events=None):
        self.config = config or AdmissionConfig()
        self.queue_depth = queue_depth or (lambda: 0)
        self.queue_cap = int(queue_cap)
        self.burn_rate = burn_rate or (lambda: 0.0)
        self.events = events
        self._lock = threading.Lock()
        # (client, lane) -> bucket; insertion-ordered for oldest-first
        # eviction (move-to-end on touch keeps actives resident).
        self._buckets: "Dict[tuple, _TokenBucket]" = {}
        # Seeded shed stream: the n-th shed draw is fixed per seed, so a
        # replayed decision sequence sheds identically (prng.py posture).
        self._shed_rng = prng.stream(seed, "admission.shed")
        self._decisions: "deque" = deque(maxlen=DECISION_RING)
        # Monotonic totals. Mutated ONLY under self._lock: RPC dispatch
        # admits on concurrent threads, and an unlocked read-modify-write
        # on a dict entry drops increments under GIL preemption — the
        # artifact's controller-vs-injector cross-check would then
        # mismatch intermittently. Reads (snapshot/summary) stay
        # lock-free: a torn read is a stale count, never a lost one.
        self.admitted = 0
        self.rejected = 0
        self.evicted_clients = 0
        self.by_reason: Dict[str, int] = {}
        self.by_lane: Dict[str, Dict[str, int]] = {}

    # -- the decision -------------------------------------------------------

    def admit_job(self, job, client_id: str = "") -> None:
        """Front-door check for one job registration / evaluation
        request. Raises RejectError (typed, retry-after-hinted) or
        returns with the request admitted."""
        self.admit(client_id, lane_for_job(job), ref=job.id)

    def admit(self, client_id: str, lane: str, ref: str = "") -> None:
        cfg = self.config
        if not cfg.enabled or (
            cfg.client_rate <= 0
            and self.queue_cap <= 0
            and cfg.shed_start_burn <= 0
        ):
            # Permissive fast path: count and go. No lane table, no
            # draws, no events — decision-invariant with the
            # pre-admission stack. (The counter still takes the lock:
            # loss-free totals are the whole point of having them.)
            with self._lock:
                self.admitted += 1
            telemetry.incr_counter(("admission", "admit"))
            return
        # Gate 1: the acceptance queue's bound. Checked BEFORE the rate
        # lane so a capacity rejection never burns the client's token —
        # a client that honors a QUEUE_FULL retry-after must not find
        # its lane drained by the very rejections it was handed.
        if self.queue_cap > 0 and self.queue_depth() >= self.queue_cap:
            self._reject(
                REJECT_QUEUE_FULL, client_id, lane,
                cfg.queue_full_retry_after, ref,
                f"eval acceptance queue at cap ({self.queue_cap})",
            )
        # Gate 2: SLO-coupled shedding — batch AND express yield first
        # (a shed batch door must shed express too: express is a latency
        # lane, not a rate-limit bypass); the service lane keeps flowing
        # regardless of burn. Also token-free.
        if cfg.shed_start_burn > 0 and lane in SHED_LANES:
            burn = self.burn_rate()
            if burn > cfg.shed_start_burn:
                frac = min(1.0, (burn - cfg.shed_start_burn)
                           / (cfg.shed_full_burn - cfg.shed_start_burn))
                with self._lock:
                    draw = self._shed_rng.random()
                if draw < frac:
                    self._reject(
                        REJECT_SHED, client_id, lane,
                        cfg.shed_retry_after, ref,
                        f"batch lane shed (placed-latency burn "
                        f"{burn:.2f} > {cfg.shed_start_burn:.2f})",
                    )
        # Gate 3: the client's rate lane — the LAST gate, so a consumed
        # token always corresponds to an actual admission.
        if cfg.client_rate > 0:
            now = time.monotonic()
            key = (client_id, lane)
            with self._lock:
                bucket = self._buckets.get(key)
                if bucket is None:
                    bucket = _TokenBucket(cfg.burst, now)
                    self._buckets[key] = bucket
                    while len(self._buckets) > cfg.max_clients:
                        self._buckets.pop(next(iter(self._buckets)))
                        self.evicted_clients += 1
                else:
                    # Touch-order eviction: re-insert on use.
                    self._buckets.pop(key)
                    self._buckets[key] = bucket
                hint = bucket.take(cfg.client_rate, cfg.burst, now)
            if hint > 0.0:
                self._reject(
                    REJECT_RATE_LIMITED, client_id, lane, hint, ref,
                    f"client {client_id or '<anonymous>'} {lane} lane "
                    f"rate limited",
                )
        with self._lock:
            self.admitted += 1
            lanes = self.by_lane.setdefault(lane, {"admit": 0, "reject": 0})
            lanes["admit"] += 1
        telemetry.incr_counter(("admission", "admit"))

    def _reject(self, reason: str, client_id: str, lane: str,
                retry_after: float, ref: str, message: str) -> None:
        with self._lock:
            self.rejected += 1
            self.by_reason[reason] = self.by_reason.get(reason, 0) + 1
            lanes = self.by_lane.setdefault(lane, {"admit": 0, "reject": 0})
            lanes["reject"] += 1
            self._decisions.append({
                # nomadlint: allow(DET002) -- operator-facing decision-
                # log stamp on /v1/agent/admission; never interval math.
                "time": time.time(),
                "reason": reason,
                "client_id": client_id,
                "lane": lane,
                "retry_after": round(retry_after, 3),
                "ref": ref,
            })
        telemetry.incr_counter(("admission", "reject", reason))
        if self.events is not None:
            # ONE event type for every reason: the reason rides the
            # payload, so the canonical digest (key + type sequences)
            # stays stable when only the reject-reason mix shifts.
            self.events.publish(
                "Admission", "AdmissionRejected",
                key=client_id or "anonymous",
                payload={"reason": reason, "lane": lane, "ref": ref,
                         "retry_after": round(retry_after, 3)},
            )
        raise RejectError(reason, message, retry_after=retry_after)

    # -- exposition ---------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """Compact totals for /v1/agent/metrics and agent-info."""
        return {
            "enabled": self.config.enabled,
            "admitted": self.admitted,
            "rejected": self.rejected,
            "by_reason": dict(self.by_reason),
            "clients": len(self._buckets),
            "evicted_clients": self.evicted_clients,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The /v1/agent/admission body (and the debug bundle's
        ``admission`` section): config, totals, per-lane split, the
        rate-lane table summary, current SLO coupling, and the recent
        rejection ring."""
        with self._lock:
            lanes = {
                str(key): {"tokens": round(b.tokens, 3)}
                for key, b in self._buckets.items()
            }
            decisions = list(self._decisions)
        try:
            burn = self.burn_rate()
        except Exception:
            burn = None
        return {
            **self.summary(),
            "config": {
                "client_rate": self.config.client_rate,
                "client_burst": self.config.burst,
                "max_clients": self.config.max_clients,
                "queue_cap": self.queue_cap,
                "shed_start_burn": self.config.shed_start_burn,
                "shed_full_burn": self.config.shed_full_burn,
            },
            "by_lane": {k: dict(v) for k, v in self.by_lane.items()},
            "rate_lanes": lanes,
            "placed_burn_rate": burn,
            "recent_rejections": decisions,
        }
