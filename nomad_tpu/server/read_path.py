"""Follower read plane: consistency-tiered read serving.

ROADMAP item 2's cash-in: every ``/v1`` read used to be answered by
whichever server the client happened to dial — correct only because
clients dialed the leader. This module makes the consistency contract
explicit and promotes followers to first-class read servers. Three
lanes (the reference repo's HTTP layer carries exactly this allow-stale
posture; Consul/Nomad semantics):

- **default** — serve from the local FSM, no freshness promise beyond
  the stamped books. Any server answers; the response carries its
  last-applied raft index (``X-Nomad-LastIndex``) and measured leader-
  contact age (``X-Nomad-LastContact``, ms) so the client can judge.
- **stale** — the client OPTS IN to bounded staleness (``?stale=`` /
  ``X-Nomad-Consistency: stale``, SDK ``allow_stale=`` with a
  ``max_stale_ms`` bound). Any server answers from its own FSM iff its
  last leader contact is within the bound; past it the request is
  refused with a typed retriable ``RejectError(STALE_BOUND)`` — the
  next heartbeat (or the next server in the client's rotation) can
  satisfy the bound, and a read provably had no side effects.
- **linearizable** — a read as strong as a write, WITHOUT a raft log
  write: the leader confirms leadership via the heartbeat-riding read
  lease (one quorum wait when the lease is cold — ``RaftNode
  .read_index``, the ReadIndex protocol), and the serving server waits
  until its applied index passes the confirmed read index. A follower
  obtains the index over the ``Raft.ReadIndex`` RPC; DevMode's
  InProcRaft confirms trivially (quorum of one) with honest books.

The class is a SERVING-PATH component (it admits/refuses requests), not
an observatory: it keeps its own plain books under one lock and never
imports the read observatory (the freshness ledger split lives there;
the HTTP layer stamps role+lane into it at record time).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from nomad_tpu import telemetry
from nomad_tpu.structs import REJECT_STALE_BOUND, RejectError

# Consistency lanes (distinct from read_observe's transport lanes
# plain/blocking/sse: a blocking query can ride any consistency lane).
LANE_DEFAULT = "default"
LANE_STALE = "stale"
LANE_LINEARIZABLE = "linearizable"
CONSISTENCY_LANES = (LANE_DEFAULT, LANE_STALE, LANE_LINEARIZABLE)

ROLE_LEADER = "leader"
ROLE_FOLLOWER = "follower"


@dataclass
class ReadPathConfig:
    """The ``server { read_path { ... } }`` block, parse-time validated
    (the CapacityConfig posture: typos and nonsense ranges fail config
    load, not first use)."""

    # Gates the lane machinery: staleness-bound enforcement on the stale
    # lane and read-index confirmation on the linearizable lane. OFF
    # keeps local serving byte-identical to the pre-lane posture (every
    # lane degrades to default) — the read-storm contrast arm's leader-
    # only posture.
    enabled: bool = True
    # Staleness bound applied when a stale-lane client opts in without
    # naming one (ms of leader-contact age).
    default_max_stale_ms: float = 5000.0
    # How long the leader may spend confirming leadership for one
    # linearizable read (lease hit: ~0; cold lease: one quorum wait).
    read_index_timeout: float = 2.0
    # How long a server waits for its applied index to reach a confirmed
    # read index before refusing the linearizable read (typed,
    # retriable) — a follower further behind than this is not a useful
    # linearizable server right now.
    apply_wait_timeout: float = 2.0

    @classmethod
    def parse(cls, spec: Optional[Dict[str, Any]]) -> "ReadPathConfig":
        if spec is None:
            return cls()
        if not isinstance(spec, dict):
            raise ValueError("read_path config must be a mapping")
        known = set(cls.__dataclass_fields__)
        unknown = [k for k in spec if k not in known]
        if unknown:
            raise ValueError(
                f"unknown read_path config key(s): {sorted(unknown)} "
                f"(have: {sorted(known)})"
            )
        out = cls(**{
            k: (bool(v) if k == "enabled" else float(v))
            for k, v in spec.items()
        })
        if out.default_max_stale_ms <= 0:
            raise ValueError("read_path.default_max_stale_ms must be > 0")
        if out.read_index_timeout <= 0:
            raise ValueError("read_path.read_index_timeout must be > 0")
        if out.apply_wait_timeout <= 0:
            raise ValueError("read_path.apply_wait_timeout must be > 0")
        return out


def _q(sample) -> Dict[str, float]:
    return {
        "mean": round(sample.mean, 4),
        "max": round(sample.max, 4),
        **{k: round(v, 4) for k, v in sample.quantiles().items()},
    }


class ReadPath:
    """One server's consistency-lane front: resolves each read's lane
    BEFORE the handler runs, enforces the stale bound, obtains/awaits
    the linearizable read index, and keeps per-(role, lane) serve books.
    ``server`` is the owning Server/ClusterServer — ``server.raft`` is
    re-read per request (ClusterServer swaps InProcRaft for a RaftNode
    after construction) and ``server.confirmed_read_index`` is the seam
    followers forward through."""

    def __init__(self, server, config: Optional[ReadPathConfig] = None):
        self.server = server
        self.config = config or ReadPathConfig()
        self._lock = threading.Lock()
        self.served: Dict[str, Dict[str, int]] = {
            ROLE_LEADER: {lane: 0 for lane in CONSISTENCY_LANES},
            ROLE_FOLLOWER: {lane: 0 for lane in CONSISTENCY_LANES},
        }
        self.stale_refused = 0
        self.linear_refused = 0
        self._stale_age_ms = telemetry.AggregateSample()
        self._linear_wait_ms = telemetry.AggregateSample()

    # -- per-request lane state ---------------------------------------------

    def role(self) -> str:
        return (ROLE_LEADER if self.server.raft.is_leader
                else ROLE_FOLLOWER)

    def last_contact_ms(self) -> Optional[float]:
        """Measured leader-contact age of THIS server in ms (0.0 on the
        leader; None when a follower has never heard from a leader)."""
        age_s = self.server.raft.last_contact_s()
        return None if age_s is None else age_s * 1000.0

    def _retry_hint_s(self) -> float:
        """Retry-after for a refused read: one heartbeat interval — the
        cadence at which a follower's contact age resets."""
        cfg = getattr(self.server.raft, "config", None)
        return float(getattr(cfg, "heartbeat_interval", 0.05) or 0.05)

    def enter(self, lane: str,
              max_stale_ms: Optional[float] = None) -> Dict[str, Any]:
        """Resolve one read's consistency lane before its handler runs.
        Returns the header material: ``applied_index``,
        ``last_contact_ms`` (None = never contacted), ``role``, ``lane``
        as served, and ``read_index`` on the linearizable lane. Raises
        ``RejectError(STALE_BOUND)`` — typed, retriable, zero side
        effects — when this server cannot satisfy the asked lane."""
        if not self.config.enabled:
            lane = LANE_DEFAULT
        role = self.role()
        age_ms = self.last_contact_ms()
        out: Dict[str, Any] = {
            "role": role,
            "lane": lane,
            "applied_index": int(self.server.raft.applied_index),
            "last_contact_ms": age_ms,
        }
        if lane == LANE_STALE:
            bound = (self.config.default_max_stale_ms
                     if max_stale_ms is None else float(max_stale_ms))
            measured = float("inf") if age_ms is None else age_ms
            if measured > bound:
                with self._lock:
                    self.stale_refused += 1
                raise RejectError(
                    REJECT_STALE_BOUND,
                    f"staleness {measured:.1f}ms exceeds bound "
                    f"{bound:.1f}ms",
                    retry_after=self._retry_hint_s(),
                )
            with self._lock:
                self._stale_age_ms.ingest(measured)
        elif lane == LANE_LINEARIZABLE:
            out["read_index"] = self._await_read_index()
            out["applied_index"] = int(self.server.raft.applied_index)
        with self._lock:
            self.served[role][lane] += 1
        return out

    def _await_read_index(self) -> int:
        """Confirmed read index, then wait until the LOCAL applied index
        passes it — the serving half of the ReadIndex protocol. The
        leader's wait is a no-op (commit implies local apply here);
        a follower's wait rides the ordinary replication stream."""
        from nomad_tpu.raft.node import NotLeaderError

        t0 = time.monotonic()
        try:
            idx = int(self.server.confirmed_read_index(
                timeout=self.config.read_index_timeout))
        except (NotLeaderError, TimeoutError) as e:
            with self._lock:
                self.linear_refused += 1
            raise RejectError(
                REJECT_STALE_BOUND,
                f"no confirmed read index: {e}",
                retry_after=self._retry_hint_s(),
            ) from e
        deadline = time.monotonic() + self.config.apply_wait_timeout
        while int(self.server.raft.applied_index) < idx:
            if time.monotonic() >= deadline:
                with self._lock:
                    self.linear_refused += 1
                raise RejectError(
                    REJECT_STALE_BOUND,
                    f"applied index {self.server.raft.applied_index} "
                    f"behind read index {idx}",
                    retry_after=self._retry_hint_s(),
                )
            time.sleep(0.001)
        with self._lock:
            self._linear_wait_ms.ingest(
                (time.monotonic() - t0) * 1000.0)
        return idx

    # -- exposition -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        raft = self.server.raft
        with self._lock:
            served = {role: dict(lanes)
                      for role, lanes in self.served.items()}
            total = sum(sum(lanes.values()) for lanes in served.values())
            follower = sum(served[ROLE_FOLLOWER].values())
            return {
                "enabled": self.config.enabled,
                "served": served,
                "requests": total,
                "follower_serve_share": (
                    round(follower / total, 4) if total else 0.0
                ),
                "stale": {
                    "refused": self.stale_refused,
                    "age_ms": _q(self._stale_age_ms),
                    "default_max_stale_ms":
                        self.config.default_max_stale_ms,
                },
                "linearizable": {
                    "refused": self.linear_refused,
                    "wait_ms": _q(self._linear_wait_ms),
                    "read_index": {
                        "calls": getattr(raft, "read_index_calls", 0),
                        "lease_hits": getattr(raft, "read_lease_hits", 0),
                        "quorum_confirms": getattr(
                            raft, "read_quorum_confirms", 0),
                        "refused": getattr(raft, "read_index_refused", 0),
                    },
                },
            }

    def summary(self) -> Dict[str, Any]:
        snap = self.snapshot()
        return {
            "enabled": snap["enabled"],
            "requests": snap["requests"],
            "follower_serve_share": snap["follower_serve_share"],
            "stale_refused": snap["stale"]["refused"],
            "stale_age_p95_ms": snap["stale"]["age_ms"].get("p95", 0.0),
            "linear_refused": snap["linearizable"]["refused"],
        }
