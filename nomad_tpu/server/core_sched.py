"""Core scheduler: internal garbage collection of evals, allocs, and nodes.

Reference: /root/reference/nomad/core_sched.go. Registered for ``_core``
evals (worker.go:246-248); the eval's JobID encodes which GC to run.
"""

from __future__ import annotations

import time

from nomad_tpu.structs import (
    CORE_JOB_EVAL_GC,
    CORE_JOB_NODE_GC,
    Evaluation,
)


class CoreScheduler:
    """core_sched.go:15-47"""

    def __init__(self, server, snapshot):
        self.server = server
        self.snap = snapshot

    def process(self, ev: Evaluation) -> None:
        if ev.job_id == CORE_JOB_EVAL_GC:
            self._eval_gc(ev)
        elif ev.job_id == CORE_JOB_NODE_GC:
            self._node_gc(ev)
        else:
            raise ValueError(f"core scheduler cannot handle job '{ev.job_id}'")

    def _eval_gc(self, ev: Evaluation) -> None:
        """Reap terminal evals (and their allocs) older than the GC
        threshold, when every alloc is terminal (core_sched.go:42-101)."""
        threshold = self.server.config.eval_gc_threshold
        # nomadlint: allow(DET002) -- compared against TimeTable's
        # persisted WALL stamps (survive restarts); monotonic clocks
        # don't span processes.
        oldest = time.time() - threshold
        old_index = self.server.time_table.nearest_index(oldest)

        gc_evals = []
        gc_allocs = []
        for e in self.snap.evals():
            if not e.terminal_status() or e.modify_index > old_index:
                continue
            allocs = self.snap.allocs_by_eval(e.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            gc_evals.append(e.id)
            gc_allocs.extend(a.id for a in allocs)

        if gc_evals or gc_allocs:
            self.server.logger.debug(
                "core.sched: eval GC: %d evaluations, %d allocs eligible",
                len(gc_evals), len(gc_allocs),
            )
            self.server.raft.apply(
                "eval_delete", {"evals": gc_evals, "allocs": gc_allocs}
            ).result()

    def _node_gc(self, ev: Evaluation) -> None:
        """Reap down nodes with no non-terminal allocs
        (core_sched.go:103-188)."""
        threshold = self.server.config.node_gc_threshold
        # nomadlint: allow(DET002) -- same wall-stamp comparison as
        # _eval_gc above.
        oldest = time.time() - threshold
        old_index = self.server.time_table.nearest_index(oldest)

        for node in self.snap.nodes():
            if not node.terminal_status() or node.modify_index > old_index:
                continue
            allocs = self.snap.allocs_by_node(node.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            self.server.logger.debug("core.sched: node GC: %s eligible", node.id)
            self.server.raft.apply(
                "node_deregister", {"node_id": node.id}
            ).result()
