"""Replicated state machine: applies log entries to the state store.

Reference: /root/reference/nomad/fsm.go. Message types mirror
fsm.go:116-144; applying an eval update enqueues pending evals into the
broker (fsm.go:243-250). Snapshot/restore serializes the full state through
StateRestore (fsm.go:299-593).

``InProcRaft`` is the DevMode replication layer: synchronous apply with a
monotonic index (the reference's testing posture, raft.NewInmemStore at
server.go:420-427). The multi-server replicated log slots in behind the same
``apply``/``applied_index`` interface.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, TYPE_CHECKING

from nomad_tpu import faults, telemetry, trace

if TYPE_CHECKING:  # injected collaborator; import would be circular
    from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.events import EventBroker
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Allocation, Evaluation, Job, Node


class FSM:
    """Applies replicated messages to a fresh StateStore
    (reference: nomad/fsm.go:38-114)."""

    def __init__(
        self,
        eval_broker: Optional["EvalBroker"] = None,
        logger: Optional[logging.Logger] = None,
        events: Optional[EventBroker] = None,
    ):
        self.state = StateStore()
        self.eval_broker = eval_broker
        # Per-FSM event broker (nomad_tpu.events): every apply publishes
        # the state transition it just made, stamped with its raft index.
        # Per-replica ownership is what makes the log exactly-once: each
        # server applies each committed entry exactly once, so each
        # server's event stream records exactly one PlanApplied per plan.
        self.events = events if events is not None else EventBroker()
        # Gate for broker enqueue on apply: in a cluster this is raft
        # leadership, checked synchronously at apply time. The broker's own
        # enabled flag lags leadership changes (they notify asynchronously),
        # so a deposed leader could otherwise enqueue replicated evals into
        # its stale broker and double-deliver.
        self.enqueue_guard = lambda: True
        self.logger = logger or logging.getLogger("nomad_tpu.fsm")
        # Last snapshot-restore forensics (plain data, read by
        # nomad_tpu/raft_observe.py for the recovery timeline): wall
        # cost and per-table row counts of the most recent
        # restore_bytes, None until one happens.
        self.last_restore: Optional[Dict[str, Any]] = None
        self._handlers: Dict[str, Callable[[int, dict], Any]] = {
            "node_register": self._apply_node_register,
            "node_batch_register": self._apply_node_batch_register,
            "node_deregister": self._apply_node_deregister,
            "node_status_update": self._apply_node_status_update,
            "node_drain_update": self._apply_node_drain_update,
            "job_register": self._apply_job_register,
            "job_deregister": self._apply_job_deregister,
            "eval_update": self._apply_eval_update,
            "eval_delete": self._apply_eval_delete,
            "alloc_update": self._apply_alloc_update,
            "alloc_client_update": self._apply_alloc_client_update,
        }

    def apply(self, index: int, msg_type: str, payload: dict) -> Any:
        handler = self._handlers.get(msg_type)
        if handler is None:
            raise ValueError(f"failed to apply request: unknown type {msg_type!r}")
        # Injected apply stall (mode 'delay' only — fire() sleeps it; an
        # injected ERROR would make a deterministic FSM diverge per
        # replica, which is not a failure mode production exhibits).
        faults.fire("fsm.apply", target=msg_type)
        # Per-message-type apply timing (reference: nomad/fsm.go:148
        # `defer metrics.MeasureSince([]string{"nomad","fsm",...})`), plus
        # a child span when the applying thread carries one (the plan
        # applier's synchronous-raft posture).
        start = time.perf_counter()
        parent = trace.current_span()
        span = (
            trace.get_tracer().start_span(
                parent.trace_id, "fsm.apply", parent=parent,
                annotations={"msg_type": msg_type, "index": index},
            )
            if parent is not None else trace.NULL_SPAN
        )
        try:
            return handler(index, payload)
        finally:
            span.finish()
            telemetry.measure_since(("fsm", "apply", msg_type), start)

    # -- handlers (fsm.go:146-297) ----------------------------------------

    def _apply_node_register(self, index: int, payload: dict) -> None:
        node = payload["node"]
        self.state.upsert_node(index, node)
        self.events.publish("Node", "NodeRegistered", key=node.id,
                            raft_index=index,
                            payload={"status": node.status})

    def _apply_node_batch_register(self, index: int, payload: dict) -> None:
        """Bulk registration (one log entry for a whole fleet tranche —
        the Node.BatchRegister path). ONE event per batch, not per node:
        a 10k-node fleet bring-up must not evict the whole event ring
        (the same granularity cut the columnar alloc commits make)."""
        nodes = payload["nodes"]
        self.state.upsert_nodes(index, nodes)
        self.events.publish(
            "Node", "NodeBatchRegistered",
            key=nodes[0].id if nodes else "", raft_index=index,
            payload={"count": len(nodes)},
        )

    def _apply_node_deregister(self, index: int, payload: dict) -> None:
        self.state.delete_node(index, payload["node_id"])
        self.events.publish("Node", "NodeDeregistered",
                            key=payload["node_id"], raft_index=index)

    def _apply_node_status_update(self, index: int, payload: dict) -> None:
        self.state.update_node_status(index, payload["node_id"], payload["status"])
        self.events.publish("Node", "NodeStatusUpdated",
                            key=payload["node_id"], raft_index=index,
                            payload={"status": payload["status"]})

    def _apply_node_drain_update(self, index: int, payload: dict) -> None:
        self.state.update_node_drain(index, payload["node_id"], payload["drain"])
        self.events.publish("Node", "NodeDrainUpdated",
                            key=payload["node_id"], raft_index=index,
                            payload={"drain": bool(payload["drain"])})

    def _apply_job_register(self, index: int, payload: dict) -> None:
        job = payload["job"]
        self.state.upsert_job(index, job)
        self.events.publish("Job", "JobRegistered", key=job.id,
                            raft_index=index, payload={"type": job.type})

    def _apply_job_deregister(self, index: int, payload: dict) -> None:
        self.state.delete_job(index, payload["job_id"])
        self.events.publish("Job", "JobDeregistered",
                            key=payload["job_id"], raft_index=index)

    def _apply_eval_update(self, index: int, payload: dict) -> None:
        evals = payload["evals"]
        self.state.upsert_evals(index, evals)
        for ev in evals:
            self.events.publish("Eval", "EvalUpdated", key=ev.id,
                                raft_index=index,
                                payload={"status": ev.status,
                                         "job_id": ev.job_id,
                                         "triggered_by": ev.triggered_by})
        # On the leader, hand pending evals to the broker (fsm.go:243-250).
        # wait_index = the eval's own apply index: the worker's snapshot
        # must contain at least the write that created the eval.
        if self.eval_broker is not None and self.enqueue_guard():
            # One lock hold for the whole entry: a coalescing batch
            # dequeuer parked on the broker wakes to the full burst, not
            # to whichever prefix the per-eval notify race exposed.
            pending = [ev for ev in evals if ev.should_enqueue()]
            if pending:
                # A committed entry cannot fail: past the broker's
                # pending cap enqueue_many SPILLS (typed, counted) and
                # the server's readmission loop re-enqueues from state
                # as capacity frees — bounded queue, no lost evals.
                spilled = self.eval_broker.enqueue_many(
                    pending, wait_index=index)
                if spilled:
                    telemetry.incr_counter(
                        ("broker", "enqueue_spilled"), spilled)

    def _apply_eval_delete(self, index: int, payload: dict) -> None:
        self.state.delete_eval(index, payload["evals"], payload["allocs"])
        for ev_id in payload["evals"]:
            self.events.publish("Eval", "EvalDeleted", key=ev_id,
                                raft_index=index)

    def _apply_alloc_update(self, index: int, payload: dict) -> None:
        allocs = payload.get("allocs") or []
        if allocs:
            self.state.upsert_allocs(index, allocs)
            # Per-alloc events only for object rows: bounded by plan size.
            for a in allocs:
                self.events.publish(
                    "Alloc", "AllocUpserted", key=a.id, raft_index=index,
                    payload={"node_id": a.node_id, "job_id": a.job_id,
                             "desired_status": a.desired_status},
                )
        # Columnar placements commit as stored blocks — O(node runs), no
        # per-Allocation expansion (state/blocks.py).
        batches = payload.get("alloc_batches") or []
        if batches:
            self.state.upsert_alloc_blocks(index, batches)
            # One event per BLOCK, keyed by eval — per-member fan-out
            # would cost O(placements) per commit (the state watch makes
            # the same granularity cut for bulk columnar transitions).
            for b in batches:
                self.events.publish(
                    "Alloc", "AllocUpserted", key=b.eval_id,
                    raft_index=index,
                    payload={"columnar": True,
                             "count": int(sum(b.node_counts))},
                )
        # Columnar in-place updates: whole-block field swaps where a batch
        # covers a stored block, row re-stamps elsewhere.
        ubatches = payload.get("update_batches") or []
        if ubatches:
            self.state.apply_update_batches(index, ubatches)
        # The plan applier marks plan commits (plan_apply.py _apply): one
        # PlanApplied per committed plan entry, after its alloc events.
        plan_meta = payload.get("plan")
        if plan_meta:
            self.events.publish(
                "Plan", "PlanApplied", key=plan_meta.get("eval_id", ""),
                raft_index=index,
                payload={k: v for k, v in plan_meta.items()
                         if k != "eval_id"},
            )

    def _apply_alloc_client_update(self, index: int, payload: dict) -> None:
        self.state.update_allocs_from_client(index, payload["allocs"])
        for a in payload["allocs"]:
            # eval_id/job_id ride the payload so lifecycle consumers
            # (nomad_tpu.lifecycle, nomad_tpu.slo) can close the
            # submit→running loop from the event stream alone — the
            # event key stays the alloc id and the digest (key + type
            # sequences) is unchanged.
            self.events.publish(
                "Alloc", "AllocClientUpdated", key=a.id, raft_index=index,
                payload={"client_status": a.client_status,
                         "eval_id": a.eval_id, "job_id": a.job_id},
            )

    # -- snapshot/restore (fsm.go:299-593) ---------------------------------

    def snapshot_cow(self):
        """Cheap copy-on-write snapshot handle, safe to take under the raft
        lock; serialization happens off-lock via serialize_cow (the
        reference's nomadSnapshot holds a StateSnapshot the same way,
        fsm.go:299-311)."""
        return self.state.snapshot()

    def snapshot_bytes(self) -> bytes:
        """Serialize the full FSM state. The reference streams msgpack with
        type tags (fsm.go:414-593); we serialize table dumps (internal
        format, not a wire protocol)."""
        return self.serialize_cow(self.snapshot_cow())

    def serialize_cow(self, snap) -> bytes:
        payload = {
            "nodes": snap.nodes(),
            "jobs": snap.jobs(),
            "evals": snap.evals(),
            # Object rows and columnar blocks persist in their native forms:
            # a 100k-placement block snapshots as its runs, not 100k rows.
            "allocs": snap.allocs_objects(),
            "blocks": snap.alloc_blocks(),
            "indexes": {
                t: snap.get_index(t) for t in ("nodes", "jobs", "evals", "allocs")
            },
        }
        return pickle.dumps(payload)

    def restore_bytes(self, data: bytes) -> None:
        """Rebuild a fresh state store from a snapshot (fsm.go:313-410)."""
        t0 = time.perf_counter()
        payload = pickle.loads(data)
        old_store = self.state
        self.state = StateStore()
        # The watcher-registration cap is configuration, not state: a
        # snapshot install must not silently unbound the fresh registry.
        self.state.watch.max_watchers = old_store.watch.max_watchers
        restore = self.state.restore()
        for node in payload["nodes"]:
            restore.node_restore(node)
        for job in payload["jobs"]:
            restore.job_restore(job)
        for ev in payload["evals"]:
            restore.eval_restore(ev)
        for alloc in payload["allocs"]:
            restore.alloc_restore(alloc)
        for block in payload.get("blocks", []):
            restore.block_restore(block)
        for table, index in payload["indexes"].items():
            restore.index_restore(table, index)
        restore.commit()
        blocks = payload.get("blocks", [])
        self.last_restore = {
            "wall_ms": round((time.perf_counter() - t0) * 1000.0, 3),
            "bytes": len(data),
            "nodes": len(payload["nodes"]),
            "jobs": len(payload["jobs"]),
            "evals": len(payload["evals"]),
            "allocs": len(payload["allocs"]),
            "blocks": len(blocks),
            # Placements the snapshot re-materialized: object rows plus
            # the columnar blocks' live members — the recovery report's
            # placements-per-second numerator starts here.
            "placements": len(payload["allocs"]) + sum(
                cnt for b in blocks for _nid, cnt in b.live_node_counts()
            ),
        }
        # Blocking queries parked on the replaced store would never be
        # notified again; wake them so they re-check against the live one.
        old_store.watch.notify_all()


class InProcRaft:
    """Single-process replication layer: synchronous apply, monotonic index.

    Interface contract shared with the future multi-server layer:
    - apply(msg_type, payload) -> Future resolving to the log index
    - applied_index property
    """

    def __init__(self, fsm: FSM):
        self.fsm = fsm
        self._lock = threading.Lock()
        self._index = 0
        # Write-path anchor records (the RaftNode book surface, read by
        # nomad_tpu/raft_observe.py): DevMode attribution degrades
        # honestly — no persistence/replication, so those stages are
        # exactly zero wide and fsm_apply dominates. Entry bytes stay 0:
        # InProcRaft payloads are live objects, and serializing them
        # here would cost the hot path a dumps it never needed.
        self._wp_done: "deque" = deque(maxlen=1024)
        self._wp_seq = 0
        # Read-index books (server/read_path.py): a quorum of one is
        # always itself, so every linearizable read confirms trivially —
        # counted honestly rather than pretended away, so the DevMode
        # /v1/agent/reads books name the posture they were measured in.
        self.read_index_calls = 0
        self.read_lease_hits = 0
        self.read_quorum_confirms = 0
        self.read_index_refused = 0

    @property
    def applied_index(self) -> int:
        with self._lock:
            return self._index

    @property
    def is_leader(self) -> bool:
        """A quorum of one: always the leader of itself."""
        return True

    def read_index(self, timeout: float = 2.0) -> int:
        """Trivially-confirmed linearizable read point: synchronous
        replication means the applied index IS the commit index and the
        single member IS the quorum. Books kept honest (lease_hits) so
        lane accounting is comparable across DevMode and cluster runs."""
        del timeout
        with self._lock:
            self.read_index_calls += 1
            self.read_lease_hits += 1
            return self._index

    def last_contact_s(self) -> float:
        """The single member is its own leader: contact age is zero."""
        return 0.0

    def write_path_records(self, since: int):
        """(sequence, finalized records newer than ``since``) — the raft
        observatory's drain, same contract as RaftNode's."""
        with self._lock:
            seq = self._wp_seq
            n = seq - int(since)
            if n <= 0:
                return seq, []
            n = min(n, len(self._wp_done))
            return seq, list(self._wp_done)[-n:]

    def apply(self, msg_type: str, payload: dict) -> Future:
        """Apply under the lock, publishing the index only after the FSM has
        executed the entry — readers of applied_index (worker wait_for_index)
        must never observe an index whose entry is not yet visible, and
        entries must hit the FSM in log order.

        A failed apply still consumes its index: the log entry committed and
        the FSM error is deterministic, matching replicated-raft semantics.
        """
        future: Future = Future()
        t_submit = time.monotonic()
        with self._lock:
            index = self._index + 1
            anchors = {"submit": t_submit}
            # Synchronous quorum-of-one: append/persist/replicate/commit
            # all collapse to the lock acquisition.
            anchors["persisted"] = anchors["committed"] = time.monotonic()
            anchors["fsm_start"] = time.monotonic()
            try:
                self.fsm.apply(index, msg_type, payload)
            except Exception as e:
                self._index = index
                anchors["fsm_end"] = time.monotonic()
                future.set_exception(e)
            else:
                self._index = index
                anchors["fsm_end"] = time.monotonic()
                future.set_result(index)
            anchors["resolved"] = time.monotonic()
            self._wp_done.append({"index": index, "msg_type": msg_type,
                                  "bytes": 0, "anchors": anchors})
            self._wp_seq += 1
        return future
