"""Optimistic parallel plan pipeline: the Omega-posture plan applier.

Replaces the one-at-a-time serial applier (the old ``PlanApplier`` in
plan_apply.py): N scheduler workers evaluate concurrently against
delta-rolled snapshots, and this pipeline drains up to K pending plans per
cycle, verifies all K in **one fused batched tensor pass** over the
columnar ``_NodeTable`` (a K x nodes feasibility check generalizing
``evaluate_plan``), commits the non-conflicting subsets in commit order,
and bounces conflicting plans back to their workers through the existing
RefreshIndex path.

Conflict semantics are transaction-time per Omega (Schwarzkopf et al.,
EuroSys 2013, PAPERS.md): every plan is evaluated optimistically against
the snapshot its worker held; at apply time the pipeline re-verifies
against current state, and a plan whose verification failed CONFLICTS iff
a commit in the same batch — or any commit since the plan's snapshot
index — touched overlapping node capacity. Conflicting plans keep the
sequential-equivalent partial-commit/refresh response (the worker
re-snapshots and re-plans the remainder), so placement decisions are
bit-identical to the serial applier; the pipeline only *attributes* and
*counts* the conflicts (``plan.conflicts``) and amortizes verification +
commit over the batch (``plan.batch_size``).

Decision identity is the load-bearing contract: ``evaluate_plans`` is
fuzz-pinned decision-identical to K sequential ``evaluate_plan`` calls
with the committed subset of each plan rolled into the snapshot between
calls (tests/test_fuzz_differential.py). The fused pass is therefore a
pure verification-cost optimization — it can never change what commits.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu import telemetry, trace
from nomad_tpu.server.eval_broker import BrokerError, EvalBroker
from nomad_tpu.server.plan_apply import (
    _AskAccum,
    _block_has_net,
    _existing_block_usage_rows,
    _node_table,
    _object_allocs,
    evaluate_plan,
)
from nomad_tpu.server.plan_queue import PendingPlan, PlanQueue
from nomad_tpu.structs import Plan, PlanResult

# How many pending plans one pipeline cycle drains at most. Sized at the
# worker-concurrency ceiling: more than ~2x the worker count can never be
# pending at once (each worker blocks on one plan), and a small K keeps
# the fused pass's K x nodes scratch arrays cache-resident.
DEFAULT_MAX_BATCH = 8

# Commit-log depth for transaction-time conflict attribution: (index,
# touched-node-set) of recent commits. Bounded because attribution only
# needs to cover plans currently in flight — a worker's snapshot is at
# most a few commits old; anything older than the horizon is attributed
# conservatively (treated as overlapping).
COMMIT_LOG_DEPTH = 64


class _PipelineTotals:
    """Process-wide lifetime counters shared by every pipeline instance —
    the GLOBAL_MIRROR_CACHE posture, so /v1/agent/metrics and the debug
    bundle can surface pipeline health without holding a server ref."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.batches = 0
        self.plans = 0
        self.committed = 0
        self.noops = 0
        self.rejected = 0
        self.conflicts = 0
        self.refreshes = 0
        self.fused_plans = 0
        self.scalar_plans = 0
        self.max_batch_seen = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "batches": self.batches,
                "plans": self.plans,
                "committed": self.committed,
                "noops": self.noops,
                "rejected": self.rejected,
                "conflicts": self.conflicts,
                "refreshes": self.refreshes,
                "fused_plans": self.fused_plans,
                "scalar_plans": self.scalar_plans,
                "max_batch_seen": self.max_batch_seen,
            }


PIPELINE_TOTALS = _PipelineTotals()


def _plan_touched_nodes(plan: Plan) -> set:
    """Node ids whose capacity this plan touches — the conflict-detection
    granularity (Omega's per-machine transaction footprint)."""
    nodes = set(plan.node_allocation)
    nodes.update(plan.node_update)
    for b in plan.alloc_batches:
        nodes.update(b.node_ids)
    for b in plan.update_batches:
        if b.src_node_ids:
            nodes.update(b.src_node_ids)
        elif getattr(b, "allocs", None):
            nodes.update(a.node_id for a in b.allocs)
    return nodes


def apply_result_to_snapshot(snap, result: PlanResult, index: int) -> None:
    """Roll one plan's committed subset into ``snap`` — the ONE optimistic
    mutation shared by the batched verifier (sequential-equivalence rolls)
    and the pipeline's cross-batch optimistic snapshot, so the two can
    never drift."""
    allocs = _object_allocs(result)
    if allocs:
        snap.upsert_allocs(index, allocs)
    if result.alloc_batches:
        snap.upsert_alloc_blocks(index, result.alloc_batches)
    if result.update_batches:
        snap.apply_update_batches(index, result.update_batches)


def _whole_commit_result(plan: Plan) -> PlanResult:
    """The whole-commit PlanResult shape evaluate_plan returns on its
    pure-columnar fast path — the fused pass must produce the identical
    object shape for decision identity."""
    result = PlanResult(
        node_update={},
        node_allocation={},
        failed_allocs=plan.failed_allocs,
    )
    result.alloc_batches = [b for b in plan.alloc_batches if b.n]
    result.update_batches = [b for b in plan.update_batches if b.n]
    return result


def _fused_eligible(plan: Plan) -> bool:
    """A plan rides the fused K x nodes pass iff its entire ask is pure
    columnar placement batches: no per-node object placements or evictions
    (those need the scalar/object merge paths), no update batches (delta
    semantics), and no network-carrying batches (sequential port
    semantics — and a committed net batch flips later plans' nodes to the
    scalar path, which the cumulative-ask trick can't express)."""
    if plan.node_allocation or plan.node_update or plan.update_batches:
        return False
    return all(not _block_has_net(b) for b in plan.alloc_batches)


def _fused_prefix(snap, plans: List[Plan], table,
                  reservations=None) -> Tuple[int, List[PlanResult]]:
    """Verify a leading run of fused-eligible plans in ONE batched tensor
    pass over the node table: stack the K per-plan asks, prefix-cumsum
    along K (each plan sees every earlier plan's ask as committed usage —
    exactly the sequential roll), and fit-check all K x touched-rows at
    once. Returns (m, results): the longest prefix whose plans ALL fully
    fit, with their whole-commit results. m == 0 means the first plan
    needs the scalar path (ineligible, or doesn't fully fit — the exact
    partial answer comes from evaluate_plan)."""
    import numpy as np

    if table is None or table.n == 0:
        return 0, []
    if snap.nodes_with_object_allocs():
        # Object rows change per-node usage in ways only the per-node
        # walk accounts; the whole batch takes the sequential path.
        return 0, []

    run: List[Plan] = []
    for plan in plans:
        if not _fused_eligible(plan):
            break
        run.append(plan)
    if not run:
        return 0, []

    block_usage, net_rows, _blocks = _existing_block_usage_rows(snap, table)

    asks = []          # per plan: dense [N,4] int64 ask (or None)
    plan_rows = []     # per plan: row indices its ask touches
    eligible = len(run)
    for i, plan in enumerate(run):
        ask = _AskAccum()
        for b in plan.alloc_batches:
            ask.add_batch(
                b.node_ids, b.node_counts,
                np.asarray(b.resource_vector(), dtype=np.int64),
                src=b.src_hint,
            )
        arr, _flat_ids, rows = ask.accumulate_rows(table)
        if rows.size:
            valid = rows >= 0
            if not valid.all():
                # Unknown node id: sequential would partial-commit; this
                # plan and everything after it leave the fused run.
                eligible = i
                break
            sc = table.dead[rows] | table.scalar_only[rows]
            if net_rows is not None:
                sc = sc | net_rows[rows]
            if sc.any():
                eligible = i
                break
        asks.append(
            arr if arr is not None
            else np.zeros((table.n, 4), dtype=np.int64)
        )
        plan_rows.append(rows)
    if eligible == 0:
        return 0, []

    run = run[:eligible]
    # One fused pass: inclusive prefix over the K stacked asks restricted
    # to the union of touched rows, one broadcast compare against totals.
    union = np.unique(np.concatenate([r for r in plan_rows if r.size]
                                     or [np.empty(0, dtype=np.int64)]))
    if union.size == 0:
        # Nothing asks for capacity: every plan trivially whole-commits.
        return len(run), [_whole_commit_result(p) for p in run]
    stacked = np.stack([a[union] for a in asks])          # [K, U, 4]
    cum = np.cumsum(stacked, axis=0)                      # inclusive
    base = table.reserved[union].astype(np.int64)
    if block_usage is not None:
        base = base + block_usage[union]
    if reservations:
        # Active express capacity leases (server/express.py): charged as
        # base usage so no fused-verified plan can take leased capacity.
        # Fused-eligible plans are never express (express plans carry
        # node_allocation, which disqualifies them above), so no
        # own-lease exemption arises here.
        res_rows = np.zeros((table.n, 4), dtype=np.int64)
        rows_get = table.rows.get
        for nid, vec in reservations.items():
            row = rows_get(nid)
            if row is not None:
                res_rows[row] += vec
        base = base + res_rows[union]
    # Same int32 clamp as the scalar verifier's native.fit_check feed —
    # decision identity must survive saturating asks.
    used = np.minimum(base[None, :, :] + cum, 2**31 - 1)
    fits = np.all(used <= table.totals[union].astype(np.int64)[None, :, :],
                  axis=2)                                 # [K, U]
    pos = {int(r): i for i, r in enumerate(union.tolist())}
    m = 0
    for i, rows in enumerate(plan_rows):
        if rows.size:
            idxs = [pos[int(r)] for r in rows.tolist()]
            if not fits[i, idxs].all():
                break
        m = i + 1
    return m, [_whole_commit_result(p) for p in run[:m]]


def evaluate_plans(snap, plans: List[Plan],
                   stamp_index: Callable[[], int] = lambda: 0,
                   totals: Optional[_PipelineTotals] = None,
                   ledger=None,
                   ) -> List[PlanResult]:
    """Batched, sequential-equivalent plan verification: one PlanResult per
    plan, decision-identical to calling ``evaluate_plan(snap, plan)`` and
    rolling each committed subset into ``snap`` (apply_result_to_snapshot)
    before the next call. MUTATES ``snap`` the same way. The pure-columnar
    common case verifies whole runs of plans in one fused tensor pass;
    anything the fused pass can't prove falls to the exact scalar path for
    that plan and re-fuses the remainder.

    ``ledger`` (optional) is the express lane's ReservationLedger
    (server/express.py): active lease debits charge as existing usage in
    both the fused and scalar paths, with each express plan's OWN lease
    exempted from its verification — the reservation-aware verify.
    None (or an empty ledger) is decision-identical to before."""
    full_debits = None
    if ledger is not None:
        full_debits = ledger.debit_map() or None
    results: List[PlanResult] = []
    i = 0
    n = len(plans)
    while i < n:
        m = 0
        if n - i > 1:
            # A lone plan takes evaluate_plan directly — its own
            # pure-columnar fast path is the K=1 case of the fused pass.
            m, fused_results = _fused_prefix(
                snap, plans[i:], _node_table(snap),
                reservations=full_debits,
            )
        if m:
            for plan, result in zip(plans[i:i + m], fused_results):
                apply_result_to_snapshot(snap, result, stamp_index())
                results.append(result)
            if totals is not None:
                with totals._lock:
                    totals.fused_plans += m
            i += m
            continue
        plan = plans[i]
        reservations = full_debits
        if ledger is not None and plan.express_lease:
            # The express plan verifying its own async commit: exempt
            # its own lease (its ask IS that reservation) while still
            # charging every other outstanding lease.
            reservations = ledger.debit_map(
                exclude=(plan.express_lease,)) or None
        result = evaluate_plan(snap, plan, reservations=reservations)
        if not result.is_noop():
            apply_result_to_snapshot(snap, result, stamp_index())
        results.append(result)
        if totals is not None:
            with totals._lock:
                totals.scalar_plans += 1
        i += 1
    return results


class PlanPipeline(threading.Thread):
    """Long-lived batch applier thread (the plan_apply.go:39-117 role,
    batched). ``raft`` is anything with apply(msg_type, payload) ->
    Future[index] and an ``applied_index`` property. Verification of batch
    N+1 overlaps the (raft) apply of batch N via the rolled optimistic
    snapshot; within a batch the K raft entries dispatch back-to-back and
    one waiter thread resolves them in commit order."""

    def __init__(
        self,
        plan_queue: PlanQueue,
        eval_broker: EvalBroker,
        raft,
        fsm,
        logger: Optional[logging.Logger] = None,
        max_batch: int = DEFAULT_MAX_BATCH,
    ):
        super().__init__(daemon=True, name="plan-pipeline")
        self.plan_queue = plan_queue
        self.eval_broker = eval_broker
        self.raft = raft
        # Hold the FSM, not its StateStore: a raft snapshot restore rebinds
        # fsm.state to a fresh store and plans must verify against the
        # live one.
        self.fsm = fsm
        self.logger = logger or logging.getLogger("nomad_tpu.plan_pipeline")
        self.max_batch = max(1, int(max_batch))
        self._stop = threading.Event()
        # (commit index, touched node-id set) of recent commits, newest
        # last — the transaction-time conflict attribution window.
        self._commit_log = collections.deque(maxlen=COMMIT_LOG_DEPTH)
        self._inflight: List = []
        self._opt_snap = None
        self.totals = PIPELINE_TOTALS
        # Express reservation ledger (server/express.py), set by the
        # server when the lane is enabled: active lease debits charge as
        # usage during verification. None = lease-blind (identical to
        # the pre-express pipeline).
        self.ledger = None

    def stop(self) -> None:
        self._stop.set()

    def stats(self) -> Dict[str, int]:
        return self.totals.stats()

    # -- conflict attribution ----------------------------------------------

    def _record_commit(self, index: int, touched: set):
        """Append one commit footprint and return the (mutable) entry so
        the waiter can overwrite the estimated index with the entry's
        real raft index once its future resolves. Mutating entry[0] races
        only benignly with _conflicts_since reads (int store is atomic;
        a read of the pre-fixup estimate is no worse than the estimate
        itself)."""
        if not touched:
            return None
        entry = [index, touched]
        self._commit_log.append(entry)
        return entry

    def _conflicts_since(self, touched: set, snapshot_index: int) -> bool:
        """Transaction-time check: did any commit after ``snapshot_index``
        touch overlapping node capacity? snapshot_index == 0 means the
        submitter predates conflict stamping (wire plans from old peers,
        the legacy planner shape) — no attribution, same behavior."""
        if snapshot_index <= 0 or not touched:
            return False
        log = self._commit_log
        for index, nodes in reversed(log):
            if index <= snapshot_index:
                # The log reaches back past the snapshot: the window is
                # fully covered and no overlap was found.
                return False
            if not touched.isdisjoint(nodes):
                return True
        # Scan fell off the log's old end before reaching snapshot_index.
        # A full deque means older commits were evicted — the window is
        # NOT covered, so attribute conservatively (treated as
        # overlapping, per the COMMIT_LOG_DEPTH contract). A part-filled
        # deque holds every commit this pipeline ever made: nothing was
        # missed, no conflict.
        return len(log) == log.maxlen

    # -- the loop -----------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set():
            batch = self.plan_queue.dequeue_batch(
                self.max_batch, timeout=0.2
            )
            if not batch:
                continue
            try:
                self._process_batch(batch)
            except Exception as e:  # never leak blocked workers
                telemetry.incr_counter(("plan", "pipeline", "batch_failure"))
                self.logger.exception("plan pipeline batch failed")
                for pending in batch:
                    if not pending.future.done():
                        pending.respond(None, e)
                        if pending.plan.express_lease:
                            continue  # never marked the broker
                        # Clear the inflight mark outstanding_reset_and_mark
                        # set (the serial applier cleared it in EVERY
                        # respond path): a leaked mark makes nack defer on
                        # a retry timer forever and the eval permanently
                        # undeliverable. Unmarked/already-done plans are a
                        # harmless no-op decrement.
                        try:
                            self.eval_broker.plan_done(pending.plan.eval_id)
                        except Exception:
                            # plan_done is a lock-guarded decrement; a
                            # failure here means broker state is already
                            # torn down — count it and keep failing the
                            # remaining futures (nomadlint EXC001).
                            telemetry.incr_counter(
                                ("plan", "pipeline", "plan_done_error")
                            )

    def _process_batch(self, batch: List[PendingPlan]) -> None:
        tracer = trace.get_tracer()

        # Token verification + inflight mark, atomically per plan
        # (split-brain guard, plan_apply.go:52-58; the mark stops the nack
        # timer redelivering an eval whose plan is mid-commit).
        live: List[PendingPlan] = []
        ctxs: Dict[int, Dict[str, str]] = {}
        for pending in batch:
            eval_id = pending.plan.eval_id
            plan_ctx = pending.plan.span_ctx or tracer.root_ctx(eval_id)
            ctxs[id(pending)] = plan_ctx
            tracer.start_span(
                eval_id, "plan.queue_wait", parent=plan_ctx,
                start=pending.enqueue_time,
            ).finish()
            if pending.plan.express_lease:
                # Express async-commit plans (server/express.py): the
                # eval never rode the broker, so there is no outstanding
                # delivery to re-token or mark — and nothing to plan_done
                # later. They still verify/commit/bounce like any plan.
                live.append(pending)
                continue
            try:
                self.eval_broker.outstanding_reset_and_mark(
                    eval_id, pending.plan.eval_token
                )
            except BrokerError as e:
                self.logger.error(
                    "plan rejected for evaluation %s: %s", eval_id, e
                )
                pending.respond(None, e)
                with self.totals._lock:
                    self.totals.rejected += 1
                continue
            live.append(pending)
        if not live:
            return

        telemetry.add_sample(("plan", "batch_size"), float(len(live)))
        with self.totals._lock:
            self.totals.batches += 1
            self.totals.plans += len(live)
            self.totals.max_batch_seen = max(
                self.totals.max_batch_seen, len(live)
            )

        # Optimistic snapshot lineage: the rolled copy exists ONLY to
        # overlap verification with a still-in-flight apply. Once every
        # dispatched apply has resolved — and equally when the previous
        # batch dispatched nothing (all-bounce/noop batches leave
        # _inflight empty) — the real state is authoritative: drop the
        # rolled copy and re-snapshot fresh, so out-of-band raft writes
        # (client alloc updates freeing capacity, node drains, GC) are
        # seen and an all-bounce batch can never pin a stale snapshot
        # into an indefinite bounce loop.
        if self._inflight and all(f.done() for f in self._inflight):
            self._inflight = []
        if not self._inflight:
            self._opt_snap = None
        if self._opt_snap is None:
            self._opt_snap = self.fsm.state.snapshot()
        snap = self._opt_snap

        t0 = time.perf_counter()
        eval_spans = []
        for pending in live:
            eval_spans.append(tracer.start_span(
                pending.plan.eval_id, "plan.evaluate",
                parent=ctxs[id(pending)],
            ))
        # Commit-index estimate: the batch's K entries land back-to-back,
        # so the j-th committed plan's entry lands at base + j (exact
        # under InProcRaft absent interleaved writes; an interleaved
        # write shifts real indices up and the waiter fixes the commit
        # log up from each resolved future). The old serial "+1 for
        # every plan" stamped all K commits at the SAME index, which
        # broke the reversed commit-log scan's early-exit and
        # systematically under-attributed conflicts.
        base_index = self.raft.applied_index
        commit_seq = [0]

        def stamp_index() -> int:
            commit_seq[0] += 1
            return base_index + commit_seq[0]

        ledger = self.ledger
        if ledger is not None and not ledger.active() \
                and not any(p.plan.express_lease for p in live):
            # Empty ledger and no express plans in the batch: skip the
            # debit-map plumbing entirely (the lane-off steady state).
            ledger = None
        results = evaluate_plans(
            snap, [p.plan for p in live],
            stamp_index=stamp_index,
            totals=self.totals,
            ledger=ledger,
        )
        for span, result in zip(eval_spans, results):
            span.annotate("refresh_index", result.refresh_index)
            span.annotate("batched", len(live)).finish()
        telemetry.measure_since(("plan", "evaluate"), t0)

        # Commit-order pass: record committed footprints, attribute
        # conflicts transaction-time (same batch first — earlier commits
        # are already in the log when later plans are attributed).
        to_commit: List[Tuple[PendingPlan, PlanResult]] = []
        for pending, result in zip(live, results):
            plan = pending.plan
            if result.refresh_index:
                with self.totals._lock:
                    self.totals.refreshes += 1
                touched = _plan_touched_nodes(plan)
                if self._conflicts_since(touched, plan.snapshot_index):
                    result.conflict = True
                    telemetry.incr_counter(("plan", "conflicts"))
                    with self.totals._lock:
                        self.totals.conflicts += 1
            if result.is_noop():
                # Nothing to replicate (evict-nothing plans, whole-plan
                # bounces): respond straight away — the worker refreshes
                # and re-plans without waiting on this batch's commits.
                if not plan.express_lease:
                    self.eval_broker.plan_done(plan.eval_id)
                pending.respond(result, None)
                with self.totals._lock:
                    self.totals.noops += 1
                continue
            # Record the COMMITTED footprint (PlanResult carries the same
            # node-keyed shape as Plan), not the full ask — a bounced
            # subset took no capacity and must not charge later plans
            # with a conflict. Estimated index base + j (j-th dispatch of
            # this batch); the waiter overwrites it with the real index.
            entry = self._record_commit(
                base_index + len(to_commit) + 1,
                _plan_touched_nodes(result),
            )
            to_commit.append((pending, result, entry))
        if not to_commit:
            return

        # Bound staleness across batches: at most one batch of applies in
        # flight (plan_apply.go:119-144's single-overlap rule, batched).
        for f in self._inflight:
            try:
                f.result()
            except Exception:
                # The failure was already delivered to ITS plan's worker
                # by the waiter thread; here the future is only drained
                # for the single-overlap staleness bound. Still counted:
                # a quietly failing apply stream is a sick raft layer
                # (nomadlint EXC001).
                telemetry.incr_counter(("plan", "pipeline", "apply_error"))
        self._inflight = []

        dispatched = []
        for pending, result, entry in to_commit:
            apply_span = tracer.start_span(
                pending.plan.eval_id, "plan.apply",
                parent=ctxs[id(pending)],
            )
            future = self._apply(result, pending.plan, apply_span)
            dispatched.append((pending, result, future, apply_span, entry))
        self._inflight = [f for _, _, f, _, _ in dispatched]
        with self.totals._lock:
            self.totals.committed += len(dispatched)
        if all(f.done() for _, _, f, _, _ in dispatched):
            # Synchronous replication (InProcRaft): every future resolved
            # during dispatch — respond inline and spare each blocked
            # worker a waiter-thread spawn + context switch.
            self._resolve_batch(dispatched)
        else:
            waiter = threading.Thread(
                target=self._resolve_batch, args=(dispatched,), daemon=True,
                name="plan-pipeline-wait",
            )
            waiter.start()

    def _apply(self, result: PlanResult, plan: Plan, span=None):
        """Dispatch one plan's replicated alloc update. The optimistic
        snapshot was already rolled by evaluate_plans — only the raft
        entry goes out here."""
        t0 = time.perf_counter()
        allocs = _object_allocs(result)
        payload = {"allocs": allocs}
        if result.alloc_batches:
            payload["alloc_batches"] = result.alloc_batches
        if result.update_batches:
            payload["update_batches"] = result.update_batches
        # Plan provenance rides the replicated entry so EVERY replica's
        # FSM publishes exactly one PlanApplied per committed plan.
        payload["plan"] = {
            "eval_id": plan.eval_id,
            "allocs": len(allocs),
            "alloc_batches": len(result.alloc_batches),
            "update_batches": len(result.update_batches),
        }
        # A synchronous replication layer (InProcRaft) applies on THIS
        # thread: the active-span install lets the FSM hang its fsm.apply
        # span under plan.apply. An async raft applies elsewhere.
        with trace.use_span(span if span is not None else trace.NULL_SPAN):
            future = self.raft.apply("alloc_update", payload)
        telemetry.measure_since(("plan", "submit"), t0)
        return future

    def _resolve_batch(self, dispatched) -> None:
        """Resolve the batch's raft futures in commit order and respond —
        one thread per batch instead of one per plan (plan_apply.go:146-162
        amortized)."""
        for pending, result, future, span, entry in dispatched:
            index = 0
            try:
                try:
                    index = future.result()
                except Exception as e:  # raft apply failed
                    self.logger.error("failed to apply plan: %s", e)
                    if span is not None:
                        span.annotate("error", str(e)).finish()
                    pending.respond(None, e)
                    continue
                if entry is not None:
                    # Fix the conflict-attribution log up from estimate
                    # to the entry's real raft index (see _record_commit).
                    entry[0] = index
                result.alloc_index = index
                if span is not None:
                    span.annotate("alloc_index", index).finish()
                pending.respond(result, None)
            finally:
                # The commit is durable (or failed): redelivery may
                # proceed, and a redelivered worker's wait_index now
                # covers this plan. Express plans never marked the
                # broker, so there is nothing to clear.
                if not pending.plan.express_lease:
                    self.eval_broker.plan_done(
                        pending.plan.eval_id, commit_index=index
                    )
