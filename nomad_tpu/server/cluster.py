"""ClusterServer: a Raft-replicated, network-RPC member of a server cluster.

Reference composition: nomad/server.go (Raft + RPC wiring), nomad/leader.go
(leadership monitor enabling broker/plan queue, restoring broker state,
renewing heartbeat timers on failover), nomad/rpc.go:163-228 (leader
forwarding). Every server runs workers; followers forward Eval.Dequeue /
Plan.Submit / write RPCs to the leader, exactly like the reference's
optimistically-concurrent worker pool.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nomad_tpu import trace
from nomad_tpu.api.codec import from_dict, to_dict
from nomad_tpu.raft import NotLeaderError, RaftConfig, RaftNode
from nomad_tpu.rpc import (
    ConnPool,
    RPCError,
    RPCServer,
    RPCUndeliveredError,
    RemoteError,
)
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    Plan,
    PlanResult,
)


@dataclass
class ClusterConfig:
    """Cluster membership for one server (static peer set; the reference's
    bootstrap_expect posture, serf.go:76-134)."""

    node_id: str = ""
    bind_host: str = "127.0.0.1"
    bind_port: int = 0
    # node_id -> rpc addr for all members, incl. self; filled in by
    # form_cluster for tests or by configuration.
    peers: Dict[str, str] = field(default_factory=dict)
    raft_data_dir: str = ""
    heartbeat_interval: float = 0.05
    election_timeout_min: float = 0.15
    election_timeout_max: float = 0.30
    # Hold elections until this many members are known (serf.go:76-134
    # maybeBootstrap). 0/1 = bootstrap immediately (single-server / dev).
    bootstrap_expect: int = 1
    # Addresses to Serf.Join at startup (retry-join posture,
    # command/agent/command.go retry_join handling).
    start_join: List[str] = field(default_factory=list)
    # FSM snapshot / log-compaction cadence (raft.FileSnapshotStore retains
    # 2 at nomad/server.go:453).
    snapshot_threshold: int = 8192
    snapshot_retain: int = 2
    # Entries retained past the snapshot at compaction (hashicorp/raft
    # TrailingLogs; RaftConfig.trailing_logs).
    trailing_logs: int = 1024
    # InstallSnapshot transfer chunk size (RaftConfig.snapshot_chunk_bytes):
    # raw snapshot bytes per RPC on the catch-up path.
    snapshot_chunk_bytes: int = 256 * 1024
    # Gossip-style failure detection (serf memberlist probing, serf.go:136-
    # 194): each server pings its same-region peers every probe_interval;
    # suspicion_threshold consecutive failures mark a member failed. The
    # leader reconciles membership (leader.go:263-343): failed members are
    # removed from the Raft configuration and reaped from the member table;
    # gossip-known members missing from Raft are added.
    probe_interval: float = 1.0
    probe_timeout: float = 1.0
    suspicion_threshold: int = 5
    # Keep retrying start_join addresses until one succeeds (the agent's
    # retry-join posture, command/agent/command.go).
    retry_join_interval: float = 2.0


class ClusterServer(Server):
    def __init__(self, config: Optional[ServerConfig] = None,
                 cluster: Optional[ClusterConfig] = None,
                 logger: Optional[logging.Logger] = None):
        self.cluster = cluster or ClusterConfig()
        super().__init__(config, logger)

        # Optional TLS (ServerConfig.tls -> tlsutil.TLSConfig): the
        # listener serves the node cert (mutual when verify_incoming) and
        # the pool dials with CA verification — the reference's rpcTLS
        # arm (nomad/rpc.go:104-110).
        tls = self.config.tls
        incoming = tls.incoming_context() if tls is not None else None
        outgoing = tls.outgoing_context() if tls is not None else None
        self.rpc = RPCServer(
            self.cluster.bind_host, self.cluster.bind_port,
            self.logger.getChild("rpc"), ssl_context=incoming,
        )
        self.rpc_addr = self.rpc.addr
        # One stream-multiplexed connection per peer carries control
        # traffic AND long-polls (Eval.Dequeue, blocking queries) — the
        # yamux posture (nomad/rpc.go:120-137); see nomad_tpu/rpc.py.
        self.pool = ConnPool(timeout=5.0, ssl_context=outgoing)

        if not self.cluster.node_id:
            self.cluster.node_id = self.config.node_name
        self.cluster.peers.setdefault(self.cluster.node_id, self.rpc_addr)
        # Cross-region federation table: region -> {node_id: rpc_addr}.
        # Raft membership stays per-region (the reference replicates within
        # a region and WAN-gossips across, server.go:503-538); only the
        # same-region branch of a join touches cluster.peers.
        self.region_peers: Dict[str, Dict[str, str]] = {
            self.config.region: self.cluster.peers
        }

        # Member liveness from the probing loop: node_id -> "alive"/"failed"
        # (absent = alive, never probed bad).
        self._member_status: Dict[str, str] = {}
        self._probe_failures: Dict[str, int] = {}

        # Replace the in-process replication layer with Raft. Raft keeps
        # its OWN peer table (seeded from the gossip view at start, then
        # changed only by committed _config entries via the leader's
        # reconciliation) — the gossip table converges eventually, the
        # Raft configuration changes one committed step at a time.
        self.raft = RaftNode(
            RaftConfig(
                node_id=self.cluster.node_id,
                peers={self.cluster.node_id: self.rpc_addr},
                heartbeat_interval=self.cluster.heartbeat_interval,
                election_timeout_min=self.cluster.election_timeout_min,
                election_timeout_max=self.cluster.election_timeout_max,
                data_dir=self.cluster.raft_data_dir,
                bootstrap_expect=max(self.cluster.bootstrap_expect, 1),
                snapshot_threshold=self.cluster.snapshot_threshold,
                snapshot_retain=self.cluster.snapshot_retain,
                trailing_logs=self.cluster.trailing_logs,
                snapshot_chunk_bytes=self.cluster.snapshot_chunk_bytes,
            ),
            self.fsm,
            self.rpc,
            logger=self.logger.getChild("raft"),
            # Raft keeps its own (shorter-timeout) pool; it must dial with
            # the same TLS posture or peers' TLS listeners reject its
            # plaintext vote/append traffic.
            pool=ConnPool(timeout=2.0, ssl_context=outgoing),
        )
        self.raft.on_leadership_change = self._leadership_changed
        # Only a current leader feeds its broker during FSM apply; raft role
        # flips synchronously under the raft lock, unlike the async
        # leadership notification that enables/disables the broker.
        self.fsm.enqueue_guard = lambda: self.raft.is_leader
        # Plan applier must ride the raft replication layer
        self.plan_applier.raft = self.raft
        self._register_endpoints()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        # Same ordering contract as Server.start: the mesh must be
        # configured before any worker builds a mirror.
        self._apply_solver_mesh()
        self.rpc.start()
        joined = not self.cluster.start_join
        for addr in self.cluster.start_join:
            try:
                n = self.join(addr)
                self.logger.info("cluster: joined %d peers via %s", n, addr)
                joined = True
            except RPCError as e:
                self.logger.warning("cluster: start_join %s failed: %s", addr, e)
        # Seed the Raft peer table from the gossip view as of startup;
        # later membership moves only via committed _config entries.
        self.raft.config.peers.update(self.cluster.peers)
        if not joined:
            threading.Thread(
                target=self._retry_join_loop, daemon=True,
                name=f"retry-join-{self.cluster.node_id}",
            ).start()
        threading.Thread(
            target=self._membership_loop, daemon=True,
            name=f"membership-{self.cluster.node_id}",
        ).start()
        self.raft.start()
        self.plan_applier.start()
        if self.slo_monitor is not None:
            self.slo_monitor.start()
        self.express_lane.start()
        self.capacity_accountant.start()
        self.raft_observatory.start()
        # Start the read observatory here too: this override previously
        # omitted it, so cluster members served every HTTP read with the
        # freshness/serving ledger stopped at its construction snapshot —
        # exactly the servers whose follower-serving books matter most.
        self.read_observatory.start()
        self.runtime_observatory.start()
        from nomad_tpu.server.worker import Worker

        for i in range(self.config.scheduler_workers):
            worker = Worker(self, i)
            worker.start()
            self.workers.append(worker)
        reaper = threading.Thread(
            target=self._reap_failed_evaluations, daemon=True,
            name="failed-eval-reaper",
        )
        reaper.start()
        self._start_readmission()

    def shutdown(self) -> None:
        super().shutdown()
        self.raft.shutdown()
        self.rpc.shutdown()
        self.pool.shutdown()

    def _leadership_changed(self, is_leader: bool) -> None:
        """establishLeadership / revokeLeadership (leader.go:99-140,
        240-260)."""
        self.fsm.events.publish(
            "Leader", "LeaderAcquired" if is_leader else "LeaderLost",
            key=self.cluster.node_id,
            payload={"term": getattr(self.raft, "current_term", 0)},
        )
        if is_leader:
            self.logger.info("cluster: %s gained leadership",
                             self.cluster.node_id)
            # Leader barrier BEFORE enabling the broker (leader.go
            # establishLeadership's raft.Barrier): the FSM must contain
            # every entry committed by prior terms — in particular any
            # plan a dying leader applied for a still-pending eval — so
            # restore_eval_broker's wait_index covers it and no worker
            # schedules that eval against a pre-plan snapshot.
            try:
                self.raft.barrier(timeout=10.0)
            except Exception as e:
                # Stalled quorum; proceed — a low wait_index degrades to
                # the pre-barrier behavior rather than wedging leadership
                # establishment.
                self.logger.warning("cluster: leader barrier failed: %s", e)
            # Leadership callbacks run on unordered daemon threads: the
            # lose-handler may have fully run (disable+flush) DURING the
            # barrier. Enabling now would leave broker/plan queue live on
            # a follower — re-check before touching anything.
            if not self.raft.is_leader:
                self.logger.info(
                    "cluster: %s lost leadership during establishment",
                    self.cluster.node_id)
                return
            self.plan_queue.set_enabled(True)
            self.eval_broker.set_enabled(True)
            self.restore_eval_broker()
            # Renew heartbeat TTLs with the failover grace so nodes aren't
            # marked down during the transition (heartbeat.go:13-42).
            for node in self.state_store.nodes():
                if not node.terminal_status():
                    self.heartbeat.reset_heartbeat_timer(node.id)
            # The recovery timeline's terminal anchor: leadership is
            # established, the broker restored, TTLs renewed — this
            # server answers queries and schedules again (time-to-
            # serving, nomad_tpu/raft_observe.py). Idempotent.
            self.raft.mark_serving()
        else:
            self.logger.info("cluster: %s lost leadership",
                             self.cluster.node_id)
            self.plan_queue.set_enabled(False)
            self.eval_broker.set_enabled(False)
            self.heartbeat.clear_all()
            # Express leases are leader-local promises against a view
            # this server no longer owns: drop them (counted). Pending
            # express commits reconcile to the new leader via the
            # committer's forward path.
            self.express_lane.demote()

    # -- forwarding (rpc.go:163-228) ------------------------------------------
    #
    # Forwarding audit (the consistency-lane contract): ONLY writes and
    # leader-owned machinery cross the wire from a follower — Eval.* broker
    # ops, Plan.Submit, Express.Reconcile, Job.*/Node.* mutations, and the
    # linearizable lane's Raft.ReadIndex (an 8-byte index exchange, not the
    # read itself). Every read RPC in _register_endpoints below
    # (Node.GetAllocs, Eval.GetEval, Job.GetJob, Alloc.GetAlloc, Status.*)
    # and every HTTP GET run against LOCAL state on whichever server was
    # dialed; the stale lane never produces a leader RPC (regression-pinned
    # by tests/test_read_path.py::test_stale_read_zero_leader_rpcs).

    def _forward(self, method: str, args: dict,
                 timeout: Optional[float] = None):
        """Forward an RPC to the current leader. Waits briefly for leader
        discovery (a follower learns the leader from the first heartbeat of a
        term); raises NotLeaderError if none appears — callers back off and
        retry like the reference worker (worker.go:398-411).

        Undelivered requests (stale leader address across an election, a
        connection the peer closed before the frame went out) are retried
        twice against the freshly-discovered leader — the handler provably
        never ran, so even non-idempotent RPCs are safe to replay (the
        RPCUndeliveredError contract, rpc.py:78-83; policy shared with
        backoff.retry_undelivered). Timeouts and lost responses are NOT
        retried: the request may have executed, and the delivery
        guarantees belong to the caller (the broker's Nack machinery,
        raft-upsert idempotency)."""
        import time as _time

        from nomad_tpu.backoff import Backoff

        deadline = _time.monotonic() + 1.0
        # Jittered, not flat: every follower worker forwarding to a dead
        # leader retries on this path at once, and the decorrelation is
        # what keeps the freshly-elected leader from absorbing a synchro-
        # nized thundering herd.
        retry_bo = Backoff(base=0.05, max_delay=0.5)
        discover_bo = Backoff(base=0.02, max_delay=0.2)
        # At most one retry per address: a severed-but-healthy leader conn
        # reconnects on the first retry; a blackholed leader (connect
        # timeout) must not burn attempt x connect-timeout before failing.
        undelivered_to: dict = {}
        while True:
            leader = self.raft.leader_addr
            if leader:
                try:
                    return self.pool.call(leader, method, args,
                                          timeout=timeout)
                except RemoteError as e:
                    # Recover typed admission rejections from the error
                    # envelope: without this, a follower degrades the
                    # leader's cheap 429/503-with-hint into a generic
                    # 500 for every HTTP caller (the typed contract must
                    # not depend on which server the client dialed).
                    from nomad_tpu.structs import parse_reject

                    rejection = parse_reject(str(e))
                    if rejection is not None:
                        raise rejection from e
                    raise
                except RPCUndeliveredError:
                    if undelivered_to.get(leader, 0) >= 1 or \
                            len(undelivered_to) >= 3:
                        raise
                    undelivered_to[leader] = 1
                    deadline = _time.monotonic() + 1.0
                    retry_bo.sleep()
                    continue
            if self.raft.is_leader or _time.monotonic() >= deadline:
                raise NotLeaderError("")
            discover_bo.sleep()

    # -- overridden server seams ----------------------------------------------

    def eval_dequeue(self, schedulers: List[str], timeout: float):
        if self.raft.is_leader:
            return super().eval_dequeue(schedulers, timeout)
        out = self._forward(
            "Eval.Dequeue", {"schedulers": schedulers, "timeout": timeout},
            timeout=timeout + 5.0,
        )
        if out.get("eval") is None:
            return None, "", 0
        ev = from_dict(Evaluation, out["eval"])
        # Adopt the leader broker's root span context so this follower's
        # worker spans parent correctly across the RPC boundary.
        trace.get_tracer().adopt_root(ev.id, out.get("span_ctx") or {})
        return ev, out["token"], int(out.get("wait_index", 0))

    def eval_dequeue_batch(self, schedulers: List[str], max_batch: int,
                           timeout: float):
        if self.raft.is_leader:
            return super().eval_dequeue_batch(schedulers, max_batch, timeout)
        out = self._forward(
            "Eval.DequeueBatch",
            {"schedulers": schedulers, "max_batch": max_batch,
             "timeout": timeout},
            timeout=timeout + 5.0,
        )
        batch = []
        tracer = trace.get_tracer()
        for item in out["batch"]:
            ev = from_dict(Evaluation, item["eval"])
            tracer.adopt_root(ev.id, item.get("span_ctx") or {})
            batch.append((ev, item["token"],
                          int(item.get("wait_index", 0))))
        return batch

    def eval_ack(self, eval_id: str, token: str) -> None:
        if self.raft.is_leader:
            self.eval_broker.ack(eval_id, token)
            return
        self._forward("Eval.Ack", {"eval_id": eval_id, "token": token})

    def eval_nack(self, eval_id: str, token: str) -> None:
        if self.raft.is_leader:
            self.eval_broker.nack(eval_id, token)
            return
        self._forward("Eval.Nack", {"eval_id": eval_id, "token": token})

    def eval_touch(self, eval_id: str, token: str) -> None:
        if self.raft.is_leader:
            self.eval_broker.outstanding_reset(eval_id, token)
            return
        self._forward("Eval.Reset", {"eval_id": eval_id, "token": token})

    def eval_upsert(self, evals: List[Evaluation]) -> int:
        if self.raft.is_leader:
            return self.raft.apply("eval_update", {"evals": evals}).result()
        return self._forward(
            "Eval.Upsert", {"evals": [to_dict(e) for e in evals]}
        )

    def plan_submit(self, plan: Plan) -> PlanResult:
        if self.raft.is_leader:
            return self.plan_queue.enqueue(plan).wait()
        out = self._forward("Plan.Submit", {"plan": to_dict(plan)})
        return from_dict(PlanResult, out)

    def confirmed_read_index(self, timeout: float = 2.0) -> int:
        """Linearizable-lane seam: the leader confirms via its own read
        lease / quorum round; a follower asks the leader for a confirmed
        index over Raft.ReadIndex — the only read-path traffic that ever
        crosses the wire (the data itself is served from local state once
        applied catches up, read_path._await_read_index)."""
        if self.raft.is_leader:
            return self.raft.read_index(timeout=timeout)
        try:
            out = self._forward("Raft.ReadIndex", {"timeout": timeout},
                                timeout=timeout + 2.0)
        except RemoteError as e:
            # Leader-side refusal (deposed mid-call, stalled quorum)
            # crosses the wire untyped; surface it as the retriable
            # refusal the lane maps to a typed STALE_BOUND reject.
            raise TimeoutError(f"read index forward failed: {e}") from e
        return int(out["index"])

    def express_reconcile(self, job: Job, evals: List[Evaluation]) -> int:
        """Express slow-path reconciliation rides to the CURRENT leader:
        a deposed server's committer must be able to durably hand its
        uncommitted express placements over (server/express.py)."""
        if self.raft.is_leader:
            return super().express_reconcile(job, evals)
        return self._forward(
            "Express.Reconcile",
            {"job": to_dict(job), "evals": [to_dict(e) for e in evals]},
        )

    def job_register(self, job: Job, client_id: str = ""):
        # Cross-region submissions route to the owning region first
        # (rpc.go:163-177 forward: region mismatch -> forwardRegion).
        # client_id rides every hop so the LEADER's admission rate lanes
        # see the true submitter, not the forwarding server.
        if job.region and job.region != self.config.region:
            out = self.forward_region(
                job.region, "Job.Register",
                {"job": to_dict(job), "client_id": client_id},
            )
            return out["eval_id"], out["index"]
        if self.raft.is_leader:
            return super().job_register(job, client_id=client_id)
        out = self._forward(
            "Job.Register", {"job": to_dict(job), "client_id": client_id}
        )
        return out["eval_id"], out["index"]

    def job_evaluate(self, job_id: str, client_id: str = ""):
        # Eval ingress is admission-gated like registration — and the
        # gate lives on the LEADER (its rate-lane table and live broker
        # depth are the real ones; a follower's are vacuous). Forward
        # before checking anything locally.
        if self.raft.is_leader:
            return super().job_evaluate(job_id, client_id=client_id)
        out = self._forward(
            "Job.Evaluate", {"job_id": job_id, "client_id": client_id}
        )
        return out["eval_id"], out["index"]

    def job_deregister(self, job_id: str):
        if self.raft.is_leader:
            return super().job_deregister(job_id)
        out = self._forward("Job.Deregister", {"job_id": job_id})
        return out["eval_id"], out["index"]

    def node_register(self, node: Node):
        if self.raft.is_leader:
            return super().node_register(node)
        return self._forward("Node.Register", {"node": to_dict(node)})

    def node_batch_register(self, nodes: List[Node]):
        if self.raft.is_leader:
            return super().node_batch_register(nodes)
        return self._forward(
            "Node.BatchRegister", {"nodes": [to_dict(n) for n in nodes]},
            # A whole tranche rides one frame; give the leader time to
            # apply + arm before the caller's deadline fires.
            timeout=30.0,
        )

    def node_batch_heartbeat(self, node_ids: List[str]):
        if self.raft.is_leader:
            return super().node_batch_heartbeat(node_ids)
        # Same extended deadline as BatchRegister: a tranche of non-ready
        # nodes costs the leader one raft apply + eval fan-out EACH.
        return self._forward("Node.BatchHeartbeat", {"node_ids": node_ids},
                             timeout=30.0)

    def node_update_status(self, node_id: str, status: str):
        if self.raft.is_leader:
            return super().node_update_status(node_id, status)
        return self._forward(
            "Node.UpdateStatus", {"node_id": node_id, "status": status}
        )

    def node_update_drain(self, node_id: str, drain: bool):
        if self.raft.is_leader:
            return super().node_update_drain(node_id, drain)
        return self._forward(
            "Node.UpdateDrain", {"node_id": node_id, "drain": drain}
        )

    def update_allocs_from_client(self, allocs: List[Allocation]) -> int:
        if self.raft.is_leader:
            return super().update_allocs_from_client(allocs)
        return self._forward(
            "Node.UpdateAlloc", {"allocs": [to_dict(a) for a in allocs]}
        )

    # -- RPC endpoint registration (server.go:130-137) -------------------------

    def _register_endpoints(self) -> None:
        r = self.rpc.register
        r("Status.Ping", lambda args: "pong")
        r("Status.Leader", lambda args: self.raft.leader_addr)
        r("Status.Peers", lambda args: list(self.cluster.peers.values()))
        r("Status.Stats", lambda args: {**self.stats(), **self.raft.stats()})
        r("Status.Regions", lambda args: self.regions())

        r("Eval.Dequeue", self._rpc_eval_dequeue)
        r("Eval.DequeueBatch", self._rpc_eval_dequeue_batch)
        r("Eval.Ack", lambda a: self.eval_ack(a["eval_id"], a["token"]))
        r("Eval.Nack", lambda a: self.eval_nack(a["eval_id"], a["token"]))
        r("Eval.Reset", lambda a: self.eval_touch(a["eval_id"], a["token"]))
        r("Eval.Upsert", lambda a: self.eval_upsert(
            [from_dict(Evaluation, e) for e in a["evals"]]
        ))
        r("Plan.Submit", self._rpc_plan_submit)
        r("Express.Reconcile", lambda a: self.express_reconcile(
            from_dict(Job, a["job"]),
            [from_dict(Evaluation, e) for e in a["evals"]],
        ))
        r("Job.Register", self._rpc_job_register)
        r("Job.Evaluate", self._rpc_job_evaluate)
        r("Job.Deregister", self._rpc_job_deregister)
        r("Node.Register", lambda a: self.node_register(from_dict(Node, a["node"])))
        r("Node.BatchRegister", lambda a: self.node_batch_register(
            [from_dict(Node, n) for n in a["nodes"]]
        ))
        r("Node.BatchHeartbeat", lambda a: self.node_batch_heartbeat(
            list(a["node_ids"])
        ))
        r("Node.UpdateStatus", lambda a: self.node_update_status(
            a["node_id"], a["status"]
        ))
        r("Node.UpdateDrain", lambda a: self.node_update_drain(
            a["node_id"], a["drain"]
        ))
        r("Node.UpdateAlloc", lambda a: self.update_allocs_from_client(
            [from_dict(Allocation, x) for x in a["allocs"]]
        ))
        r("Node.GetAllocs", self._rpc_node_get_allocs)
        r("Eval.GetEval", self._rpc_eval_get)
        r("Job.GetJob", self._rpc_job_get)
        r("Alloc.GetAlloc", self._rpc_alloc_get)
        r("Serf.Join", self._rpc_serf_join)
        r("Serf.PeerUpdate", self._rpc_serf_peer_update)

    def _rpc_eval_dequeue(self, args: dict):
        ev, token, wait_index = self.eval_dequeue(
            args["schedulers"], min(float(args.get("timeout", 0.5)), 10.0)
        )
        if ev is None:
            return {"eval": None, "token": ""}
        return {"eval": to_dict(ev), "token": token,
                "wait_index": wait_index,
                "span_ctx": trace.get_tracer().root_ctx(ev.id)}

    def _rpc_eval_dequeue_batch(self, args: dict):
        batch = self.eval_dequeue_batch(
            args["schedulers"], int(args.get("max_batch", 1)),
            min(float(args.get("timeout", 0.5)), 10.0),
        )
        tracer = trace.get_tracer()
        return {"batch": [
            {"eval": to_dict(ev), "token": token, "wait_index": wait_index,
             "span_ctx": tracer.root_ctx(ev.id)}
            for ev, token, wait_index in batch
        ]}

    def _rpc_plan_submit(self, args: dict):
        plan = from_dict(Plan, args["plan"])
        return to_dict(self.plan_submit(plan))

    def _rpc_job_register(self, args: dict):
        eval_id, index = self.job_register(
            from_dict(Job, args["job"]),
            client_id=str(args.get("client_id", "") or ""),
        )
        return {"eval_id": eval_id, "index": index}

    def _rpc_job_evaluate(self, args: dict):
        eval_id, index = self.job_evaluate(
            args["job_id"],
            client_id=str(args.get("client_id", "") or ""),
        )
        return {"eval_id": eval_id, "index": index}

    def _rpc_job_deregister(self, args: dict):
        eval_id, index = self.job_deregister(args["job_id"])
        return {"eval_id": eval_id, "index": index}

    def _rpc_node_get_allocs(self, args: dict):
        """Blocking Node.GetAllocs (node_endpoint.go:328) over the shared
        blocking_query machinery (server/blocking.py; rpc.go:270-335).
        Served from local (possibly follower) state — the stale-read
        path."""
        from nomad_tpu.server.blocking import blocking_query
        from nomad_tpu.state.store import item_alloc_node

        node_id = args["node_id"]
        min_index = int(args.get("min_index", 0))

        index, allocs = blocking_query(
            get_store=lambda: self.state_store,
            items=lambda store: [item_alloc_node(node_id)],
            run=lambda store: (
                store.get_index("allocs"), store.allocs_by_node(node_id)
            ),
            index_of=lambda store: store.get_index("allocs"),
            min_index=min_index,
            timeout=float(args.get("timeout", 0.5)),
        )
        if index <= min_index:
            return {"allocs": None, "index": index}
        return {"allocs": [to_dict(a) for a in allocs], "index": index}

    def _rpc_eval_get(self, args: dict):
        """Blocking Eval.GetEval (eval_endpoint.go GetEval + rpc.go
        blockingRPC): long-poll an evaluation's modify index — the RPC-tier
        feed for eval monitors."""
        from nomad_tpu.server.blocking import blocking_query
        from nomad_tpu.state.store import item_eval

        eval_id = args["eval_id"]
        min_index = int(args.get("min_index", 0))

        def run(store):
            ev = store.eval_by_id(eval_id)
            if ev is None:
                # Not-yet-created evals resolve on the table index, like
                # the reference's table-default QueryMeta.Index.
                return store.get_index("evals"), None
            return ev.modify_index, ev

        # item_eval fires on create, update, AND delete (store.py
        # upsert_evals/delete_eval), so the table-wide item is unnecessary
        # — and watching it would wake every parked monitor on every
        # unrelated eval write.
        index, ev = blocking_query(
            get_store=lambda: self.state_store,
            items=lambda store: [item_eval(eval_id)],
            run=run,
            min_index=min_index,
            timeout=float(args.get("timeout", 0.5)),
        )
        return {"eval": None if ev is None else to_dict(ev), "index": index}

    def _rpc_job_get(self, args: dict):
        """Blocking Job.GetJob (job_endpoint.go GetJob + rpc.go
        blockingRPC)."""
        from nomad_tpu.server.blocking import blocking_query
        from nomad_tpu.state.store import item_job

        job_id = args["job_id"]
        min_index = int(args.get("min_index", 0))

        def run(store):
            job = store.job_by_id(job_id)
            if job is None:
                return store.get_index("jobs"), None
            return job.modify_index, job

        index, job = blocking_query(
            get_store=lambda: self.state_store,
            items=lambda store: [item_job(job_id)],
            run=run,
            min_index=min_index,
            timeout=float(args.get("timeout", 0.5)),
        )
        return {"job": None if job is None else to_dict(job), "index": index}

    def _rpc_alloc_get(self, args: dict):
        alloc = self.state_store.alloc_by_id(args["alloc_id"])
        return None if alloc is None else to_dict(alloc)

    # -- membership (serf-lite; reference: nomad/serf.go + hashicorp/serf) ----

    def _retry_join_loop(self) -> None:
        """Keep retrying start_join until one address answers
        (command/agent/command.go retry-join)."""
        while not self._periodic_stop.is_set():
            self._periodic_stop.wait(self.cluster.retry_join_interval)
            if self._periodic_stop.is_set():
                return
            for addr in self.cluster.start_join:
                try:
                    n = self.join(addr)
                    self.logger.info(
                        "cluster: retry-join reached %d peers via %s", n, addr
                    )
                    return
                except RPCError:
                    continue

    def _membership_loop(self) -> None:
        """Failure detector + leader reconciliation (serf.go:136-194 member
        probing -> nodeFailed; leader.go:263-343 reconcile)."""
        leaderless_since = None
        while not self._periodic_stop.is_set():
            self._periodic_stop.wait(self.cluster.probe_interval)
            if self._periodic_stop.is_set():
                return
            try:
                self._probe_members()
                if self.raft.is_leader:
                    leaderless_since = None
                    self._reconcile_membership()
                elif self.raft.leader_addr:
                    leaderless_since = None
                else:
                    # No leader known. A server that was removed while
                    # partitioned (it never saw its own removal commit and
                    # members ignore its votes) self-heals here: re-join
                    # through gossip so the leader's reconciliation re-adds
                    # it to the Raft configuration.
                    import time as _time

                    now = _time.monotonic()
                    if leaderless_since is None:
                        leaderless_since = now
                    elif now - leaderless_since > max(
                        5 * self.cluster.probe_interval, 3.0
                    ):
                        leaderless_since = now
                        self._rejoin_any_member()
            except Exception:  # pragma: no cover - keep the loop alive
                self.logger.exception("cluster: membership pass failed")

    def _rejoin_any_member(self) -> None:
        for pid, addr in list(self.cluster.peers.items()):
            if pid == self.cluster.node_id:
                continue
            if self._member_status.get(pid) == "failed":
                continue
            try:
                self.join(addr)
                self.logger.info(
                    "cluster: leaderless; re-announced to %s via gossip", pid
                )
                return
            except (RPCError, RemoteError):
                continue

    def _probe_members(self) -> None:
        for pid, addr in list(self.cluster.peers.items()):
            if pid == self.cluster.node_id:
                continue
            try:
                self.pool.call(
                    addr, "Status.Ping", {},
                    timeout=self.cluster.probe_timeout,
                )
            except (RPCError, RemoteError):
                n = self._probe_failures.get(pid, 0) + 1
                self._probe_failures[pid] = n
                if (n >= self.cluster.suspicion_threshold
                        and self._member_status.get(pid) != "failed"):
                    self._member_status[pid] = "failed"
                    self.logger.warning(
                        "cluster: member %s failed (%d missed probes)",
                        pid, n,
                    )
            else:
                self._probe_failures.pop(pid, None)
                if self._member_status.get(pid) == "failed":
                    self.logger.info("cluster: member %s recovered", pid)
                self._member_status[pid] = "alive"

    def _reconcile_membership(self) -> None:
        """Leader-only: converge the Raft configuration with the gossip
        member table, one committed change at a time (leader.go:263-343;
        Raft single-server membership change)."""
        raft_peers = dict(self.raft.config.peers)
        # Members known to gossip but absent from Raft: add (nodeJoin ->
        # addRaftPeer, serf.go:76-134).
        for pid, addr in list(self.cluster.peers.items()):
            if pid in raft_peers or self._member_status.get(pid) == "failed":
                continue
            try:
                self.raft.add_peer(pid, addr).result(2.0)
                self.logger.info("cluster: added raft peer %s", pid)
            except Exception as e:
                self.logger.debug("cluster: add_peer %s deferred: %s", pid, e)
                return
        # Failed members still in Raft: remove and reap from the member
        # table (nodeFailed -> removeRaftPeer, serf.go:136-194).
        for pid in list(raft_peers):
            if pid == self.cluster.node_id:
                continue
            if self._member_status.get(pid) != "failed":
                continue
            try:
                self.raft.remove_peer(pid).result(2.0)
            except Exception as e:
                self.logger.debug(
                    "cluster: remove_peer %s deferred: %s", pid, e
                )
                return
            self.cluster.peers.pop(pid, None)
            self.logger.warning(
                "cluster: reaped failed member %s (now %d members)",
                pid, len(self.cluster.peers),
            )
            self._broadcast_peers()

    def join(self, addr: str) -> int:
        """Join an existing cluster member at ``addr`` (serf gossip join →
        nodeJoin → Raft peer add, serf.go:76-134). Joining a server of
        another region federates (region table only); same region adds
        raft peers. Returns servers joined."""
        out = self.pool.call(
            addr, "Serf.Join",
            {
                "node_id": self.cluster.node_id,
                "addr": self.rpc_addr,
                "region": self.config.region,
            },
        )
        peers = out.get("peers", {})
        self._merge_peers(peers)
        self._merge_region_peers(out.get("regions", {}))
        return len(peers) + sum(
            len(m) for r, m in out.get("regions", {}).items()
            if r != self.config.region
        )

    def force_leave(self, node_id: str) -> None:
        """Remove a member and broadcast the removal (serf.go nodeFailed /
        server-force-leave). Marks the member failed so the leader's
        reconciliation also drops it from the Raft configuration."""
        self.cluster.peers.pop(node_id, None)
        self._member_status[node_id] = "failed"
        if self.raft.is_leader and node_id in self.raft.config.peers:
            try:
                self.raft.remove_peer(node_id).result(2.0)
            except Exception as e:
                self.logger.warning(
                    "cluster: force-leave raft removal of %s deferred: %s",
                    node_id, e,
                )
        self._broadcast_peers()

    def members(self):
        return [
            {
                "name": pid,
                "addr": addr,
                "status": self._member_status.get(pid, "alive"),
                "leader": addr == self.raft.leader_addr,
            }
            for pid, addr in sorted(self.cluster.peers.items())
        ]

    def _merge_peers(self, peers: Dict[str, str]) -> None:
        before = dict(self.cluster.peers)
        self.cluster.peers.update(peers)
        if self.cluster.peers != before:
            self.logger.info(
                "cluster: peer set now %s", sorted(self.cluster.peers)
            )
            # Pre-bootstrap, discovered members seed Raft directly so the
            # first election can reach bootstrap_expect (maybeBootstrap);
            # afterwards the leader commits the additions.
            self.raft.seed_peers(dict(self.cluster.peers))

    def _merge_region_peers(self, regions: Dict[str, Dict[str, str]]) -> None:
        for region, members in regions.items():
            if region == self.config.region:
                continue  # own region raft membership only moves via joins
            self.region_peers.setdefault(region, {}).update(members)

    def _region_table(self) -> Dict[str, Dict[str, str]]:
        return {region: dict(m) for region, m in self.region_peers.items()}

    def regions(self) -> List[str]:
        """Known federated regions (reference: region tables built from serf
        tags, nomad/serf.go nodeJoin)."""
        return sorted(self.region_peers)

    def forward_region(self, region: str, method: str, args: dict):
        """RPC to any server of another region (rpc.go:204-228
        forwardRegion picks a random server from the region table)."""
        from nomad_tpu import prng

        members = self.region_peers.get(region)
        if not members:
            raise RPCError(f"no path to region {region!r}")
        addrs = list(members.values())
        # Load-spreading shuffle over region servers; a per-instance
        # name-salted stream decorrelates successive forwards without
        # the global random cursor (nomadlint DET001).
        rng = getattr(self, "_region_rng", None)
        if rng is None:
            rng = self._region_rng = prng.stream(
                prng.salt(self.config.node_name), "cluster.forward_region"
            )
        rng.shuffle(addrs)
        last: Optional[Exception] = None
        for addr in addrs:
            try:
                return self.pool.call(addr, method, args)
            except RemoteError as e:
                # Typed rejection from the remote region's front door:
                # surface it typed (and final — another server of the
                # same region would consult the same leader).
                from nomad_tpu.structs import parse_reject

                rejection = parse_reject(str(e))
                if rejection is not None:
                    raise rejection from e
                last = e
            except RPCError as e:
                last = e
        raise last

    def _broadcast_peers(self) -> None:
        snapshot = dict(self.cluster.peers)
        regions = self._region_table()
        targets = dict(snapshot)
        for members in regions.values():
            targets.update(members)
        for pid, addr in list(targets.items()):
            if pid == self.cluster.node_id:
                continue
            try:
                self.pool.call(
                    addr, "Serf.PeerUpdate",
                    {"peers": snapshot, "regions": regions,
                     "region": self.config.region},
                )
            except RPCError:
                pass  # gossip is best-effort; next join/update converges

    def _rpc_serf_join(self, args: dict):
        joiner_region = args.get("region", self.config.region)
        if joiner_region == self.config.region:
            self._merge_peers({args["node_id"]: args["addr"]})
        else:
            self.region_peers.setdefault(joiner_region, {})[
                args["node_id"]
            ] = args["addr"]
        self._broadcast_peers()
        return {
            "peers": dict(self.cluster.peers)
            if joiner_region == self.config.region
            else {},
            "regions": self._region_table(),
        }

    def _rpc_serf_peer_update(self, args: dict):
        sender_region = args.get("region", self.config.region)
        if sender_region == self.config.region:
            self._merge_peers(dict(args.get("peers", {})))
        else:
            self.region_peers.setdefault(sender_region, {}).update(
                args.get("peers", {})
            )
        self._merge_region_peers(dict(args.get("regions", {})))
        return {}


def form_cluster(
    n: int,
    server_config: Optional[ServerConfig] = None,
    base_cluster: Optional[ClusterConfig] = None,
    logger: Optional[logging.Logger] = None,
) -> List[ClusterServer]:
    """Build an n-server cluster on localhost with a shared static peer set
    (the in-process multi-server posture of reference server tests,
    nomad/server_test.go:26-87)."""
    import copy as _copy

    servers: List[ClusterServer] = []
    peers: Dict[str, str] = {}
    for i in range(n):
        cfg = _copy.deepcopy(server_config) if server_config else ServerConfig()
        cfg.node_name = f"server-{i}"
        cluster = _copy.deepcopy(base_cluster) if base_cluster else ClusterConfig()
        cluster.node_id = cfg.node_name
        cluster.peers = peers  # shared dict: filled as servers bind
        srv = ClusterServer(cfg, cluster, logger)
        servers.append(srv)
    for srv in servers:
        srv.start()
    return servers


def wait_for_leader(servers: List[ClusterServer], timeout: float = 10.0):
    """testutil.WaitForLeader (testutil/wait.go:33)."""
    import time as _time

    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        for srv in servers:
            if srv.raft.is_leader:
                return srv
        _time.sleep(0.02)
    raise TimeoutError("no cluster leader elected")
