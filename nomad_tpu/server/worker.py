"""Scheduling worker: dequeues evals, runs the scheduler, submits plans.

Reference: /root/reference/nomad/worker.go. Each server runs N workers
(NumSchedulers, config.go:223). The worker implements the scheduler's
Planner interface: SubmitPlan stamps the EvalToken and routes through the
plan queue; a RefreshIndex response forces a state refresh before retry.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Tuple

from nomad_tpu import telemetry, trace
from nomad_tpu.backoff import Backoff
from nomad_tpu.scheduler import new_scheduler
from nomad_tpu.server.eval_broker import BrokerError
from nomad_tpu.structs import JOB_TYPE_CORE, Evaluation, Plan, PlanResult

RAFT_SYNC_LIMIT = 2.0  # reference raftSyncLimit (worker.go:31-34)
DEQUEUE_TIMEOUT = 0.5


class Worker(threading.Thread):
    """One scheduling thread (worker.go:45-125)."""

    def __init__(self, server, worker_id: int = 0):
        super().__init__(daemon=True, name=f"worker-{worker_id}")
        self.server = server
        self.logger = server.logger.getChild(f"worker{worker_id}")
        self._stop = threading.Event()
        self._paused = False
        self._pause_cond = threading.Condition()
        self.eval_token: Optional[str] = None
        # State snapshot used for the current eval
        self._snapshot = None
        # Size of the most recent broker batch drain (observability/tests)
        self.last_batch_size = 0
        # Shared jittered backoff for dequeue failures (broker disabled,
        # leader-forwarding blips, injected broker.dequeue faults): resets
        # on any successful dequeue so a healthy broker pays nothing, and
        # decorrelates N workers hammering the same recovering leader.
        # max_delay deliberately small: a worker mid-sleep when leadership
        # returns adds this much to first-eval pickup after failover, so
        # the cap trades retry rate (<=4/s/worker while down) against
        # recovery latency (<=0.25s added).
        self._dequeue_backoff = Backoff(base=0.05, max_delay=0.25)

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        self.set_pause(False)

    def set_pause(self, paused: bool) -> None:
        """Leader pauses one worker to reduce contention (worker.go:77-93)."""
        with self._pause_cond:
            self._paused = paused
            self._pause_cond.notify_all()

    def _check_paused(self) -> None:
        """Pure condition-notify park: both exits (set_pause(False) and
        stop(), which routes through set_pause) notify the condition, so
        the 0.2s poll the loop used to carry bought nothing but wakeups —
        at N workers it was N/0.2 spurious scheduler passes per second of
        paused time."""
        with self._pause_cond:
            while self._paused and not self._stop.is_set():
                self._pause_cond.wait()

    def run(self) -> None:
        batch_size = getattr(self.server.config, "eval_batch_size", 1)
        while not self._stop.is_set():
            self._check_paused()
            if batch_size > 1:
                batch = self._dequeue_batch(batch_size)
                if not batch:
                    continue
                self.last_batch_size = len(batch)
                if len(batch) == 1:
                    self._process(*batch[0])
                    continue
                # Concurrent compatible evals (distinct jobs) from one
                # broker drain: run them in parallel so their device
                # solves stack into one coalesced dispatch
                # (ops/coalesce.py; SURVEY.md §7 "Batched evals").
                telemetry.add_sample(
                    ("worker", "eval_batch_size"), float(len(batch))
                )
                # Announce the burst so the coalescer holds its dispatch
                # until all of these evals' solves have stacked (or a
                # short window passes) instead of fragmenting on their
                # staggered host prep.
                from nomad_tpu.ops.coalesce import (
                    MAX_BATCH_BUCKET, GLOBAL_SOLVER,
                )

                # Clamped at the dispatch chunk size: holding for more
                # arrivals than one chunk can carry buys no coalescing.
                burst_token = GLOBAL_SOLVER.hint_burst(
                    min(len(batch), MAX_BATCH_BUCKET)
                )

                def process_burst_member(ev, token, wait_index):
                    # Account this eval against ITS announced burst
                    # exactly once: its first solve submit, or — for
                    # evals that never reach the coalescer (exact-path
                    # small counts, scale-downs, failed prep) — its
                    # completion, so the hold never waits on a solve
                    # that will never come.
                    GLOBAL_SOLVER.burst_begin(burst_token)
                    try:
                        self._process(ev, token, wait_index)
                    finally:
                        GLOBAL_SOLVER.burst_done()

                threads = [
                    threading.Thread(
                        target=process_burst_member,
                        args=(ev, token, wait_index),
                        daemon=True, name=f"{self.name}-batch{i}",
                    )
                    for i, (ev, token, wait_index) in enumerate(batch)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            else:
                dequeued = self._dequeue_evaluation()
                if dequeued is None:
                    continue
                self._process(*dequeued)

    def _process(self, ev: Evaluation, token: str,
                 wait_index: int = 0) -> None:
        # Wait for the local FSM to reach both the eval's modify index and
        # the broker's wait_index (worker.go:209-230 + Dequeue WaitIndex):
        # a redelivered eval's wait_index covers any plan an earlier
        # delivery committed before a leader died — snapshotting short of
        # it double-places the eval.
        tracer = trace.get_tracer()
        root_ctx = tracer.root_ctx(ev.id)
        sync_span = tracer.start_span(
            ev.id, "worker.wait_for_index", parent=root_ctx,
            annotations={"index": max(ev.modify_index, wait_index)},
        )
        try:
            self._wait_for_index(
                max(ev.modify_index, wait_index), RAFT_SYNC_LIMIT
            )
        except TimeoutError as e:
            sync_span.annotate("error", str(e)).finish()
            self.logger.error("error waiting for state sync: %s", e)
            self._send_ack(ev.id, token, ack=False)
            return
        sync_span.finish()
        # Touch the broker's nack timer while the scheduler runs: a cold
        # first compile of a new shape bucket can exceed eval_nack_timeout
        # before any plan is submitted, and a redelivered eval mid-solve
        # would double-schedule (OutstandingReset, eval_broker.go:396-412;
        # the plan applier's reset only fires once a plan exists).
        stop_touch = threading.Event()
        interval = max(self.server.config.eval_nack_timeout / 3.0, 0.05)

        def touch_loop():
            while not stop_touch.wait(interval):
                try:
                    self.server.eval_touch(ev.id, token)
                except BrokerError as e:
                    # The eval is no longer outstanding (acked/nacked/lost
                    # leadership): touching is moot.
                    self.logger.debug(
                        "eval touch stopped for %s: %s", ev.id, e
                    )
                    return
                except Exception as e:
                    # Transient forwarding failure (follower -> leader blip):
                    # keep trying — one miss must not disable the keep-alive
                    # for the rest of a long solve. Counted so a touch loop
                    # that NEVER succeeds shows up in metrics, not just a
                    # debug log (nomadlint EXC001).
                    telemetry.incr_counter(("worker", "touch_error"))
                    self.logger.debug(
                        "eval touch failed for %s (retrying): %s", ev.id, e
                    )

        toucher = threading.Thread(
            target=touch_loop, daemon=True, name=f"{self.name}-touch"
        )
        toucher.start()
        # device_activity: scheduler invocation does device work on THIS
        # thread (mirror device_puts, exact-path solves, result fetches);
        # quiesce_all must be able to drain it before interpreter teardown
        # — a daemon worker of a shut-down server can still be mid-solve.
        from nomad_tpu.ops.coalesce import device_activity

        inv_span = tracer.start_span(
            ev.id, "worker.invoke_scheduler", parent=root_ctx,
            annotations={"worker": self.name, "type": ev.type},
        )
        ok = False
        try:
            with device_activity(), trace.use_span(inv_span):
                ok = self._invoke_scheduler(
                    ev, token, planner=_EvalRun(self, token)
                )
        finally:
            stop_touch.set()
            inv_span.annotate("ok", ok).finish()
        self._send_ack(ev.id, token, ack=ok)

    # -- internals ---------------------------------------------------------

    def _dequeue_evaluation(self) -> Optional[Tuple[Evaluation, str, int]]:
        start = time.perf_counter()
        try:
            ev, token, wait_index = self.server.eval_dequeue(
                self.server.config.enabled_schedulers, timeout=DEQUEUE_TIMEOUT
            )
        except BrokerError:
            self._dequeue_backoff.sleep(stop=self._stop)
            return None
        except Exception as e:
            # Transient cluster conditions (no leader yet, forwarding error)
            telemetry.incr_counter(("worker", "dequeue_error"))
            self.logger.debug("dequeue failed, retrying: %s", e)
            self._dequeue_backoff.sleep(stop=self._stop)
            return None
        self._dequeue_backoff.reset()
        if ev is None:
            return None
        telemetry.measure_since(("worker", "dequeue_eval"), start)
        self.logger.debug("dequeued evaluation %s", ev.id)
        return ev, token, wait_index

    def _dequeue_batch(self, max_batch: int):
        start = time.perf_counter()
        try:
            batch = self.server.eval_dequeue_batch(
                self.server.config.enabled_schedulers, max_batch,
                timeout=DEQUEUE_TIMEOUT,
            )
        except BrokerError:
            self._dequeue_backoff.sleep(stop=self._stop)
            return []
        except Exception as e:
            telemetry.incr_counter(("worker", "dequeue_error"))
            self.logger.debug("batch dequeue failed, retrying: %s", e)
            self._dequeue_backoff.sleep(stop=self._stop)
            return []
        self._dequeue_backoff.reset()
        if batch:
            telemetry.measure_since(("worker", "dequeue_eval"), start)
            self.logger.debug(
                "dequeued %d evaluation(s): %s",
                len(batch), [ev.id for ev, _, _ in batch],
            )
        return batch

    def _send_ack(self, eval_id: str, token: str, ack: bool) -> None:
        """Best effort ack/nack (worker.go:172-202)."""
        start = time.perf_counter()
        try:
            if ack:
                self.server.eval_ack(eval_id, token)
            else:
                self.server.eval_nack(eval_id, token)
        except Exception as e:
            # Best-effort, but an ack that never lands re-delivers the
            # eval after nack_timeout — count it so a systematically
            # failing ack path alarms (nomadlint EXC001).
            telemetry.incr_counter(
                ("worker", "send_ack_error" if ack else "send_nack_error")
            )
            self.logger.error(
                "failed to %s evaluation '%s': %s", "ack" if ack else "nack",
                eval_id, e,
            )
        else:
            telemetry.measure_since(
                ("worker", "send_ack" if ack else "send_nack"), start
            )

    def _wait_for_index(self, index: int, timeout: float) -> None:
        """Spin until the FSM has applied ``index`` (worker.go:204-230).
        Timing recorded as nomad.worker.wait_for_index (worker.go:212)."""
        t0 = time.perf_counter()
        bo = Backoff(base=0.001, max_delay=0.1, jitter=0.0, deadline=timeout)
        alive = True
        while True:
            if self.server.raft.applied_index >= index:
                telemetry.measure_since(("worker", "wait_for_index"), t0)
                return
            if not alive:
                raise TimeoutError("sync wait timeout reached")
            alive = bo.sleep()  # one final index check after expiry

    def _invoke_scheduler(self, ev: Evaluation, token: str,
                          planner: Optional["_EvalRun"] = None) -> bool:
        """worker.go:232-261. ``planner`` carries per-eval token/snapshot
        state for batched processing; defaults to the worker itself (the
        single-eval posture, kept for the legacy call shape)."""
        start = time.perf_counter()
        # Transaction timestamp BEFORE the snapshot: the snapshot can only
        # be newer than the index read, so conflict attribution against it
        # errs toward reporting a conflict, never toward missing one.
        snapshot_index = self.server.raft.applied_index
        snapshot = self.server.state_store.snapshot()
        if planner is not None:
            planner.snapshot_index = snapshot_index
        if planner is None:
            # Legacy single-eval posture only: concurrent batch threads
            # must not stamp shared worker state (their token rides in
            # the per-eval _EvalRun).
            self.eval_token = token
            self._snapshot = snapshot
        try:
            if ev.type == JOB_TYPE_CORE:
                from nomad_tpu.server.core_sched import CoreScheduler

                sched = CoreScheduler(self.server, snapshot)
            else:
                factory = self.server.config.scheduler_factory(ev.type)
                sched = new_scheduler(
                    factory, snapshot, planner or self, self.logger
                )
            sched.process(ev)
            telemetry.measure_since(("worker", "invoke_scheduler", ev.type), start)
            return True
        except Exception:
            # The eval is nack'd by the caller (at-least-once redelivery),
            # but a scheduler crash is the highest-signal failure a worker
            # can see — counted per eval type (nomadlint EXC001).
            telemetry.incr_counter(("worker", "scheduler_failure", ev.type))
            self.logger.exception("failed to process evaluation %s", ev.id)
            return False

    # -- Planner interface (worker.go:263-396) ------------------------------

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[object]]:
        return _EvalRun(self, self.eval_token).submit_plan(plan)

    def update_eval(self, ev: Evaluation) -> None:
        self.server.eval_upsert([ev])

    def create_eval(self, ev: Evaluation) -> None:
        self.server.eval_upsert([ev])


class _EvalRun:
    """Per-eval Planner context (worker.go:263-396 semantics).

    Batched workers process several evals concurrently; each carries its
    own EvalToken so concurrent submit_plans can't stamp each other's
    token (the split-brain guard checked at plan apply,
    /root/reference/nomad/plan_apply.go:53-58)."""

    def __init__(self, worker: Worker, token: Optional[str]):
        self.worker = worker
        self.eval_token = token
        # Raft applied index of the snapshot this eval is planning
        # against; stamped by _invoke_scheduler and re-stamped on every
        # forced refresh. Rides each plan as Plan.snapshot_index — the
        # pipeline's conflict-attribution timestamp.
        self.snapshot_index = 0

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[object]]:
        start = time.perf_counter()
        plan.eval_token = self.eval_token
        plan.snapshot_index = self.snapshot_index
        # The submit span's context rides the request envelope
        # (Plan.span_ctx) so the leader's applier parents its plan.* spans
        # on it even across the RPC boundary.
        tracer = trace.get_tracer()
        span = tracer.start_span(
            plan.eval_id, "worker.submit_plan",
            parent=trace.current_span() or tracer.root_ctx(plan.eval_id),
        )
        plan.span_ctx = span.ctx()
        try:
            result = self.worker.server.plan_submit(plan)
        finally:
            span.finish()
        telemetry.measure_since(("worker", "submit_plan"), start)

        new_state = None
        if result.refresh_index != 0:
            # Stale data: wait for the log to catch up, then refresh
            # (worker.go:304-322). The wait MUST also cover this plan's
            # own commit (alloc_index): refresh_index alone can be lower,
            # and a worker on a lagging follower would re-snapshot WITHOUT
            # the allocs it just placed — then re-place them. (The chaos
            # test's dominant duplicate-placement mode: partial plan →
            # stale refresh → the remainder solve re-places the whole
            # group.)
            self.worker._wait_for_index(
                max(result.refresh_index, result.alloc_index),
                RAFT_SYNC_LIMIT,
            )
            self.snapshot_index = self.worker.server.raft.applied_index
            new_state = self.worker.server.state_store.snapshot()
        return result, new_state

    def update_eval(self, ev: Evaluation) -> None:
        self.worker.server.eval_upsert([ev])

    def create_eval(self, ev: Evaluation) -> None:
        self.worker.server.eval_upsert([ev])
