"""Generic RPC-tier blocking-query machinery.

The reference's ``blockingRPC`` (/root/reference/nomad/rpc.go:270-335) is a
reusable mechanism any endpoint opts into: register watch items, run the
query, retry until the result index passes the caller's MinQueryIndex or
the timeout lapses. This is that mechanism for our RPC tier; the HTTP tier
long-polls through the same store watch registry.

Fan-out posture (the ~50k-watcher hardening): the watch registry behind
this loop is the coalesced index-bucketed ``state.store._Watch`` —
registration samples bucket generation counters and the writer's notify is
O(touched items) regardless of how many watchers are parked (the old
per-watcher ``Event.set()`` fan-out cost the FSM apply thread O(watchers)
per write; tests/test_wake_storm.py pins the difference). A watcher woken
by a bucket-sharing neighbor simply re-probes its index and re-parks —
the loop below has always tolerated spurious wakes. Registrations are
bounded (``_Watch.max_watchers``, the ``max_blocking_watchers`` server
knob): past the cap ``register`` raises a typed
``RejectError(WATCH_LIMIT)`` which propagates to the RPC/HTTP caller as a
cheap 503-with-retry-after instead of unbounded registry growth.

One subtlety the reference doesn't have: a raft snapshot install rebinds
``fsm.state`` to a fresh StateStore, so the live store must be re-read
every pass and the watch registration raced against the rebind (the old
store fires ``notify_all`` on replacement, and an identity re-check after
registration closes the remaining window).
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Tuple

# Server-side clamp on client-requested waits (rpc.go maxQueryTime analog).
MAX_QUERY_TIME = 10.0


def blocking_query(
    get_store: Callable[[], object],
    items: Callable[[object], Iterable[Tuple[str, str]]],
    run: Callable[[object], Tuple[int, object]],
    min_index: int,
    timeout: float,
    max_timeout: float = MAX_QUERY_TIME,
    index_of: Callable[[object], int] = None,
) -> Tuple[int, object]:
    """Run ``run(store)`` until its index passes ``min_index`` or the
    timeout lapses (rpc.go:270-335 semantics).

    - ``get_store``: returns the CURRENT live store (re-read each pass —
      a snapshot restore rebinds it).
    - ``items``: watch items to park on, given the store.
    - ``run``: executes the query; returns (index, result). The index is
      the query's table/item index (QueryMeta.Index analog).
    - ``min_index`` <= 0 or a fresh-enough index returns immediately.
    - ``index_of``: cheap index-only probe used for the post-registration
      re-check (defaults to running the full query and dropping the
      result).

    Returns the final (index, result) — on timeout, the last read.
    Raises ``structs.RejectError(WATCH_LIMIT)`` when the store's watcher
    cap refuses the registration (typed, retry-after-hinted — never a
    silent park).
    """
    if index_of is None:
        index_of = lambda store: run(store)[0]  # noqa: E731
    timeout = min(timeout, max_timeout)
    end = time.monotonic() + timeout
    while True:
        store = get_store()
        # Index probe first: the full query (which may materialize a large
        # result) runs only when it will actually be returned.
        remaining = end - time.monotonic()
        if index_of(store) > min_index or remaining <= 0:
            return run(store)
        ticket = store.watch.register(list(items(store)))
        try:
            # Identity re-check closes the register-vs-rebind race; a
            # rebind after registration fires notify_all on the old store,
            # so a full-length wait is safe. The index re-check closes the
            # write-between-run-and-register race the same way (the
            # register-then-recheck protocol _Watch's coalesced buckets
            # rely on for their no-lost-wakeup argument).
            if (get_store() is store
                    and index_of(store) <= min_index):
                fired = store.watch.wait(ticket, timeout=remaining)
                if fired and index_of(store) <= min_index:
                    # Bucket-sharing neighbor's publish woke us but our
                    # index never moved: the re-probe-and-re-park cost
                    # the coalesced registry trades for O(items)
                    # publishes. Plain counter; read_observe drains it.
                    store.watch.spurious_wakes += 1
        finally:
            store.watch.unregister(ticket)
