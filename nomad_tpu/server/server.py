"""The server: wires state, broker, plan pipeline, workers, heartbeats.

Reference: /root/reference/nomad/server.go + the RPC endpoint files. This is
the single-process ("DevMode") composition — replication is the synchronous
InProcRaft (the reference's raft.NewInmemStore testing posture,
server.go:420-427); the multi-server layer slots in behind the same
apply/applied_index interface. Endpoint methods carry the semantics of the
net/rpc endpoints (job_endpoint.go, node_endpoint.go, eval_endpoint.go,
plan_endpoint.go) minus the wire format, which lives in nomad_tpu.api.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nomad_tpu import structs
from nomad_tpu.events import EventBroker
from nomad_tpu.server.core_sched import CoreScheduler
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.fsm import FSM, InProcRaft
from nomad_tpu.server.heartbeat import HeartbeatManager
from nomad_tpu.server.plan_pipeline import PlanPipeline
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.server.timetable import TimeTable
from nomad_tpu.server.worker import Worker
from nomad_tpu.structs import (
    CORE_JOB_EVAL_GC,
    CORE_JOB_NODE_GC,
    CORE_JOB_PRIORITY,
    JOB_TYPE_CORE,
    Evaluation,
    Job,
    Node,
    Plan,
    PlanResult,
    generate_uuid,
)


@dataclass
class ServerConfig:
    """Server tunables (reference: nomad/config.go:46-236 defaults)."""

    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = "server-1"
    # Scheduler worker concurrency: N workers evaluate concurrently
    # against delta-rolled snapshots and the plan pipeline resolves
    # their plans optimistically (Omega posture). First-class validated
    # knob — agent config `server { scheduler_workers = N }` with
    # ``num_schedulers`` as the legacy alias; the AGENT layer resolves
    # the two (scheduler_workers preferred) and passes one value down.
    # At THIS constructor a passed num_schedulers wins over
    # scheduler_workers, because None-vs-set is the only explicit signal
    # a dataclass can see — scheduler_workers' default is
    # indistinguishable from an explicit 4.
    scheduler_workers: int = 4
    num_schedulers: Optional[int] = None
    # How many pending plans the pipeline drains and verifies per fused
    # batch pass (plan_pipeline.py). 1 degenerates to the serial applier.
    plan_batch_size: int = 8
    # Seed for the server's name-salted decision-path PRNG streams
    # (broker scheduler choice, heartbeat jitter — nomad_tpu.prng). The
    # simcluster scenario runner stamps its run seed here so replays
    # draw identically.
    seed: int = 0
    enabled_schedulers: List[str] = field(
        default_factory=lambda: [
            structs.JOB_TYPE_SERVICE,
            structs.JOB_TYPE_BATCH,
            structs.JOB_TYPE_SYSTEM,
            JOB_TYPE_CORE,
        ]
    )
    # 'tpu' routes service/batch/system evals to the dense-solve factories;
    # 'host' uses the scalar oracle.
    scheduler_backend: str = "tpu"
    eval_nack_timeout: float = 60.0
    eval_delivery_limit: int = 3
    # Broker-level eval coalescing: each worker drains up to this many
    # ready evals (distinct jobs) per dequeue and runs them concurrently,
    # stacking their device solves into one vmapped dispatch
    # (SURVEY.md §7 "Batched evals"; 1 disables).
    eval_batch_size: int = 4
    eval_gc_interval: float = 300.0
    eval_gc_threshold: float = 3600.0
    node_gc_interval: float = 300.0
    node_gc_threshold: float = 24 * 3600.0
    min_heartbeat_ttl: float = 10.0
    max_heartbeats_per_second: float = 50.0
    failover_heartbeat_ttl: float = 300.0
    periodic_dispatch: bool = False  # GC dispatch loop (leader.go:170-200)
    # Pre-compile the device solve programs for the cluster's shape buckets
    # in the background at start/leader-establish, so a first eval doesn't
    # pay a cold XLA compile against the nack timeout (tpu/solver.py
    # warm_shapes; the worker's nack-touch loop covers the gap meanwhile).
    prewarm_shapes: bool = True
    # Optional TLS on the RPC tier (reference nomad/rpc.go:104-110 rpcTLS
    # + tlsutil): a nomad_tpu.tlsutil.TLSConfig; None runs plaintext.
    tls: object = None
    # Ring size of the cluster event stream (nomad_tpu.events) — the
    # /v1/event/stream resume window. Consumers further behind than this
    # get a truncation marker and must re-list.
    event_buffer_size: int = 2048
    # Declarative latency SLOs (nomad_tpu.slo): objective name ->
    # threshold ms, e.g. {"submit_to_placed_p95_ms": 250}. None = the
    # slo.DEFAULT_OBJECTIVES set; {} disables the monitor entirely.
    slo_objectives: Optional[Dict[str, float]] = None
    # Rolling error-budget window for the SLO burn-rate accounting.
    slo_window_s: float = 3600.0
    # -- admission control & backpressure (nomad_tpu/server/admission.py).
    # Enforced bound on the broker's pending evals (ready + blocked +
    # waiting): the admission front door rejects QUEUE_FULL at it, and
    # the broker itself spills (typed NACK + readmission) past it for
    # internally generated evals. 0 = unbounded (historical posture).
    eval_pending_cap: int = 0
    # Enforced plan-queue depth cap: enqueue past it is a typed
    # PlanQueueError(ERR_QUEUE_FULL) -> worker nack. 0 = unbounded.
    plan_queue_cap: int = 0
    # Bound on blocking-query watcher registrations (state store + event
    # stream): past it register raises RejectError(WATCH_LIMIT) -> fast
    # 503 instead of unbounded registry growth. 0 = unbounded.
    max_blocking_watchers: int = 0
    # Admission front-door spec (AdmissionConfig.parse mapping): per-
    # client token-bucket rate lanes + SLO-coupled shedding. None =
    # permissive defaults (admit everything — decision-invariant).
    admission: Optional[Dict] = None
    # Express placement lane spec (ExpressConfig.parse mapping,
    # nomad_tpu/server/express.py): leader-local sub-millisecond
    # placement of express-eligible batch jobs under leased capacity
    # reservations. None = lane OFF (decision-invariant: the banked
    # steady-10k digests pin that default).
    express: Optional[Dict] = None
    # Capacity observatory spec (CapacityConfig.parse mapping,
    # nomad_tpu/capacity.py): the read-only accountant behind
    # /v1/agent/capacity — fragmentation, per-lane usage, stranded-
    # capacity %. None = defaults (enabled; decision-invariant by
    # construction, pinned by the churn-fragmentation contrast arm).
    capacity: Optional[Dict] = None
    # Raft & recovery observatory spec (RaftObserveConfig.parse mapping,
    # nomad_tpu/raft_observe.py): the read-only observer behind
    # /v1/agent/raft — write-path stage attribution per msg_type,
    # follower lag, log/snapshot economy, restart-replay timeline.
    # None = defaults (enabled; decision-invariant by construction: the
    # observer drains bounded books the raft node keeps as plain data).
    raft_observe: Optional[Dict] = None
    # Read-path observatory spec (ReadObserveConfig.parse mapping,
    # nomad_tpu/read_observe.py): the read-only observer behind
    # /v1/agent/reads — per-route serving attribution, the blocking
    # hold/serve partition, SSE session books, watch-registry wake
    # economy, response-staleness distribution. None = defaults
    # (enabled; decision-invariant by construction: the HTTP layer
    # writes plain books, nothing feeds back — pinned by the read-storm
    # contrast arm).
    reads: Optional[Dict] = None
    # Follower read plane spec (ReadPathConfig.parse mapping,
    # nomad_tpu/server/read_path.py): consistency-tiered read serving —
    # the stale lane's staleness-bound enforcement, the linearizable
    # lane's read-index/lease confirmation, per-(role, lane) serve
    # books. None = defaults (enabled). Decision scope: this is a
    # SERVING path (it refuses requests), not an observatory — but it is
    # read-decision-invariant for the write path: no lane ever touches
    # the log beyond the once-per-term barrier no-op, pinned by the
    # read-storm digest equality.
    read_path: Optional[Dict] = None
    # Runtime self-observatory spec (ProfileObserveConfig.parse mapping,
    # nomad_tpu/profile_observe.py): the read-only observer behind
    # /v1/agent/profile and /v1/agent/runtime — continuous stack-
    # sampling profiler (seeded-jittered cadence, thread-role wall
    # shares, flamegraph exports), lock-contention table (read from the
    # installed telemetry.LockWatchdog), and the byte-economy ledger
    # with the measured-per-row 1M-node mirror projection. None =
    # defaults (enabled; decision-invariant by construction: it samples
    # frames and reads array metadata, nothing feeds back — pinned by
    # the steady-10k profiler-off contrast arm).
    profile: Optional[Dict] = None
    # Solver mesh spec (SolverMeshConfig.parse mapping,
    # nomad_tpu/parallel/mesh.py): shard the node axis of every device
    # solve (and the mirror's padded buffers) over a JAX device mesh —
    # `{node_shards: N, eval_parallel: M}`. None/default = single-device
    # (decision-invariant: sharded solves are fuzz-pinned identical, the
    # knob only moves where the flops run). Applied at start with a
    # transparent single-device fallback when the local device set can't
    # satisfy the extents.
    solver_mesh: Optional[Dict] = None

    def __post_init__(self) -> None:
        if self.num_schedulers is not None:
            self.scheduler_workers = self.num_schedulers
        # Both spellings read the same resolved value afterwards.
        self.num_schedulers = self.scheduler_workers
        if (not isinstance(self.scheduler_workers, int)
                or isinstance(self.scheduler_workers, bool)
                or not 0 <= self.scheduler_workers <= 128):
            raise ValueError(
                "scheduler_workers must be an integer in [0, 128], got "
                f"{self.scheduler_workers!r}"
            )
        if (not isinstance(self.plan_batch_size, int)
                or isinstance(self.plan_batch_size, bool)
                or not 1 <= self.plan_batch_size <= 256):
            raise ValueError(
                "plan_batch_size must be an integer in [1, 256], got "
                f"{self.plan_batch_size!r}"
            )
        for knob in ("eval_pending_cap", "plan_queue_cap",
                     "max_blocking_watchers"):
            v = getattr(self, knob)
            if (not isinstance(v, int) or isinstance(v, bool)
                    or not 0 <= v <= 10_000_000):
                raise ValueError(
                    f"{knob} must be an integer in [0, 10000000], got {v!r}"
                )
        # Parse-time validation of the admission block (typo'd keys and
        # out-of-range values fail config load, like scheduler_workers);
        # the parsed config is what Server consumes.
        from nomad_tpu.server.admission import AdmissionConfig

        self.admission_config = AdmissionConfig.parse(self.admission)
        from nomad_tpu.server.express import ExpressConfig

        self.express_config = ExpressConfig.parse(self.express)
        from nomad_tpu.capacity import CapacityConfig

        self.capacity_config = CapacityConfig.parse(self.capacity)
        from nomad_tpu.raft_observe import RaftObserveConfig

        self.raft_observe_config = RaftObserveConfig.parse(self.raft_observe)
        from nomad_tpu.read_observe import ReadObserveConfig

        self.reads_config = ReadObserveConfig.parse(self.reads)
        from nomad_tpu.server.read_path import ReadPathConfig

        self.read_path_config = ReadPathConfig.parse(self.read_path)
        from nomad_tpu.profile_observe import ProfileObserveConfig

        self.profile_config = ProfileObserveConfig.parse(self.profile)
        from nomad_tpu.parallel.mesh import SolverMeshConfig

        self.solver_mesh_config = SolverMeshConfig.parse(self.solver_mesh)

    def scheduler_factory(self, eval_type: str) -> str:
        if self.scheduler_backend == "tpu" and eval_type in (
            structs.JOB_TYPE_SERVICE,
            structs.JOB_TYPE_BATCH,
            structs.JOB_TYPE_SYSTEM,
        ):
            return f"tpu-{eval_type}"
        return eval_type


class Server:
    """Single-process scheduling brain (reference: nomad/server.go:57-230,
    leader lifecycle at nomad/leader.go:99-140)."""

    def __init__(self, config: Optional[ServerConfig] = None,
                 logger: Optional[logging.Logger] = None):
        self.config = config or ServerConfig()
        self.logger = logger or logging.getLogger("nomad_tpu.server")

        self.eval_broker = EvalBroker(
            self.config.eval_nack_timeout, self.config.eval_delivery_limit,
            seed=self.config.seed,
            pending_cap=self.config.eval_pending_cap,
        )
        self.fsm = FSM(
            eval_broker=self.eval_broker, logger=self.logger,
            events=EventBroker(capacity=self.config.event_buffer_size,
                               emitter=self.config.node_name),
        )
        # Bounded blocking-query fan-out: the watcher-registration caps
        # ride the watch registries themselves (typed WATCH_LIMIT
        # rejection past them, server/blocking.py).
        if self.config.max_blocking_watchers:
            self.fsm.state.watch.max_watchers = \
                self.config.max_blocking_watchers
            self.fsm.events.watch.max_watchers = \
                self.config.max_blocking_watchers
        self.raft = InProcRaft(self.fsm)
        self.plan_queue = PlanQueue(max_depth=self.config.plan_queue_cap)
        self.time_table = TimeTable()
        self.heartbeat = HeartbeatManager(self)
        self.plan_applier = PlanPipeline(
            self.plan_queue, self.eval_broker, self.raft, self.fsm,
            self.logger, max_batch=self.config.plan_batch_size,
        )
        self.workers: List[Worker] = []
        # Live SLO accounting over this server's own event stream
        # (nomad_tpu.slo; /v1/agent/slo). An empty objectives dict opts
        # out; None means the default objective set. Read-only on
        # decisions: the monitor is an event-ring consumer.
        self.slo_monitor: Optional[object] = None
        if self.config.slo_objectives is None or self.config.slo_objectives:
            from nomad_tpu.slo import EXPRESS_OBJECTIVES, SLOMonitor

            objectives = self.config.slo_objectives
            if objectives is None and self.config.express_config.enabled:
                # Default objective set + the express lane's own target:
                # an enabled lane is judged (express_placed_p50_ms)
                # without the operator re-spelling the defaults.
                from nomad_tpu.slo import DEFAULT_OBJECTIVES

                objectives = {**DEFAULT_OBJECTIVES, **EXPRESS_OBJECTIVES}
            self.slo_monitor = SLOMonitor(
                self.fsm.events, objectives,
                window_s=self.config.slo_window_s,
            )
        # The bounded front door (server/admission.py): consulted by
        # job_register/job_evaluate BEFORE any raft apply. Default-
        # permissive — with no caps/rates configured it admits on a
        # no-lock fast path (decision-invariant with the banked digests).
        from nomad_tpu.server.admission import AdmissionController

        monitor = self.slo_monitor
        self.admission = AdmissionController(
            self.config.admission_config,
            seed=self.config.seed,
            queue_depth=self.eval_broker.pending_total,
            queue_cap=self.config.eval_pending_cap,
            burn_rate=(monitor.burn_rate if monitor is not None
                       else None),
            events=self.fsm.events,
        )
        # The express placement lane (server/express.py): constructed
        # always (exposition/stats answer lane-off too), active only
        # when configured. The plan pipeline verifies under the lane's
        # reservation ledger iff the lane is ON — a None ledger keeps
        # the verifier bit-identical to the pre-express posture.
        from nomad_tpu.server.express import ExpressLane

        self.express_lane = ExpressLane(self, self.config.express_config)
        if self.config.express_config.enabled:
            self.plan_applier.ledger = self.express_lane.ledger
        # The capacity observatory (nomad_tpu/capacity.py): a read-only
        # consumer of the state store's change logs, composed HERE and
        # only here — decision-path modules are statically barred from
        # importing it (nomadlint OBS001). The store getter re-reads
        # fsm.state per poll so a raft snapshot install (which rebinds
        # the store) rolls into a counted full rebuild, never a stale
        # view.
        from nomad_tpu.capacity import CapacityAccountant

        self.capacity_accountant = CapacityAccountant(
            lambda: self.fsm.state,
            self.config.capacity_config,
            events=self.fsm.events,
        )
        # The raft & recovery observatory (nomad_tpu/raft_observe.py):
        # drains the bounded write-path/log/recovery books the raft node
        # keeps as plain data. Composed HERE and only here — the same
        # OBS001 composition-root contract as the capacity accountant.
        # The raft getter re-reads self.raft per poll: ClusterServer
        # swaps InProcRaft for a RaftNode after this constructor runs.
        from nomad_tpu.raft_observe import RaftObservatory

        self.raft_observatory = RaftObservatory(
            lambda: self.raft,
            self.config.raft_observe_config,
            events=self.fsm.events,
            fsm_getter=lambda: self.fsm,
        )
        # The read-path observatory (nomad_tpu/read_observe.py): owns
        # the recorder the HTTP exposition layer writes per-request
        # books into, and samples the watch registries' plain wake-
        # economy counters. Same OBS001 composition-root contract; the
        # getters re-read per poll (snapshot installs rebind fsm.state,
        # ClusterServer swaps the raft node).
        from nomad_tpu.read_observe import ReadObservatory

        self.read_observatory = ReadObservatory(
            lambda: self.fsm.state,
            lambda: self.raft,
            self.config.reads_config,
            events=self.fsm.events,
        )
        # The follower read plane (server/read_path.py): consistency-
        # lane resolution for every HTTP read — stale-bound enforcement,
        # linearizable read-index confirmation, per-(role, lane) serve
        # books. A serving-path component (not an observatory): it can
        # refuse a request, so it lives with the server, and it re-reads
        # self.raft per request (ClusterServer swaps in a RaftNode).
        from nomad_tpu.server.read_path import ReadPath

        self.read_path = ReadPath(self, self.config.read_path_config)
        # The runtime self-observatory (nomad_tpu/profile_observe.py):
        # stack-sampling profiler + lock-contention table + byte-economy
        # ledger. Same OBS001 composition-root contract. The ring/table
        # getters re-read the live handles per poll so restarts and
        # snapshot installs never leave it holding a dead object.
        from nomad_tpu.profile_observe import RuntimeObservatory

        self.runtime_observatory = RuntimeObservatory(
            self.config.profile_config,
            events=self.fsm.events,
            store_getter=lambda: self.fsm.state,
            rings_getter=self._runtime_rings,
            tables_getter=self._runtime_tables,
        )
        self._periodic_stop = threading.Event()
        self._started = False

    def _runtime_rings(self):
        """The bounded rings the byte-economy ledger accounts: event
        broker, trace ring, admission decision ring, express
        pending/outcome queues, plan-pipeline commit log. getattr-
        guarded — a ring that doesn't exist on this composition simply
        doesn't appear in the ledger."""
        from nomad_tpu import trace

        return {
            "events": getattr(self.fsm.events, "_events", None),
            "traces": getattr(trace.get_tracer(), "_traces", None),
            "admission_decisions": getattr(
                self.admission, "_decisions", None),
            "express_pending": getattr(
                self.express_lane, "_pending", None),
            "express_outcomes": getattr(
                self.express_lane, "_outcomes", None),
            "plan_commit_log": getattr(
                self.plan_applier, "_commit_log", None),
        }

    def _runtime_tables(self):
        """The sibling observatories' in-memory books, approximated via
        their summary views (deep-sized by the ledger) — the 'what does
        watching cost' line of the byte economy."""
        out = {}
        if self.config.capacity_config.enabled:
            out["capacity"] = self.capacity_accountant.snapshot()
        if self.config.raft_observe_config.enabled:
            out["raft_observe"] = self.raft_observatory.snapshot()
        if self.config.reads_config.enabled:
            out["read_observe"] = self.read_observatory.snapshot()
        return out

    @property
    def plan_pipeline(self) -> PlanPipeline:
        """The optimistic batch applier (``plan_applier`` is the legacy
        spelling kept for the reference's naming)."""
        return self.plan_applier

    @property
    def state_store(self):
        return self.fsm.state

    # -- lifecycle (leader.go:99-140 establishLeadership) -------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._apply_solver_mesh()
        self.plan_queue.set_enabled(True)
        self.eval_broker.set_enabled(True)
        self.plan_applier.start()
        if self.slo_monitor is not None:
            self.slo_monitor.start()
        self.express_lane.start()
        self.capacity_accountant.start()
        self.raft_observatory.start()
        self.read_observatory.start()
        self.runtime_observatory.start()
        self.restore_eval_broker()
        for i in range(self.config.scheduler_workers):
            worker = Worker(self, i)
            worker.start()
            self.workers.append(worker)
        if self.config.periodic_dispatch:
            t = threading.Thread(
                target=self._periodic_dispatcher, daemon=True,
                name="periodic-gc",
            )
            t.start()
        reaper = threading.Thread(
            target=self._reap_failed_evaluations, daemon=True,
            name="failed-eval-reaper",
        )
        reaper.start()
        self._start_readmission()
        emitter = threading.Thread(
            target=self._emit_stats, daemon=True, name="stats-emitter",
        )
        emitter.start()
        if self.config.prewarm_shapes and self.config.scheduler_backend == "tpu":
            warmer = threading.Thread(
                target=self._prewarm_solver, daemon=True, name="shape-warmer",
            )
            warmer.start()

    def _apply_solver_mesh(self) -> None:
        """Configure the process solve mesh from `server { solver_mesh }`
        BEFORE any worker can build a mirror: node tensors are born with
        the configured sharding (mirror.put_node_sharded), so ordering is
        what keeps the warm path reshard-free. Transparent fallback on a
        box that can't satisfy the extents. Shared by Server.start and
        ClusterServer.start so the gating can never drift."""
        if (self.config.solver_mesh_config.enabled
                and self.config.scheduler_backend == "tpu"):
            from nomad_tpu.parallel import mesh as mesh_lib

            mesh_lib.apply_solver_mesh(
                self.config.solver_mesh_config, self.logger
            )

    def _prewarm_solver(self) -> None:
        """Background shape-bucket pre-compile (see ServerConfig
        .prewarm_shapes). Waits for device acquisition, then re-warms
        whenever the cluster's node-bucket signature changes — a fresh
        cluster warms as soon as nodes register, and growth into a larger
        padded bucket triggers a new compile before an eval needs it. A
        host-only deployment simply never warms."""
        from nomad_tpu.ops.binpack import bucket
        from nomad_tpu.scheduler import wait_for_device

        solver = wait_for_device(timeout=600.0, logger=self.logger)
        if solver is None:
            return
        warmed_sig = None
        while not self._periodic_stop.is_set():
            snap = self.state_store.snapshot()
            nodes = [
                n for n in snap.nodes()
                if n.status == structs.NODE_STATUS_READY and not n.drain
            ]
            per_dc: Dict[str, int] = {}
            for n in nodes:
                per_dc[n.datacenter] = per_dc.get(n.datacenter, 0) + 1
            sig = (
                bucket(len(nodes)) if nodes else 0,
                tuple(sorted(bucket(c) for c in per_dc.values())),
            )
            if nodes and sig != warmed_sig:
                try:
                    solver.warm_shapes(
                        snap, logger=self.logger,
                        stop=self._periodic_stop.is_set,
                    )
                    warmed_sig = sig
                except Exception:
                    self.logger.exception("shape prewarm failed")
            self._periodic_stop.wait(5.0)

    def shutdown(self) -> None:
        self._periodic_stop.set()
        for worker in self.workers:
            worker.stop()
        self.express_lane.stop()
        self.capacity_accountant.stop()
        self.raft_observatory.stop()
        self.read_observatory.stop()
        self.runtime_observatory.stop()
        if self.slo_monitor is not None:
            self.slo_monitor.stop()
        self.plan_applier.stop()
        self.plan_queue.set_enabled(False)
        self.eval_broker.set_enabled(False)
        self.heartbeat.clear_all()

    def _emit_stats(self) -> None:
        """Periodic telemetry gauges at 1 Hz (server.go:213-228 EmitStats ->
        eval_broker.go:557-575, plan_queue.go:198-209, heartbeat.go:135-148)."""
        from nomad_tpu import telemetry

        while not self._periodic_stop.wait(1.0):
            broker = self.eval_broker.snapshot_stats()
            telemetry.set_gauge(
                ("broker", "total_ready"), broker.total_ready
            )
            telemetry.set_gauge(
                ("broker", "total_unacked"), broker.total_unacked
            )
            telemetry.set_gauge(
                ("broker", "total_blocked"), broker.total_blocked
            )
            telemetry.set_gauge(
                ("broker", "total_waiting"), broker.total_waiting
            )
            for queue, stats in broker.by_scheduler.items():
                telemetry.set_gauge(
                    ("broker", queue, "ready"), stats.ready
                )
                telemetry.set_gauge(
                    ("broker", queue, "unacked"), stats.unacked
                )
            # The ONE plan.queue_depth writer: a periodic gauge keeps the
            # series present in every retained interval (an event-driven
            # write would vanish from the exposition after 60s of queue
            # inactivity, breaking absent()-style alerts).
            telemetry.set_gauge(
                ("plan", "queue_depth"), self.plan_queue.depth()
            )
            # Worker concurrency + pipeline batch ceiling: the two knobs
            # whose product bounds optimistic-apply parallelism; gauged
            # so the exposition names the posture a conflict-rate curve
            # was measured under.
            telemetry.set_gauge(
                ("worker", "concurrency"),
                sum(1 for w in self.workers if w.is_alive()),
            )
            telemetry.set_gauge(
                ("plan", "pipeline_batch_max"), self.plan_applier.max_batch
            )
            telemetry.set_gauge(
                ("heartbeat", "active"), self.heartbeat.num_timers()
            )
            # Blocking-query fan-out health: parked watcher counts and
            # typed WATCH_LIMIT rejections per registry (store + event
            # stream) — the 50k-watcher story's live gauges.
            for name, registry in (("state", self.state_store.watch),
                                   ("events", self.fsm.events.watch)):
                wstats = registry.stats()
                telemetry.set_gauge(
                    ("blocking", name, "watchers"), wstats["watchers"]
                )
                telemetry.set_gauge(
                    ("blocking", name, "watch_rejected"),
                    wstats["rejected"],
                )
            solver = self.solver_stats()
            device = solver.get("device", {})
            # probe state as a numeric gauge: 1 ready / 0 probing-unprobed /
            # -1 down — alertable without string handling
            state_num = {"ready": 1, "down": -1}.get(
                str(device.get("status")), 0
            )
            telemetry.set_gauge(("scheduler", "device", "state"), state_num)
            telemetry.set_gauge(
                ("scheduler", "device", "fallbacks"),
                float(device.get("fallbacks", 0)),
            )

    def restore_eval_broker(self) -> None:
        """Re-enqueue non-terminal evals after (re)gaining leadership
        (leader.go:142-168). wait_index = the post-barrier applied index:
        an earlier delivery of a restored eval may have committed a plan
        right before the previous leader died, and the next worker's
        snapshot must contain that plan or the eval gets placed twice."""
        from nomad_tpu.server.eval_broker import BrokerFullError

        wait_index = self.raft.applied_index
        for ev in self.state_store.evals():
            if ev.should_enqueue():
                try:
                    self.eval_broker.enqueue(ev, wait_index=wait_index)
                except BrokerFullError:
                    # Cap reached mid-restore: the rest stays durable in
                    # state; the readmission loop drains it as capacity
                    # frees (the spill flag is already set).
                    break

    def _start_readmission(self) -> None:
        """Arm the spill-readmission loop iff the broker is bounded (an
        unbounded broker never spills; the thread would idle forever).
        Shared by Server.start and ClusterServer.start."""
        if not self.config.eval_pending_cap:
            return
        threading.Thread(
            target=self._readmission_loop, daemon=True,
            name="eval-readmit",
        ).start()

    def _readmission_loop(self) -> None:
        """Drain spilled evals back into the bounded broker as capacity
        frees. Spilling (eval_broker.pending_cap) keeps over-cap evals
        durable in the state store only; this loop is the other half of
        that contract — without it a spilled eval would be stuck pending
        forever. Polling is cheap: the broker hands out one True per
        spill episode (reclaim_spilled), so the state scan runs only
        when there is actually something to readmit."""
        from nomad_tpu import telemetry
        from nomad_tpu.server.eval_broker import BrokerError, BrokerFullError

        while not self._periodic_stop.wait(0.5):
            if not self.eval_broker.reclaim_spilled():
                continue
            wait_index = self.raft.applied_index
            pending = [ev for ev in self.state_store.evals()
                       if ev.should_enqueue()]
            # Highest priority first, then oldest — the order the broker
            # itself would have served them in.
            pending.sort(key=lambda e: (-e.priority, e.create_index, e.id))
            readmitted = 0
            for ev in pending:
                try:
                    self.eval_broker.enqueue(
                        ev, wait_index=wait_index)
                    readmitted += 1
                except BrokerFullError:
                    break  # flag re-armed by the broker; next episode
                except BrokerError:
                    break  # disabled (leadership lost) — moot
            if readmitted:
                telemetry.incr_counter(("broker", "readmitted"), readmitted)
                self.logger.debug(
                    "readmitted %d spilled evals", readmitted)

    def _periodic_dispatcher(self) -> None:
        """Dispatch GC core evals periodically (leader.go:170-200)."""
        import time as _time

        last_eval_gc = last_node_gc = _time.monotonic()
        while not self._periodic_stop.wait(1.0):
            now = _time.monotonic()
            self.time_table.witness(self.raft.applied_index)
            if now - last_eval_gc >= self.config.eval_gc_interval:
                self._dispatch_core_job(CORE_JOB_EVAL_GC)
                last_eval_gc = now
            if now - last_node_gc >= self.config.node_gc_interval:
                self._dispatch_core_job(CORE_JOB_NODE_GC)
                last_node_gc = now

    def _reap_failed_evaluations(self) -> None:
        """Drain the broker's _failed queue: mark the eval failed through the
        log and ack it so the job's blocked evals unwedge
        (reference: leader.go:202-238)."""
        from nomad_tpu.server.eval_broker import FAILED_QUEUE, BrokerError

        while not self._periodic_stop.is_set():
            try:
                ev, token = self.eval_broker.dequeue([FAILED_QUEUE], timeout=0.5)
            except BrokerError:
                if self._periodic_stop.wait(0.2):
                    return
                continue
            if ev is None:
                continue
            self.logger.warning("failed evaluation %s reached delivery limit, marking as failed", ev.id)
            new_eval = ev.copy()
            new_eval.status = structs.EVAL_STATUS_FAILED
            new_eval.status_description = (
                f"evaluation reached delivery limit "
                f"({self.config.eval_delivery_limit})"
            )
            try:
                self.eval_upsert([new_eval])
                self.eval_broker.ack(ev.id, token)
            except Exception:
                self.logger.exception("failed to reap evaluation %s", ev.id)

    def _dispatch_core_job(self, job_id: str) -> None:
        from nomad_tpu.server.eval_broker import BrokerFullError

        ev = Evaluation(
            id=generate_uuid(),
            priority=CORE_JOB_PRIORITY,
            type=JOB_TYPE_CORE,
            triggered_by=structs.EVAL_TRIGGER_SCHEDULED,
            job_id=job_id,
            status=structs.EVAL_STATUS_PENDING,
        )
        try:
            self.eval_broker.enqueue(ev)
        except BrokerFullError:
            # GC is periodic: the next tick retries after the overload
            # passes; the breach itself is already counted by the broker.
            self.logger.debug("core job %s dispatch spilled at cap", job_id)

    # -- Job endpoint (job_endpoint.go) -------------------------------------

    def job_register(self, job: Job, client_id: str = "") -> Tuple[str, int]:
        """Register/update a job and create its evaluation
        (job_endpoint.go:18-72). Returns (eval_id, index).

        The admission front door is checked FIRST — before validation
        even, so an overload rejection stays cheap — and before any raft
        apply, so a raised RejectError proves zero side effects (the
        typed-retry safety contract)."""
        self.admission.admit_job(job, client_id)
        job.validate()
        if job.type == JOB_TYPE_CORE:
            raise ValueError("job type cannot be core")
        # Express lane (server/express.py): an eligible job places
        # synchronously against the leader's mirror under a leased
        # reservation — no broker, no worker, no plan queue on the
        # submit path; the raft entry commits asynchronously. None =
        # ineligible or the lane declined (capacity, backlog): take the
        # ordinary path below.
        express = self.express_lane.submit(job, client_id)
        if express is not None:
            return express
        # A same-id EXPRESS submission may still be mid-async-commit
        # (this one was ineligible or declined): wait it out so the
        # scheduler's snapshot contains its allocations — registering
        # over an uncommitted express entry would double-place the job.
        # A commit stalled past the wait is a typed capacity rejection,
        # not a green light: nothing has been applied yet, so the
        # client's replay-after-hint stays safe.
        if not self.express_lane.await_inflight(job.id):
            raise structs.RejectError(
                structs.REJECT_QUEUE_FULL,
                f"express commit for job {job.id} still in flight",
                retry_after=1.0,
            )
        index = self.raft.apply("job_register", {"job": job}).result()

        ev = Evaluation(
            id=generate_uuid(),
            priority=job.priority,
            type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            job_modify_index=index,
            status=structs.EVAL_STATUS_PENDING,
        )
        eval_index = self.eval_upsert([ev])
        return ev.id, eval_index

    def job_evaluate(self, job_id: str, client_id: str = "") -> Tuple[str, int]:
        """Force re-evaluation (job_endpoint.go:75-128). Eval ingress is
        admission-gated like registration (same front door, same typed
        rejection)."""
        job = self.state_store.job_by_id(job_id)
        if job is None:
            raise KeyError("job not found")
        self.admission.admit_job(job, client_id)
        ev = Evaluation(
            id=generate_uuid(),
            priority=job.priority,
            type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            job_modify_index=job.modify_index,
            status=structs.EVAL_STATUS_PENDING,
        )
        index = self.eval_upsert([ev])
        return ev.id, index

    def job_deregister(self, job_id: str) -> Tuple[str, int]:
        """Remove a job and evaluate the teardown
        (job_endpoint.go:130-183)."""
        # Same guard as registration: a deregister racing an in-flight
        # express commit would otherwise no-op against absent state and
        # then watch the committer resurrect the job (or strand its
        # allocations) after the "successful" removal.
        if not self.express_lane.await_inflight(job_id):
            raise structs.RejectError(
                structs.REJECT_QUEUE_FULL,
                f"express commit for job {job_id} still in flight",
                retry_after=1.0,
            )
        job = self.state_store.job_by_id(job_id)
        index = self.raft.apply("job_deregister", {"job_id": job_id}).result()

        priority = job.priority if job else structs.JOB_DEFAULT_PRIORITY
        jtype = job.type if job else structs.JOB_TYPE_SERVICE
        ev = Evaluation(
            id=generate_uuid(),
            priority=priority,
            type=jtype,
            triggered_by=structs.EVAL_TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            job_modify_index=index,
            status=structs.EVAL_STATUS_PENDING,
        )
        eval_index = self.eval_upsert([ev])
        return ev.id, eval_index

    # -- Node endpoint (node_endpoint.go) ------------------------------------

    @staticmethod
    def _validate_registration(node: Node) -> None:
        """Shared by the single and batch registration paths — a check
        added to one must hold on both or invalid nodes reach the raft
        log through whichever path drifted."""
        if not node.id:
            raise ValueError("missing node ID for client registration")
        if not node.datacenter:
            raise ValueError("missing datacenter for client registration")
        if not node.name:
            raise ValueError("missing node name for client registration")
        if not node.status:
            node.status = structs.NODE_STATUS_INIT
        if not structs.valid_node_status(node.status):
            raise ValueError("invalid status for node")

    def node_register(self, node: Node) -> Dict:
        """node_endpoint.go:18-80"""
        self._validate_registration(node)

        index = self.raft.apply("node_register", {"node": node}).result()

        reply: Dict = {"node_modify_index": index, "index": index, "eval_ids": []}
        if structs.should_drain_node(node.status):
            reply["eval_ids"], reply["eval_create_index"] = self.create_node_evals(
                node.id, index
            )
        if not node.terminal_status():
            reply["heartbeat_ttl"] = self.heartbeat.reset_heartbeat_timer(node.id)
        return reply

    def node_batch_register(self, nodes: List[Node]) -> Dict:
        """Bulk registration: one raft entry and one batched heartbeat arm
        for a whole tranche of nodes. The RPC-tier enabler for a 10k-node
        fleet (nomad_tpu/simcluster): per-node Node.Register would cost
        10k raft applies and 10k timer-arm lock hops. Semantics per node
        match node_register minus the drain-eval fan-out (batch
        registration is for fresh, non-draining fleets; a draining node
        must register individually)."""
        if not nodes:
            return {"index": 0, "heartbeat_ttls": {}}
        for node in nodes:
            self._validate_registration(node)
            if structs.should_drain_node(node.status):
                raise ValueError(
                    "batch registration only accepts init/ready nodes"
                )
        index = self.raft.apply(
            "node_batch_register", {"nodes": nodes}
        ).result()
        # Every node is init/ready here (validated above), so all get TTLs.
        ttls = self.heartbeat.reset_many([n.id for n in nodes])
        return {"index": index, "heartbeat_ttls": ttls}

    def node_batch_heartbeat(self, node_ids: List[str]) -> Dict:
        """Batched TTL renewal: equivalent to N node_heartbeat calls for
        already-ready nodes, under one heartbeat-manager lock hold. Nodes
        that are unknown get ttl 0.0 (the client re-registers); nodes in a
        non-ready state fall back to the full node_update_status path so
        the down->ready transition evals still fan out."""
        snap = self.state_store.snapshot()
        renew: List[str] = []
        out: Dict[str, float] = {}
        for node_id in node_ids:
            node = snap.node_by_id(node_id)
            if node is None:
                out[node_id] = 0.0
            elif node.status == structs.NODE_STATUS_READY:
                renew.append(node_id)
            else:
                # Per-node isolation: the snapshot is stale, and a node
                # deregistered since (KeyError from the live-store
                # re-read) must cost THAT node its renewal, not the
                # whole tranche — the batch path would otherwise amplify
                # one racing failure to batch_size nodes' TTLs.
                try:
                    out[node_id] = self.node_update_status(
                        node_id, structs.NODE_STATUS_READY
                    ).get("heartbeat_ttl", 0.0)
                except (KeyError, ValueError):
                    out[node_id] = 0.0
        if renew:
            out.update(self.heartbeat.reset_many(renew))
        return {"heartbeat_ttls": out}

    def node_deregister(self, node_id: str) -> Dict:
        """node_endpoint.go:82-117"""
        index = self.raft.apply("node_deregister", {"node_id": node_id}).result()
        self.heartbeat.clear_heartbeat_timer(node_id)
        eval_ids, eval_index = self.create_node_evals(node_id, index)
        return {
            "eval_ids": eval_ids,
            "eval_create_index": eval_index,
            "node_modify_index": index,
            "index": index,
        }

    def node_update_status(self, node_id: str, status: str) -> Dict:
        """node_endpoint.go:119-184"""
        if not structs.valid_node_status(status):
            raise ValueError("invalid status for node")
        node = self.state_store.node_by_id(node_id)
        if node is None:
            raise KeyError("node not found")

        index = node.modify_index
        if node.status != status:
            index = self.raft.apply(
                "node_status_update", {"node_id": node_id, "status": status}
            ).result()

        reply: Dict = {"node_modify_index": index, "index": index, "eval_ids": []}
        transition_to_ready = (
            node.status in (structs.NODE_STATUS_INIT, structs.NODE_STATUS_DOWN)
            and status == structs.NODE_STATUS_READY
        )
        if structs.should_drain_node(status) or transition_to_ready:
            reply["eval_ids"], reply["eval_create_index"] = self.create_node_evals(
                node_id, index
            )
        if status != structs.NODE_STATUS_DOWN:
            reply["heartbeat_ttl"] = self.heartbeat.reset_heartbeat_timer(node_id)
        return reply

    def node_update_drain(self, node_id: str, drain: bool) -> Dict:
        """node_endpoint.go:187-238"""
        node = self.state_store.node_by_id(node_id)
        if node is None:
            raise KeyError("node not found")
        index = node.modify_index
        if node.drain != drain:
            index = self.raft.apply(
                "node_drain_update", {"node_id": node_id, "drain": drain}
            ).result()
        reply: Dict = {"node_modify_index": index, "index": index, "eval_ids": []}
        if drain:
            reply["eval_ids"], reply["eval_create_index"] = self.create_node_evals(
                node_id, index
            )
        return reply

    def node_evaluate(self, node_id: str) -> Dict:
        """Force re-evaluation of a node (node_endpoint.go:240-280)."""
        node = self.state_store.node_by_id(node_id)
        if node is None:
            raise KeyError("node not found")
        eval_ids, eval_index = self.create_node_evals(node_id, node.modify_index)
        return {"eval_ids": eval_ids, "eval_create_index": eval_index,
                "index": eval_index}

    def node_heartbeat(self, node_id: str) -> float:
        """Client TTL renewal via Node.UpdateStatus(ready) in the reference;
        exposed directly for the client loop."""
        return self.node_update_status(node_id, structs.NODE_STATUS_READY).get(
            "heartbeat_ttl", 0.0
        )

    def update_allocs_from_client(self, allocs: List) -> int:
        """node_endpoint.go:385-457 (Node.UpdateAlloc)"""
        return self.raft.apply("alloc_client_update", {"allocs": allocs}).result()

    def node_batch_expire(self, node_ids: List[str]) -> Dict:
        """Mass TTL expiry (the heartbeat wheel's batch path): mark every
        node down and fan out the re-placement evaluations in ONE
        eval_upsert / broker enqueue instead of a per-node storm. Per-node
        semantics stay IDENTICAL to node_update_status(down) +
        create_node_evals: same per-node status applies (pipelined rather
        than serialized), same per-node eval fan-out with NO cross-node
        dedup — which nodes die in the same wheel pass is timing, and a
        node's eval set must not depend on it."""
        status = structs.NODE_STATUS_DOWN
        staged: List[Tuple[str, object, int]] = []
        for node_id in node_ids:
            node = self.state_store.node_by_id(node_id)
            if node is None:
                continue
            if node.status != status:
                fut = self.raft.apply(
                    "node_status_update",
                    {"node_id": node_id, "status": status},
                )
                staged.append((node_id, fut, 0))
            else:
                staged.append((node_id, None, node.modify_index))
        settled: List[Tuple[str, int]] = []
        for node_id, fut, index in staged:
            if fut is not None:
                index = fut.result()
            settled.append((node_id, index))
        # One snapshot for the whole batch: every status apply above has
        # committed, and the fan-out reads only allocs-by-node + system
        # jobs, which those applies don't change.
        snap = self.state_store.snapshot()
        evals: List[Evaluation] = []
        reply: Dict = {"eval_ids": [], "nodes": len(settled)}
        for node_id, node_index in settled:
            evals.extend(self._node_eval_fanout(snap, node_id, node_index))
        if evals:
            reply["eval_create_index"] = self.eval_upsert(evals)
            reply["eval_ids"] = [e.id for e in evals]
        return reply

    def create_node_evals(self, node_id: str, node_index: int) -> Tuple[List[str], int]:
        """Fan out node-update evals: one per job with allocs on the node,
        plus every system job (node_endpoint.go:459-551)."""
        snap = self.state_store.snapshot()
        if (not snap.allocs_by_node(node_id)
                and not snap.jobs_by_scheduler(structs.JOB_TYPE_SYSTEM)):
            return [], 0
        evals = self._node_eval_fanout(snap, node_id, node_index)
        index = self.eval_upsert(evals)
        return [e.id for e in evals], index

    def _node_eval_fanout(self, snap, node_id: str,
                          node_index: int) -> List[Evaluation]:
        """One node's node-update eval set (the create_node_evals body,
        shared with the batch-expiry path so single and mass expiry build
        byte-identical evals from the same snapshot reads)."""
        allocs = snap.allocs_by_node(node_id)
        sys_jobs = snap.jobs_by_scheduler(structs.JOB_TYPE_SYSTEM)

        evals: List[Evaluation] = []
        job_ids = set()
        for alloc in allocs:
            if alloc.job_id in job_ids or alloc.job is None:
                continue
            job_ids.add(alloc.job_id)
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    priority=alloc.job.priority,
                    type=alloc.job.type,
                    triggered_by=structs.EVAL_TRIGGER_NODE_UPDATE,
                    job_id=alloc.job_id,
                    node_id=node_id,
                    node_modify_index=node_index,
                    status=structs.EVAL_STATUS_PENDING,
                )
            )
        for job in sys_jobs:
            if job.id in job_ids:
                continue
            job_ids.add(job.id)
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    priority=job.priority,
                    type=job.type,
                    triggered_by=structs.EVAL_TRIGGER_NODE_UPDATE,
                    job_id=job.id,
                    node_id=node_id,
                    node_modify_index=node_index,
                    status=structs.EVAL_STATUS_PENDING,
                )
            )

        return evals

    # -- Eval endpoint (eval_endpoint.go) ------------------------------------

    def eval_dequeue(self, schedulers: List[str], timeout: float):
        """Returns (eval, token, wait_index) — wait_index is the raft
        index the worker must observe locally before snapshotting."""
        ev, token = self.eval_broker.dequeue(schedulers, timeout)
        if ev is None:
            return None, "", 0
        # Floor at the leader's applied index: whatever was committed
        # before this delivery (earlier plans for this eval included) must
        # be visible in the processing worker's snapshot.
        return ev, token, max(self.eval_broker.wait_index(ev.id),
                              self.raft.applied_index)

    def eval_dequeue_batch(self, schedulers: List[str], max_batch: int,
                           timeout: float):
        """Coalescing dequeue: block for one eval, drain up to max_batch-1
        more ready ones (distinct jobs). The broker half of SURVEY.md §7
        'Batched evals' — the worker runs the batch concurrently so the
        device solves stack into one dispatch (ops/coalesce.py).
        Returns (eval, token, wait_index) triples."""
        return [
            (ev, token, max(self.eval_broker.wait_index(ev.id),
                            self.raft.applied_index))
            for ev, token in self.eval_broker.dequeue_batch(
                schedulers, max_batch, timeout)
        ]

    def eval_ack(self, eval_id: str, token: str) -> None:
        self.eval_broker.ack(eval_id, token)

    def eval_touch(self, eval_id: str, token: str) -> None:
        """Reset the outstanding eval's nack timer mid-processing — keeps a
        long first-compile solve from being redelivered (the broker-side
        mechanism is OutstandingReset, eval_broker.go:396-412; the
        reference only exercises it from plan submission, which is too
        late for a pre-plan cold compile)."""
        self.eval_broker.outstanding_reset(eval_id, token)

    def eval_nack(self, eval_id: str, token: str) -> None:
        self.eval_broker.nack(eval_id, token)

    def eval_upsert(self, evals: List[Evaluation]) -> int:
        """Commit evals through the log (Eval.Update / Eval.Create RPC,
        eval_endpoint.go)."""
        return self.raft.apply("eval_update", {"evals": evals}).result()

    def eval_reap(self, eval_ids: List[str], alloc_ids: List[str]) -> int:
        return self.raft.apply(
            "eval_delete", {"evals": eval_ids, "allocs": alloc_ids}
        ).result()

    # -- Plan endpoint (plan_endpoint.go:16-38) ------------------------------

    def plan_submit(self, plan: Plan) -> PlanResult:
        pending = self.plan_queue.enqueue(plan)
        return pending.wait()

    # -- Read plane (server/read_path.py) ------------------------------------

    def confirmed_read_index(self, timeout: float = 2.0) -> int:
        """A leadership-confirmed read index for the linearizable lane
        (no raft log write). DevMode's InProcRaft confirms trivially; a
        ClusterServer follower overrides this to forward Raft.ReadIndex
        to the leader."""
        return self.raft.read_index(timeout=timeout)

    # -- Express endpoint (nomad_tpu/server/express.py) ----------------------

    def express_reconcile(self, job: Job, evals: List[Evaluation]) -> int:
        """Durably hand a bounced-out/failed-over express entry to the
        ordinary scheduler: upsert the job and its evals — the original
        express eval completed-with-successor plus the PENDING reconcile
        eval — through raft (the FSM's eval apply enqueues the pending
        one into the broker). On a ClusterServer a non-leader forwards
        (Express.Reconcile) — the express committer calls this from a
        possibly-deposed server."""
        self.raft.apply("job_register", {"job": job}).result()
        return self.eval_upsert(evals)

    # -- convenience --------------------------------------------------------

    def wait_for_eval(self, eval_id: str, timeout: float = 10.0) -> Evaluation:
        """Poll until the eval reaches a terminal status (the CLI monitor's
        polling loop, command/monitor.go)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            ev = self.state_store.eval_by_id(eval_id)
            if ev is not None and ev.terminal_status():
                return ev
            _time.sleep(0.01)
        raise TimeoutError(f"eval {eval_id} did not complete")

    def stats(self) -> Dict:
        broker = self.eval_broker.snapshot_stats()
        return {
            "applied_index": self.raft.applied_index,
            "broker_ready": broker.total_ready,
            "broker_unacked": broker.total_unacked,
            "broker_blocked": broker.total_blocked,
            "plan_queue_depth": self.plan_queue.depth(),
            "plan_pipeline": self.plan_applier.stats(),
            "heartbeat_timers": self.heartbeat.num_timers(),
            "scheduler": self.solver_stats(),
            "slo": (self.slo_monitor.summary()
                    if self.slo_monitor is not None else None),
            "admission": self.admission.summary(),
            "express": self.express_lane.summary(),
            "capacity": (self.capacity_accountant.summary()
                         if self.config.capacity_config.enabled else None),
            "raft_observe": (self.raft_observatory.summary()
                             if self.config.raft_observe_config.enabled
                             else None),
            "reads": (self.read_observatory.summary()
                      if self.config.reads_config.enabled else None),
            "read_path": self.read_path.summary(),
            "runtime": (self.runtime_observatory.summary()
                        if self.config.profile_config.enabled else None),
        }

    @staticmethod
    def solver_stats() -> Dict:
        """Device-solver health: probe state + host-fallback count, the
        coalescer's dispatch/batch counters, and the mirror-cache hit rate.
        Surfaced through Stats()/agent-info so a silently-degraded device
        path (host fallback: same placements, order-of-magnitude latency
        cliff) is operator-visible. Metrics posture mirrors the
        reference's broker stats (nomad/eval_broker.go:557-575)."""
        from nomad_tpu.scheduler import DEVICE_BREAKER, device_probe_status

        out: Dict = {"device": device_probe_status(),
                     "breaker": DEVICE_BREAKER.stats()}
        try:
            import sys

            coalesce = sys.modules.get("nomad_tpu.ops.coalesce")
            mirror = sys.modules.get("nomad_tpu.tpu.mirror")
            if coalesce is not None:
                eng = coalesce.GLOBAL_SOLVER
                out["coalesce_dispatches"] = eng.dispatches
                out["coalesce_batched_evals"] = eng.coalesced
            if mirror is not None:
                cache = mirror.GLOBAL_MIRROR_CACHE
                out["mirror_cache_hits"] = cache.hits
                out["mirror_cache_misses"] = cache.misses
                out["mirror_delta_rolls"] = cache.delta_rolls
                out["mirror_full_rebuilds"] = cache.full_rebuilds
                out["mirror_rows_restaged"] = cache.rows_restaged
        except Exception:  # stats must never break agent-info
            pass
        return out
