"""Plan applier: the single serialization point of the cluster.

Reference: /root/reference/nomad/plan_apply.go. Dequeues plans, verifies
token + per-node feasibility against a state snapshot, commits the feasible
subset through the FSM, and pipelines: verification of plan N+1 overlaps the
(raft) apply of plan N via an optimistic snapshot.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from nomad_tpu.server.eval_broker import BrokerError, EvalBroker
from nomad_tpu import telemetry
from nomad_tpu.server.plan_queue import PendingPlan, PlanQueue
from nomad_tpu.structs import (
    Allocation,
    Plan,
    PlanResult,
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
)


def evaluate_node_plan(snap, plan: Plan, node_id: str,
                       batch_res=None) -> bool:
    """Check one node's placements against the snapshot
    (plan_apply.go:229-277). ``batch_res`` carries the summed Resources of
    any columnar (AllocBatch) placements on this node."""
    if not plan.node_allocation.get(node_id) and batch_res is None:
        # Evict-only plans always fit.
        return True

    node = snap.node_by_id(node_id)
    if node is None or node.status != "ready" or node.drain:
        return False

    existing = filter_terminal_allocs(snap.allocs_by_node(node_id))

    remove = list(plan.node_update.get(node_id, []))
    remove.extend(plan.node_allocation.get(node_id, []))
    proposed = remove_allocs(existing, remove)
    proposed = proposed + plan.node_allocation.get(node_id, [])
    if batch_res is not None:
        pseudo = Allocation(resources=batch_res)
        proposed = proposed + [pseudo]

    fit, _, _ = allocs_fit(node, proposed)
    return fit


# Plans below this many placements verify with the per-node scalar loop;
# larger ones go through the native bulk verifier first.
FAST_VERIFY_THRESHOLD = 64


def _node_live(snap, node_id: str) -> bool:
    node = snap.node_by_id(node_id)
    return node is not None and node.status == "ready" and not node.drain


def _res_vec(res) -> "np.ndarray":
    import numpy as np

    if res is None:
        return np.zeros(4, dtype=np.int64)
    return np.array(res.as_vector(), dtype=np.int64)


def _existing_block_usage(snap):
    """Per-node usage of stored columnar blocks: {node_id: int64[4]}, plus
    the set of nodes whose blocks carry network asks (those fall back to
    the scalar path). O(runs), no materialization."""
    import numpy as np

    usage = {}
    net_nodes = set()
    getter = getattr(snap, "alloc_blocks", None)
    blocks = getter() if getter is not None else []
    for blk in blocks:
        has_net = bool(blk.resources is not None and blk.resources.networks)
        if not has_net and blk.task_resources:
            has_net = any(
                tr is not None and tr.networks
                for tr in blk.task_resources.values()
            )
        if has_net:
            net_nodes.update(nid for nid, _ in blk.live_node_counts())
            continue
        vec = np.asarray(blk.resource_vector(), dtype=np.int64)
        for nid, cnt in blk.live_node_counts():
            prev = usage.get(nid)
            usage[nid] = vec * cnt if prev is None else prev + vec * cnt
    return usage, net_nodes, blocks


def _prevaluate_nodes_bulk(snap, plan: Plan, batch_ask=None):
    """Bulk-verify the network-free nodes of a large plan with the native
    kernels (nomad_tpu.native): one scatter-add of every placement's
    resource row + one vectorized superset check, instead of per-node
    AllocsFit object walks. Nodes with any network asks (port collisions
    need the sequential NetworkIndex, funcs.go:73-86) or that fail here in
    a way the scalar path must diagnose stay out of the returned map and
    fall through to evaluate_node_plan. ``batch_ask`` maps node_id to the
    summed int64 resource vector of columnar (AllocBatch) placements.
    Returns {node_id: fit}.
    """
    import numpy as np

    from nomad_tpu import native

    batch_ask = batch_ask or {}
    out = {}
    ids = [nid for nid, placed in plan.node_allocation.items() if placed]
    ids.extend(nid for nid in batch_ask if nid not in plan.node_allocation)

    # Existing usage held in columnar blocks, accounted without
    # materialization; reads below then only walk the object table.
    block_usage, block_net_nodes, blocks = _existing_block_usage(snap)
    read_objects = getattr(snap, "allocs_by_node_objects", None)
    if read_objects is None:
        read_objects = snap.allocs_by_node
        block_usage, block_net_nodes, blocks = {}, set(), []

    def evicted_block_vec(nid):
        """Resource sum of this plan's evictions that live in blocks (the
        object walk below can't see them); stale eviction ids subtract
        nothing."""
        total = None
        for a in plan.node_update.get(nid, ()):
            if any(blk.find(a.id) is not None for blk in blocks):
                vec = _res_vec(a.resources)
                total = vec if total is None else total + vec
        return total

    totals_rows = []
    base_rows = []
    kept = []  # node ids eligible for the bulk check, in row order

    # Shared-object caches: the TPU scheduler's lean path aliases one
    # Resources / task_resources object across a task group's allocs, so
    # these collapse 100k attribute walks into dict hits.
    vec_cache = {}
    net_cache = {}

    def alloc_row(alloc):
        """(vec, has_networks) for one allocation, cached by identity."""
        key = id(alloc.resources)
        vec = vec_cache.get(key)
        if vec is None:
            vec = _res_vec(alloc.resources)
            vec_cache[key] = vec
        nkey = (key, id(alloc.task_resources))
        has_net = net_cache.get(nkey)
        if has_net is None:
            has_net = bool(alloc.resources is not None and alloc.resources.networks)
            if not has_net and alloc.task_resources:
                has_net = any(
                    tr is not None and tr.networks
                    for tr in alloc.task_resources.values()
                )
            net_cache[nkey] = has_net
        return vec, has_net

    for nid in ids:
        node = snap.node_by_id(nid)
        if node is None or node.status != "ready" or node.drain:
            out[nid] = False
            continue
        if node.reserved is not None and node.reserved.networks:
            continue  # reserved-port semantics: scalar path
        if nid in block_net_nodes:
            continue  # network-carrying block members: scalar path
        placements = plan.node_allocation.get(nid, ())

        base = _res_vec(node.reserved)
        extra = batch_ask.get(nid)
        if extra is not None:
            base = base + extra
        blk_used = block_usage.get(nid)
        if blk_used is not None:
            base = base + blk_used
            if plan.node_update.get(nid):
                evicted = evicted_block_vec(nid)
                if evicted is not None:
                    base = base - evicted
        existing = filter_terminal_allocs(read_objects(nid))
        bail = False
        if existing:
            removed = {a.id for a in plan.node_update.get(nid, [])}
            removed.update(a.id for a in placements)
            # Identity-counted accumulation: existing allocs share a few
            # Resources objects, so this is dict hits + one multiply-add
            # per distinct shape instead of a numpy add per alloc. Keyed
            # by the (resources, task_resources) pair — has_net depends on
            # both (alloc_row's net_cache key).
            ex_counts = {}
            for alloc in existing:
                if alloc.id in removed:
                    continue
                key = (id(alloc.resources), id(alloc.task_resources))
                n = ex_counts.get(key)
                if n is None:
                    _vec, has_net = alloc_row(alloc)
                    if has_net:
                        bail = True
                        break
                    ex_counts[key] = 1
                else:
                    ex_counts[key] = n + 1
            if not bail:
                for key, n in ex_counts.items():
                    base = base + vec_cache[key[0]] * n
        if bail:
            continue

        # Placements overwhelmingly alias a handful of Resources objects
        # (one per task group); count per distinct object, then one
        # multiply-accumulate per distinct ask shape.
        counts = {}
        for alloc in placements:
            key = (id(alloc.resources), id(alloc.task_resources))
            n = counts.get(key)
            if n is None:
                vec, has_net = alloc_row(alloc)
                if has_net:
                    bail = True
                    break
                counts[key] = 1
            else:
                counts[key] = n + 1
        if bail:
            continue
        ask = base
        for key, n in counts.items():
            ask = ask + vec_cache[key[0]] * n

        kept.append(nid)
        totals_rows.append(_res_vec(node.resources))
        base_rows.append(ask)

    if not kept:
        return out

    used = np.asarray(base_rows, dtype=np.int64)
    fit, _exhausted = native.fit_check(
        np.minimum(used, 2**31 - 1).astype(np.int32),
        np.asarray(totals_rows, dtype=np.int32),
    )
    for nid, ok in zip(kept, fit.tolist()):
        out[nid] = ok
    return out


def evaluate_plan(snap, plan: Plan) -> PlanResult:
    """Determine the committable subset of a plan (plan_apply.go:164-227).

    Columnar batches verify without expansion: each batch contributes
    ``count x resource-vector`` per node run, folded into the same per-node
    fit checks as the object placements; committed batches are the runs on
    fitting nodes."""
    import numpy as np

    result = PlanResult(
        node_update={},
        node_allocation={},
        failed_allocs=plan.failed_allocs,
    )

    # Per-node resource ask of the columnar placements.
    batch_ask = {}
    for b in plan.alloc_batches:
        vec = np.asarray(b.resource_vector(), dtype=np.int64)
        for nid, cnt in zip(b.node_ids, b.node_counts):
            prev = batch_ask.get(nid)
            batch_ask[nid] = vec * cnt if prev is None else prev + vec * cnt

    # In-place update batches contribute their per-node (new - old)
    # resource delta; delta-free nodes only need a liveness check. Wire-
    # received batches resolve ids against this snapshot first (stale ids
    # drop out -> partial commit). Old vectors are identity-counted: a
    # batch's allocs share a handful of Resources objects, so per-alloc
    # work is dict hits, not numpy.
    upd_nodes = set()
    for b in plan.update_batches:
        b.resolve(snap)
        new_vec = np.asarray(b.resource_vector(), dtype=np.int64)
        counts = {}
        old_vecs = {}
        for a in b.allocs:
            upd_nodes.add(a.node_id)
            key = (a.node_id, id(a.resources))
            n = counts.get(key)
            if n is None:
                counts[key] = 1
                old_vecs[key] = (
                    np.asarray(a.resources.as_vector(), dtype=np.int64)
                    if a.resources is not None
                    else np.zeros(4, dtype=np.int64)
                )
            else:
                counts[key] = n + 1
        for key, cnt in counts.items():
            delta = (new_vec - old_vecs[key]) * cnt
            if np.any(delta):
                nid = key[0]
                prev = batch_ask.get(nid)
                batch_ask[nid] = delta if prev is None else prev + delta

    bulk_fit = {}
    n_placements = sum(len(v) for v in plan.node_allocation.values())
    n_placements += sum(b.n for b in plan.alloc_batches)
    n_placements += sum(b.n for b in plan.update_batches)
    if n_placements >= FAST_VERIFY_THRESHOLD:
        bulk_fit = _prevaluate_nodes_bulk(snap, plan, batch_ask)

    def batch_res(node_id):
        vec = batch_ask.get(node_id)
        if vec is None:
            return None
        from nomad_tpu.structs import Resources

        return Resources(
            cpu=int(vec[0]), memory_mb=int(vec[1]),
            disk_mb=int(vec[2]), iops=int(vec[3]),
        )

    fits = {}
    node_ids = (set(plan.node_update) | set(plan.node_allocation)
                | set(batch_ask) | upd_nodes)
    for node_id in node_ids:
        fit = bulk_fit.get(node_id)
        if fit is None:
            if (node_id in upd_nodes
                    and not plan.node_allocation.get(node_id)
                    and node_id not in batch_ask
                    and not plan.node_update.get(node_id)):
                fit = _node_live(snap, node_id)
            else:
                fit = evaluate_node_plan(snap, plan, node_id, batch_res(node_id))
                if fit and node_id in upd_nodes:
                    # evaluate_node_plan's evict-only shortcut skips the
                    # liveness check; re-stamped allocs need a live node.
                    fit = _node_live(snap, node_id)
        fits[node_id] = fit
        if not fit:
            # Stale scheduler data: force a refresh to the latest view.
            result.refresh_index = max(
                snap.get_index("nodes"), snap.get_index("allocs")
            )
            if plan.all_at_once:
                result.node_update = {}
                result.node_allocation = {}
                return result
            continue
        if plan.node_update.get(node_id):
            result.node_update[node_id] = plan.node_update[node_id]
        if plan.node_allocation.get(node_id):
            result.node_allocation[node_id] = plan.node_allocation[node_id]
    for b in plan.alloc_batches:
        kept = b.filter_nodes(fits)
        if kept.n:
            result.alloc_batches.append(kept)
    for b in plan.update_batches:
        kept = b.filter_nodes(fits)
        if kept.n:
            result.update_batches.append(kept)
    return result


def _object_allocs(result: PlanResult) -> list:
    """The object-row part of a committed plan. Columnar placement batches
    stay columnar all the way into the state store (state/blocks.py);
    update batches re-stamp existing rows and materialize here."""
    allocs: list = []
    for update_list in result.node_update.values():
        allocs.extend(update_list)
    for alloc_list in result.node_allocation.values():
        allocs.extend(alloc_list)
    for batch in result.update_batches:
        allocs.extend(batch.materialize())
    allocs.extend(result.failed_allocs)
    return allocs


class PlanApplier(threading.Thread):
    """Long-lived applier thread (plan_apply.go:39-117).

    ``raft`` is anything with apply(msg_type, payload) -> Future[index] and
    an ``applied_index`` property — the real replication layer or the
    in-process one. Verification of the next plan overlaps the apply of the
    previous one by verifying against an optimistic snapshot.
    """

    def __init__(
        self,
        plan_queue: PlanQueue,
        eval_broker: EvalBroker,
        raft,
        fsm,
        logger: Optional[logging.Logger] = None,
    ):
        super().__init__(daemon=True, name="plan-applier")
        self.plan_queue = plan_queue
        self.eval_broker = eval_broker
        self.raft = raft
        # Hold the FSM, not its StateStore: a raft snapshot restore rebinds
        # fsm.state to a fresh store (fsm.go:313-410 posture), and plans must
        # be verified against the live one.
        self.fsm = fsm
        self.logger = logger or logging.getLogger("nomad_tpu.plan_apply")
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        wait_event: Optional[threading.Event] = None
        snap = None

        while not self._stop.is_set():
            pending = self.plan_queue.dequeue(timeout=0.2)
            if pending is None:
                continue

            # Token verification guards split-brain evals
            # (plan_apply.go:52-58, structs.go:1466-1471).
            try:
                self.eval_broker.outstanding_reset(
                    pending.plan.eval_id, pending.plan.eval_token
                )
            except BrokerError as e:
                self.logger.error(
                    "plan rejected for evaluation %s: %s", pending.plan.eval_id, e
                )
                pending.respond(None, e)
                continue

            # Reap a completed overlap
            if wait_event is not None and wait_event.is_set():
                wait_event = None
                snap = None

            if wait_event is None or snap is None:
                snap = self.fsm.state.snapshot()

            t0 = time.perf_counter()
            result = evaluate_plan(snap, pending.plan)
            telemetry.measure_since(("plan", "evaluate"), t0)

            if result.is_noop():
                pending.respond(result, None)
                continue

            # Bound snapshot staleness: wait for any in-flight apply
            if wait_event is not None:
                wait_event.wait()
                snap = self.fsm.state.snapshot()
                # Re-evaluate against fresh state? The reference keeps the
                # earlier verification (bounded staleness); so do we.

            future = self._apply(result, snap)
            wait_event = threading.Event()
            t = threading.Thread(
                target=self._async_plan_wait,
                args=(wait_event, future, result, pending),
                daemon=True,
            )
            t.start()

    def _apply(self, result: PlanResult, snap):
        """Dispatch the replicated alloc update + optimistic snapshot apply
        (plan_apply.go:119-144)."""
        t0 = time.perf_counter()
        allocs = _object_allocs(result)
        payload = {"allocs": allocs}
        if result.alloc_batches:
            payload["alloc_batches"] = result.alloc_batches
        future = self.raft.apply("alloc_update", payload)
        telemetry.measure_since(("plan", "submit"), t0)
        if snap is not None:
            # Stamp the optimistic snapshot with the entry's real index: with
            # a synchronous replication layer the future is already resolved;
            # with an async one the entry will land at applied_index + 1.
            # Never stamp ahead of the log — a RefreshIndex taken from this
            # snapshot must be reachable by worker wait_for_index.
            if future.done() and future.exception() is None:
                idx = future.result()
            else:
                idx = self.raft.applied_index + 1
            if allocs:
                snap.upsert_allocs(idx, allocs)
            if result.alloc_batches:
                snap.upsert_alloc_blocks(idx, result.alloc_batches)
        return future

    def _async_plan_wait(self, wait_event, future, result, pending: PendingPlan):
        """plan_apply.go:146-162"""
        try:
            index = future.result()
        except Exception as e:  # raft apply failed
            self.logger.error("failed to apply plan: %s", e)
            pending.respond(None, e)
            wait_event.set()
            return
        result.alloc_index = index
        pending.respond(result, None)
        wait_event.set()
