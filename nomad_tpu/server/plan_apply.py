"""Plan verification: per-node feasibility of a submitted plan.

Reference: /root/reference/nomad/plan_apply.go (the verification half).
``evaluate_plan`` determines the committable subset of one plan against a
state snapshot — scalar per-node checks for small plans, the vectorized
columnar ``_NodeTable`` path for large ones. The applier loop itself lives
in plan_pipeline.py (the optimistic batch applier): it drains K plans at
once and generalizes this module's verification to one fused K x nodes
tensor pass, so the single-plan semantics here are the decision contract
the batched verifier is fuzz-pinned against.
"""

from __future__ import annotations

import threading

from nomad_tpu import telemetry
from nomad_tpu.structs import (
    Allocation,
    Plan,
    PlanResult,
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
)


def evaluate_node_plan(snap, plan: Plan, node_id: str,
                       batch_res=None) -> bool:
    """Check one node's placements against the snapshot
    (plan_apply.go:229-277). ``batch_res`` carries the summed Resources of
    any columnar (AllocBatch) placements on this node."""
    if not plan.node_allocation.get(node_id) and batch_res is None:
        # Evict-only plans always fit.
        return True

    node = snap.node_by_id(node_id)
    if node is None or node.status != "ready" or node.drain:
        return False

    existing = filter_terminal_allocs(snap.allocs_by_node(node_id))

    remove = list(plan.node_update.get(node_id, []))
    remove.extend(plan.node_allocation.get(node_id, []))
    proposed = remove_allocs(existing, remove)
    proposed = proposed + plan.node_allocation.get(node_id, [])
    if batch_res is not None:
        pseudo = Allocation(resources=batch_res)
        proposed = proposed + [pseudo]

    fit, _, _ = allocs_fit(node, proposed)
    return fit


# Plans below this many placements verify with the per-node scalar loop;
# larger ones go through the native bulk verifier first.
FAST_VERIFY_THRESHOLD = 64


def _node_live(snap, node_id: str) -> bool:
    node = snap.node_by_id(node_id)
    return node is not None and node.status == "ready" and not node.drain


def _res_vec(res) -> "np.ndarray":
    import numpy as np

    if res is None:
        return np.zeros(4, dtype=np.int64)
    return np.array(res.as_vector(), dtype=np.int64)


_ZERO4 = (0, 0, 0, 0)


def _table_row_vals(node):
    """(totals4, reserved4, dead, scalar_only) for one node row — the ONE
    definition of _NodeTable's per-row column semantics, shared by the
    bulk build and the delta roll so a rolled table can never drift from
    a fresh one."""
    return (
        _ZERO4 if node.resources is None
        else tuple(node.resources.as_vector()),
        _ZERO4 if node.reserved is None
        else tuple(node.reserved.as_vector()),
        node.status != "ready" or bool(node.drain),
        node.reserved is not None and bool(node.reserved.networks),
    )


class _NodeTable:
    """Columnar view of the node set for vectorized plan verification:
    id -> row, plus per-row totals/reserved/liveness. Cached per
    (store_uid, nodes index) — node rows are immutable between node-table
    writes, while usage is re-read from the snapshot every call."""

    __slots__ = ("rows", "totals", "reserved", "dead", "scalar_only", "n",
                 "block_rows_cache", "_mirror_maps", "block_usage_cache")

    def __init__(self, snap):
        import numpy as np

        nodes = snap.nodes()
        self.n = len(nodes)
        # id(block) -> (block, rows, counts): per-block node-run row
        # resolution, valid for this table's lifetime (blocks are COW).
        self.block_rows_cache = {}
        # (id-set, block refs, usage[N,4], net_rows) of the last
        # _existing_block_usage_rows accumulation — extended
        # incrementally while the block set only grows (the applier's
        # monotonic verify sequence), recomputed on any removal.
        self.block_usage_cache = None
        # id(mirror id array) -> (array, table rows aligned with it):
        # one string resolve per (table, mirror) pair; every plan built
        # from that mirror then resolves node runs by pure gathers.
        # Capped: mirrors churn with datacenter-set keys while a table
        # generation can live long, and the strong ref here is what keeps
        # each id() key valid — unbounded it would pin every mirror ever
        # seen (an id array is ~7MB at 50k nodes).
        import collections
        self._mirror_maps = collections.OrderedDict()
        self.rows = {node.id: i for i, node in enumerate(nodes)}
        # Bulk conversions, not 50k scalar-row assignments: one python
        # pass computing row tuples (_table_row_vals, shared with the
        # delta roll) feeds one np.array per column.
        if nodes:
            vals = [_table_row_vals(n) for n in nodes]
            self.totals = np.array([v[0] for v in vals], dtype=np.int32)
            self.reserved = np.array([v[1] for v in vals], dtype=np.int64)
            self.dead = np.fromiter(
                (v[2] for v in vals), dtype=bool, count=self.n)
            # reserved networks need the sequential port index: scalar path.
            self.scalar_only = np.fromiter(
                (v[3] for v in vals), dtype=bool, count=self.n)
        else:
            self.totals = np.zeros((0, 4), dtype=np.int32)
            self.reserved = np.zeros((0, 4), dtype=np.int64)
            self.dead = np.zeros(0, dtype=bool)
            self.scalar_only = np.zeros(0, dtype=bool)

    def apply_delta(self, changes, snap) -> "Optional[_NodeTable]":
        """Roll this table forward through node-table ``changes`` (the
        store's change log, same feed as NodeMirror.apply_delta): dirty
        rows patch on column copies, brand-new nodes append at the dict
        tail. Returns None when a delta can't express the change — a
        node deleted (row shift) or a removed key re-inserted (dict
        order moved) — and the caller rebuilds. Node writes no longer
        cost the plan applier an O(N) table rebuild per verify."""
        import numpy as np

        from nomad_tpu.state.store import partition_node_changes

        # This table's set is ALL nodes (liveness is the dead column,
        # not membership): resolve is a plain row lookup.
        parts = partition_node_changes(changes, self.rows.get,
                                       snap.node_by_id)
        if parts is None:
            return None
        patches, appends = parts
        if not patches and not appends:
            return self

        new = _NodeTable.__new__(_NodeTable)
        new.n = self.n + len(appends)
        row_vals = _table_row_vals
        totals = self.totals
        reserved = self.reserved
        dead = self.dead
        scalar_only = self.scalar_only
        if patches:
            totals = totals.copy()
            reserved = reserved.copy()
            dead = dead.copy()
            scalar_only = scalar_only.copy()
            for row, node in patches:
                t, r, d, s = row_vals(node)
                totals[row] = t
                reserved[row] = r
                dead[row] = d
                scalar_only[row] = s
        if appends:
            app_vals = [row_vals(node) for _pos, node in appends]
            totals = np.concatenate([totals, np.array(
                [v[0] for v in app_vals], dtype=np.int32)])
            reserved = np.concatenate([reserved, np.array(
                [v[1] for v in app_vals], dtype=np.int64)])
            dead = np.concatenate([dead, np.array(
                [v[2] for v in app_vals], dtype=bool)])
            scalar_only = np.concatenate([scalar_only, np.array(
                [v[3] for v in app_vals], dtype=bool)])
            rows = dict(self.rows)
            for i, (_pos, node) in enumerate(appends):
                rows[node.id] = self.n + i
            new.rows = rows
            # Row numbering of existing nodes didn't move, but cached
            # resolutions may hold -1 for the appended ids and the usage
            # accumulator is row-aligned: rebuild those lazily.
            new.block_rows_cache = {}
            import collections
            new._mirror_maps = collections.OrderedDict()
            new.block_usage_cache = None
        else:
            new.rows = self.rows
            # Pure row patches leave row numbering AND block usage
            # (a function of blocks, not node fields) intact: share the
            # warm caches with the ancestor.
            new.block_rows_cache = self.block_rows_cache
            new._mirror_maps = self._mirror_maps
            new.block_usage_cache = self.block_usage_cache
        new.totals = totals
        new.reserved = reserved
        new.dead = dead
        new.scalar_only = scalar_only
        return new

    def mirror_rows(self, ids_ref) -> "np.ndarray":
        """Table rows aligned with a solver mirror's id array (-1 for ids
        this table doesn't know). The id array is identity-stable across
        evals of one state generation (MirrorCache), so the per-id dict
        walk happens once per (table, mirror) pair and every subsequent
        plan resolves its node runs with a single fancy-index."""
        import numpy as np

        cached = self._mirror_maps.get(id(ids_ref))
        if cached is not None and cached[0] is ids_ref:
            self._mirror_maps.move_to_end(id(ids_ref))
            return cached[1]
        get = self.rows.get
        mapped = np.fromiter(
            (get(nid, -1) for nid in ids_ref), dtype=np.int64,
            count=len(ids_ref),
        )
        self._mirror_maps[id(ids_ref)] = (ids_ref, mapped)
        while len(self._mirror_maps) > 8:
            self._mirror_maps.popitem(last=False)
        return mapped


_NODE_TABLE_LOCK = threading.Lock()
_NODE_TABLE_CACHE: "OrderedDict" = None  # type: ignore[assignment]


def _node_table(snap):
    """Cached _NodeTable for a snapshot, or None for states without the
    store internals (protocol-only fakes). A key miss delta-rolls the
    newest cached table of the same store through the node change log
    (NodeTable.apply_delta) before falling back to a full build — the
    MirrorCache posture, applied to the plan applier's staging."""
    import collections

    global _NODE_TABLE_CACHE
    uid = getattr(snap, "store_uid", "")
    if not uid or not hasattr(snap, "alloc_blocks"):
        return None
    nodes_index = snap.get_index("nodes")
    key = (uid, nodes_index)
    ancestor = None
    with _NODE_TABLE_LOCK:
        if _NODE_TABLE_CACHE is None:
            _NODE_TABLE_CACHE = collections.OrderedDict()
        table = _NODE_TABLE_CACHE.get(key)
        if table is not None:
            _NODE_TABLE_CACHE.move_to_end(key)
            return table
        best = None
        for k in _NODE_TABLE_CACHE:
            if (k[0] == uid and k[1] < nodes_index
                    and (best is None or k[1] > best[1])):
                best = k
        if best is not None:
            ancestor = (best, _NODE_TABLE_CACHE[best])
    table = None
    if ancestor is not None and hasattr(snap, "node_changes_since"):
        changes = snap.node_changes_since(ancestor[0][1])
        if changes is not None:
            table = ancestor[1].apply_delta(changes, snap)
            if table is not None:
                telemetry.incr_counter(("plan", "node_table_rolls"))
    if table is None:
        table = _NodeTable(snap)
        telemetry.incr_counter(("plan", "node_table_rebuilds"))
    with _NODE_TABLE_LOCK:
        existing = _NODE_TABLE_CACHE.get(key)
        if existing is not None:
            _NODE_TABLE_CACHE.move_to_end(key)
            return existing
        _NODE_TABLE_CACHE[key] = table
        while len(_NODE_TABLE_CACHE) > 4:
            _NODE_TABLE_CACHE.popitem(last=False)
    return table


class _FitMap(dict):
    """{node_id: fit} answer map of the bulk verifier. ``all_fit=True``
    is the whole-commit hint: every node the plan's ask touches is live,
    port-free, and fits, so a caller whose plan has no other node sources
    can commit whole without unioning id sets or scanning values.
    When all_fit is set and the plan carries no update batches the
    per-node entries are OMITTED (the whole-commit consumer never reads
    them); otherwise entries are populated."""

    __slots__ = ("all_fit",)

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.all_fit = False


class _AskAccum:
    """Per-node resource ask of a plan's columnar batches and update
    deltas. Holds batch references; materializes either a dense row array
    (``to_rows``, one np.add.at per batch — the bulk verifier's form) or a
    lazy per-node dict (``get`` — the scalar fallback's form, built only
    when a small plan actually reads it). Unknown node ids keep their
    vectors in the dict form, so a plan targeting a deregistered node
    still fails its fit check instead of riding the evict-only shortcut."""

    def __init__(self):
        self.batches = []  # (node_ids, node_counts, vec, src)
        self.deltas = {}   # nid -> int64[4]
        self._node_ids = None
        self._dict = None

    @property
    def node_ids(self):
        """Union of all touched node ids, built on first read: the
        whole-commit fast path (all_fit) never consults it, so a fresh
        large placement skips the ~5k-string set build entirely."""
        ids = self._node_ids
        if ids is None:
            ids = set()
            for node_ids, _counts, _vec, _src in self.batches:
                ids.update(node_ids)
            ids.update(self.deltas)
            self._node_ids = ids
        return ids

    def add_batch(self, node_ids, node_counts, vec, src=None) -> None:
        """``src`` is the optional solver-mirror row hint carried by a
        columnar batch: (mirror id array, row indices into it) — lets the
        bulk verifier resolve table rows by gather instead of per-id dict
        walks."""
        self.batches.append((node_ids, node_counts, vec, src))
        self._node_ids = None
        self._dict = None

    def add_delta(self, nid: str, delta) -> None:
        prev = self.deltas.get(nid)
        self.deltas[nid] = delta if prev is None else prev + delta
        self._node_ids = None
        self._dict = None

    def get(self, nid: str):
        """Summed ask vector for one node, or None when untouched."""
        if nid not in self.node_ids:
            return None
        if self._dict is None:
            acc = {}
            for node_ids, node_counts, vec, _src in self.batches:
                for run_nid, cnt in zip(node_ids, node_counts):
                    prev = acc.get(run_nid)
                    acc[run_nid] = (
                        vec * cnt if prev is None else prev + vec * cnt
                    )
            for d_nid, delta in self.deltas.items():
                prev = acc.get(d_nid)
                acc[d_nid] = delta if prev is None else prev + delta
            self._dict = acc
        return self._dict.get(nid)

    def to_rows(self, table):
        """Dense [N, 4] int64 ask over node-table rows (or None if no
        contributions); unknown node ids drop out — the bulk verifier
        already answers False for them."""
        return self.accumulate_rows(table)[0]

    def accumulate_rows(self, table):
        """(ask_arr, flat_ids, rows): the dense [N, 4] ask PLUS the
        per-contribution row resolution it computed on the way — node ids
        in contribution order and their table rows (-1 for unknown),
        aligned. The single id→row resolve serves both the accumulation
        and any caller that needs per-node answers (the pure-columnar
        fast path); keeping them in one method keeps the ask rules from
        forking."""
        import numpy as np

        if not self.batches and not self.deltas:
            return None, [], np.empty(0, dtype=np.int64)
        arr = np.zeros((table.n, 4), dtype=np.int64)
        get = table.rows.get
        flat_ids = []
        row_parts = []
        for node_ids, node_counts, vec, src in self.batches:
            if src is not None:
                # Solver-mirror hint: resolve by gather through the
                # cached (table, mirror) row map — no per-id dict walk.
                ids_ref, src_rows = src
                rows = table.mirror_rows(ids_ref)[src_rows]
            else:
                rows = np.fromiter(
                    (get(nid, -1) for nid in node_ids), dtype=np.int64,
                    count=len(node_ids),
                )
            counts = np.asarray(node_counts, dtype=np.int64)
            valid = rows >= 0
            np.add.at(arr, rows[valid], vec[None, :] * counts[valid, None])
            flat_ids.extend(node_ids)
            row_parts.append(rows)
        for nid, delta in self.deltas.items():
            row = get(nid, -1)
            if row >= 0:
                arr[row] += delta
            flat_ids.append(nid)
            row_parts.append(np.asarray([row], dtype=np.int64))
        rows = (
            np.concatenate(row_parts) if len(row_parts) > 1
            else row_parts[0]
        )
        return arr, flat_ids, rows


class _AllocVecCache:
    """Identity-keyed (resources, task_resources) -> (vec, has_networks)
    cache shared by both bulk verifiers: the TPU scheduler's lean path
    aliases one Resources object across a task group's allocs, collapsing
    per-alloc attribute walks into dict hits."""

    def __init__(self):
        self.vec = {}
        self.net = {}

    def row(self, alloc):
        key = id(alloc.resources)
        vec = self.vec.get(key)
        if vec is None:
            vec = _res_vec(alloc.resources)
            self.vec[key] = vec
        nkey = (key, id(alloc.task_resources))
        has_net = self.net.get(nkey)
        if has_net is None:
            has_net = bool(
                alloc.resources is not None and alloc.resources.networks
            )
            if not has_net and alloc.task_resources:
                has_net = any(
                    tr is not None and tr.networks
                    for tr in alloc.task_resources.values()
                )
            self.net[nkey] = has_net
        return vec, has_net

    def sum_counted(self, allocs, removed=None):
        """Identity-counted resource sum of ``allocs`` (minus ``removed``
        ids). Returns (vec or None, bail) — bail True when any alloc
        carries network asks (sequential port semantics)."""
        counts = {}
        for alloc in allocs:
            if removed is not None and alloc.id in removed:
                continue
            key = (id(alloc.resources), id(alloc.task_resources))
            n = counts.get(key)
            if n is None:
                _vec, has_net = self.row(alloc)
                if has_net:
                    return None, True
                counts[key] = 1
            else:
                counts[key] = n + 1
        total = None
        for key, n in counts.items():
            add = self.vec[key[0]] * n
            total = add if total is None else total + add
        return total, False


def _block_has_net(blk) -> bool:
    has_net = bool(blk.resources is not None and blk.resources.networks)
    if not has_net and blk.task_resources:
        has_net = any(
            tr is not None and tr.networks
            for tr in blk.task_resources.values()
        )
    return has_net


def _existing_block_usage(snap):
    """Per-node usage of stored columnar blocks: {node_id: int64[4]}, plus
    the set of nodes whose blocks carry network asks (those fall back to
    the scalar path). O(runs), no materialization. Dict form — the
    table-less fallback; the vectorized verifier uses
    _existing_block_usage_rows."""
    import numpy as np

    usage = {}
    net_nodes = set()
    getter = getattr(snap, "alloc_blocks", None)
    blocks = getter() if getter is not None else []
    for blk in blocks:
        if _block_has_net(blk):
            net_nodes.update(nid for nid, _ in blk.live_node_counts())
            continue
        vec = np.asarray(blk.resource_vector(), dtype=np.int64)
        for nid, cnt in blk.live_node_counts():
            prev = usage.get(nid)
            usage[nid] = vec * cnt if prev is None else prev + vec * cnt
    return usage, net_nodes, blocks


def _block_rows_cached(table, blk):
    """(rows int64[k], counts int64[k]) for a block's live node runs,
    resolved against ``table`` once per (table, block) pair. Blocks are
    copy-on-write (any exclusion/update commits a NEW object,
    state/blocks.py), so the identity key can never serve stale runs;
    holding the block in the cache entry pins its id. Without this, every
    plan verify re-resolved every existing block's ~10k node ids through
    the row dict — the dominant cost of the coalesced pipeline's later
    verifies."""
    import numpy as np

    cache = table.block_rows_cache
    entry = cache.get(id(blk))
    if entry is not None and entry[0] is blk:
        return entry[1], entry[2]
    get = table.rows.get
    if blk.excluded:
        pairs = list(blk.live_node_counts())
        nids = [p[0] for p in pairs]
        counts = np.asarray([p[1] for p in pairs], dtype=np.int64)
    else:
        nids = blk.node_ids
        counts = np.asarray(blk.node_counts, dtype=np.int64)
    rows = np.fromiter(
        (get(nid, -1) for nid in nids), dtype=np.int64, count=len(nids)
    )
    cache[id(blk)] = (blk, rows, counts)
    if len(cache) > 256:
        cache.clear()
    return rows, counts


def _accumulate_block_usage(table, blocks, usage, net_rows):
    """Fold ``blocks`` into (usage[N,4], net_rows) — one np.add.at per
    block, per-block row resolution cached on the table. Mutates and
    returns the passed arrays (callers own them)."""
    import numpy as np

    for blk in blocks:
        rows, counts = _block_rows_cached(table, blk)
        valid = rows >= 0
        if _block_has_net(blk):
            if net_rows is None:
                net_rows = np.zeros(table.n, dtype=bool)
            net_rows[rows[valid]] = True
            continue
        vec = np.asarray(blk.resource_vector(), dtype=np.int64)
        if usage is None:
            usage = np.zeros((table.n, 4), dtype=np.int64)
        np.add.at(usage, rows[valid], vec[None, :] * counts[valid, None])
    return usage, net_rows


def _existing_block_usage_rows(snap, table):
    """Vectorized block usage over node-table rows: (usage[N,4] int64 or
    None, net_rows bool[N] or None, blocks).

    Incremental across the applier's verify sequence: blocks are COW
    (any exclusion/update/removal commits NEW objects), so while the
    snapshot's block identity-set only GROWS relative to the cached
    accumulation, only the new blocks fold in — a burst of K commits
    costs O(total runs) across its K verifies instead of O(K x total).
    Any removal (shrunk or replaced block) recomputes from scratch. The
    cache holds the block refs, pinning their ids against reuse; arrays
    are copied before extension so results already handed to concurrent
    readers never mutate underneath them."""
    blocks = snap.alloc_blocks()
    cache = table.block_usage_cache
    cur_ids = {id(b) for b in blocks}
    if cache is not None:
        cached_ids, _cached_refs, usage, net_rows = cache
        if cached_ids <= cur_ids:
            new = [b for b in blocks if id(b) not in cached_ids]
            if not new:
                return usage, net_rows, blocks
            usage = None if usage is None else usage.copy()
            net_rows = None if net_rows is None else net_rows.copy()
            usage, net_rows = _accumulate_block_usage(
                table, new, usage, net_rows
            )
            table.block_usage_cache = (cur_ids, list(blocks), usage,
                                       net_rows)
            return usage, net_rows, blocks
    usage, net_rows = _accumulate_block_usage(table, blocks, None, None)
    table.block_usage_cache = (cur_ids, list(blocks), usage, net_rows)
    return usage, net_rows, blocks


def _prevaluate_nodes_bulk(snap, plan: Plan, ask: _AskAccum = None,
                           table=None):
    """Bulk-verify the network-free nodes of a large plan: vectorized
    accumulation over the cached node table (one scatter-add per batch,
    per-node python only where object rows exist) + one native superset
    check. Nodes with any network asks (port collisions need the
    sequential NetworkIndex, funcs.go:73-86) stay out of the returned map
    and fall through to evaluate_node_plan. Returns {node_id: fit} — but
    a map with all_fit=True and no update batches in the plan may carry
    no entries at all (see _FitMap)."""
    if table is None:
        table = _node_table(snap)
    if ask is None:
        import numpy as np

        ask = _AskAccum()
        for b in plan.alloc_batches:
            ask.add_batch(
                b.node_ids, b.node_counts,
                np.asarray(b.resource_vector(), dtype=np.int64),
                src=b.src_hint,
            )
    if table is None:
        batch_dict = {}
        for nid in ask.node_ids:
            vec = ask.get(nid)
            if vec is not None:
                batch_dict[nid] = vec
        return _prevaluate_nodes_bulk_dict(snap, plan, batch_dict)
    return _prevaluate_nodes_bulk_rows(snap, plan, ask, table)


def _prevaluate_nodes_bulk_rows(snap, plan: Plan, ask: _AskAccum, table):
    import numpy as np

    from nomad_tpu import native

    out = _FitMap()

    block_usage, net_rows, blocks = _existing_block_usage_rows(snap, table)
    obj_nodes = snap.nodes_with_object_allocs()

    if not plan.node_allocation and not plan.node_update and not obj_nodes:
        # Pure-columnar fast path (the fresh-registration headline): no
        # per-node object rows anywhere, so the entire verify is array
        # indexing — the python walk below costs ~0.5us/node x 10k nodes
        # per eval, all of it avoidable here. Row resolution happens ONCE
        # per ask batch and serves both the ask accumulation and the fit
        # answer (ask.to_rows would re-resolve the same ids a second
        # time — the duplicate was ~2.5ms/eval at headline scale).
        if table.n == 0:
            # Every node deregistered since the solve: nothing fits.
            for nid in ask.node_ids:
                out[nid] = False
            return out
        ask_arr, flat_ids, rows = ask.accumulate_rows(table)
        # Duplicate ids across batches resolve to the same row and get
        # the same (idempotent) answer — no dedup pass needed.
        valid = rows >= 0
        keep = valid.copy()
        safe_rows = np.where(valid, rows, 0)
        keep &= ~table.dead[safe_rows]
        # Unknown or dead nodes fail their fit outright.
        for i in np.flatnonzero(~keep):
            out[flat_ids[i]] = False
        # Nodes with port semantics take the sequential path: drop them
        # from the answer map (the caller falls through per node).
        sc = table.scalar_only[safe_rows]
        if net_rows is not None:
            sc = sc | net_rows[safe_rows]
        keep &= ~sc
        rows_arr = rows[keep]
        if rows_arr.size:
            used = table.reserved[rows_arr].copy()
            if block_usage is not None:
                used += block_usage[rows_arr]
            if ask_arr is not None:
                used += ask_arr[rows_arr]
            fit, _exhausted = native.fit_check(
                np.minimum(used, 2**31 - 1).astype(np.int32),
                table.totals[rows_arr],
            )
            if bool(keep.all()) and bool(fit.all()):
                # Every asked node is live, port-free, and fits. The
                # caller can commit the plan whole without the id-set
                # union or the all() scan.
                out.all_fit = True
                if not plan.update_batches:
                    # evaluate_plan's whole-commit return never reads the
                    # per-node entries when the plan carries no update
                    # batches either — skip the ~5k dict stores. Plans
                    # WITH delta-free update nodes still get populated
                    # answers for the per-node merge.
                    return out
            kept_idx = np.flatnonzero(keep)
            for i, ok in zip(kept_idx.tolist(), fit.tolist()):
                out[flat_ids[i]] = ok
        return out

    ids = [nid for nid, placed in plan.node_allocation.items() if placed]
    in_alloc = plan.node_allocation
    ids.extend(nid for nid in ask.node_ids if nid not in in_alloc)
    ask_arr = ask.to_rows(table)

    # Per-node python only where object rows force it (placement lists or
    # existing object allocs); pure columnar nodes ride the arrays.
    cache = _AllocVecCache()
    rows_get = table.rows.get
    dead = table.dead
    scalar_only = table.scalar_only
    kept_ids = []
    kept_rows = []
    adjust = {}  # position in kept -> extra int64[4]

    for nid in ids:
        row = rows_get(nid)
        if row is None or dead[row]:
            out[nid] = False
            continue
        if scalar_only[row] or (net_rows is not None and net_rows[row]):
            continue  # sequential port semantics: scalar path
        placements = plan.node_allocation.get(nid, ())
        extra = None
        if placements:
            extra, bail = cache.sum_counted(placements)
            if bail:
                continue
        if nid in obj_nodes:
            existing = filter_terminal_allocs(
                snap.allocs_by_node_objects(nid)
            )
            removed = {a.id for a in plan.node_update.get(nid, ())}
            removed.update(a.id for a in placements)
            ex_vec, bail = cache.sum_counted(existing, removed)
            if bail:
                continue
            if ex_vec is not None:
                extra = ex_vec if extra is None else extra + ex_vec
        if block_usage is not None and plan.node_update.get(nid):
            # Evictions of block members are invisible to the object walk:
            # subtract them here (stale ids subtract nothing).
            for a in plan.node_update[nid]:
                if any(blk.find(a.id) is not None for blk in blocks):
                    sub = -_res_vec(a.resources)
                    extra = sub if extra is None else extra + sub
        if extra is not None:
            adjust[len(kept_ids)] = extra
        kept_ids.append(nid)
        kept_rows.append(row)

    if not kept_ids:
        return out

    rows_arr = np.asarray(kept_rows, dtype=np.int64)
    used = table.reserved[rows_arr].copy()
    if block_usage is not None:
        used += block_usage[rows_arr]
    if ask_arr is not None:
        used += ask_arr[rows_arr]
    for pos, extra in adjust.items():
        used[pos] += extra
    fit, _exhausted = native.fit_check(
        np.minimum(used, 2**31 - 1).astype(np.int32),
        table.totals[rows_arr],
    )
    for nid, ok in zip(kept_ids, fit.tolist()):
        out[nid] = ok
    return out


def _prevaluate_nodes_bulk_dict(snap, plan: Plan, batch_ask=None):
    """Table-less fallback of the bulk verifier (states without the store
    internals): the per-node python walk. ``batch_ask`` maps node_id to
    the summed int64 resource vector of columnar placements."""
    import numpy as np

    from nomad_tpu import native

    batch_ask = batch_ask or {}
    out = {}
    ids = [nid for nid, placed in plan.node_allocation.items() if placed]
    ids.extend(nid for nid in batch_ask if nid not in plan.node_allocation)

    # Existing usage held in columnar blocks, accounted without
    # materialization; reads below then only walk the object table.
    block_usage, block_net_nodes, blocks = _existing_block_usage(snap)
    read_objects = getattr(snap, "allocs_by_node_objects", None)
    if read_objects is None:
        read_objects = snap.allocs_by_node
        block_usage, block_net_nodes, blocks = {}, set(), []

    def evicted_block_vec(nid):
        """Resource sum of this plan's evictions that live in blocks (the
        object walk below can't see them); stale eviction ids subtract
        nothing."""
        total = None
        for a in plan.node_update.get(nid, ()):
            if any(blk.find(a.id) is not None for blk in blocks):
                vec = _res_vec(a.resources)
                total = vec if total is None else total + vec
        return total

    totals_rows = []
    base_rows = []
    kept = []  # node ids eligible for the bulk check, in row order
    cache = _AllocVecCache()

    for nid in ids:
        node = snap.node_by_id(nid)
        if node is None or node.status != "ready" or node.drain:
            out[nid] = False
            continue
        if node.reserved is not None and node.reserved.networks:
            continue  # reserved-port semantics: scalar path
        if nid in block_net_nodes:
            continue  # network-carrying block members: scalar path
        placements = plan.node_allocation.get(nid, ())

        base = _res_vec(node.reserved)
        extra = batch_ask.get(nid)
        if extra is not None:
            base = base + extra
        blk_used = block_usage.get(nid)
        if blk_used is not None:
            base = base + blk_used
            if plan.node_update.get(nid):
                evicted = evicted_block_vec(nid)
                if evicted is not None:
                    base = base - evicted
        existing = filter_terminal_allocs(read_objects(nid))
        if existing:
            removed = {a.id for a in plan.node_update.get(nid, [])}
            removed.update(a.id for a in placements)
            ex_vec, bail = cache.sum_counted(existing, removed)
            if bail:
                continue
            if ex_vec is not None:
                base = base + ex_vec

        pl_vec, bail = cache.sum_counted(placements)
        if bail:
            continue
        ask = base if pl_vec is None else base + pl_vec

        kept.append(nid)
        totals_rows.append(_res_vec(node.resources))
        base_rows.append(ask)

    if not kept:
        return out

    used = np.asarray(base_rows, dtype=np.int64)
    fit, _exhausted = native.fit_check(
        np.minimum(used, 2**31 - 1).astype(np.int32),
        np.asarray(totals_rows, dtype=np.int32),
    )
    for nid, ok in zip(kept, fit.tolist()):
        out[nid] = ok
    return out


def evaluate_plan(snap, plan: Plan, reservations=None) -> PlanResult:
    """Determine the committable subset of a plan (plan_apply.go:164-227).

    Columnar batches verify without expansion: each batch contributes
    ``count x resource-vector`` per node run, folded into the same per-node
    fit checks as the object placements; committed batches are the runs on
    fitting nodes.

    ``reservations`` (optional) maps node id -> summed int64[4] debit of
    ACTIVE express capacity leases (server/express.py ReservationLedger;
    the caller excludes this plan's own lease). Debits fold into the ask
    on every touched node, so a slow-path plan cannot verify into
    capacity an uncommitted express placement holds — the
    reservation-aware half of the express lane's capacity-safety
    invariant. None/empty is decision-identical to the pre-express
    verifier."""
    import numpy as np

    result = PlanResult(
        node_update={},
        node_allocation={},
        failed_allocs=plan.failed_allocs,
    )

    # Per-node resource ask of the columnar placements, held by reference
    # and materialized per consumer (dense rows for the bulk verifier, a
    # lazy dict for the scalar fallback).
    batch_ask = _AskAccum()
    for b in plan.alloc_batches:
        vec = np.asarray(b.resource_vector(), dtype=np.int64)
        batch_ask.add_batch(b.node_ids, b.node_counts, vec,
                            src=b.src_hint)

    # In-place update batches contribute their per-node (new - old)
    # resource delta; delta-free nodes only need a liveness check. Wire-
    # received batches resolve ids against this snapshot first (stale ids
    # drop out -> partial commit). Old vectors are identity-counted: a
    # batch's allocs share a handful of Resources objects, so per-alloc
    # work is dict hits, not numpy.
    upd_nodes = set()
    for b in plan.update_batches:
        b.resolve(snap)
        new_vec = np.asarray(b.resource_vector(), dtype=np.int64)
        if b.src_node_ids:
            # Block-columnar form: one shared old vector, node runs as
            # columns — the whole batch is a single accumulator entry.
            upd_nodes.update(b.src_node_ids)
            old_vec = (
                np.asarray(b.src_resources.as_vector(), dtype=np.int64)
                if b.src_resources is not None
                else np.zeros(4, dtype=np.int64)
            )
            delta = new_vec - old_vec
            if np.any(delta):
                batch_ask.add_batch(
                    b.src_node_ids, b.src_node_counts, delta
                )
            continue
        # One old-vector per Resources identity (a batch's allocs share a
        # handful), node multiplicities per identity — then the whole
        # delta lands as ONE accumulator batch, expanded vectorized by
        # to_rows; no per-alloc numpy at all.
        res_vecs = {}
        per_res_counts: Dict[int, Dict[str, int]] = {}
        for a in b.allocs:
            upd_nodes.add(a.node_id)
            rid = id(a.resources)
            if rid not in res_vecs:
                res_vecs[rid] = (
                    np.asarray(a.resources.as_vector(), dtype=np.int64)
                    if a.resources is not None
                    else np.zeros(4, dtype=np.int64)
                )
            cnts = per_res_counts.setdefault(rid, {})
            cnts[a.node_id] = cnts.get(a.node_id, 0) + 1
        for rid, cnts in per_res_counts.items():
            delta = new_vec - res_vecs[rid]
            if np.any(delta):
                batch_ask.add_batch(
                    list(cnts.keys()), list(cnts.values()), delta
                )

    if reservations:
        # Restricted to nodes this plan touches: a lease elsewhere in
        # the cell must not drag untouched nodes into this plan's
        # verification (or flip an untouched node's fit to False and
        # bounce a plan that asked nothing of it).
        touched = (set(plan.node_allocation) | set(plan.node_update)
                   | set(batch_ask.node_ids) | upd_nodes)
        for nid, vec in reservations.items():
            if nid in touched:
                batch_ask.add_delta(nid, vec)

    bulk_fit = {}
    n_placements = sum(len(v) for v in plan.node_allocation.values())
    n_placements += sum(b.n for b in plan.alloc_batches)
    n_placements += sum(b.n for b in plan.update_batches)
    if n_placements >= FAST_VERIFY_THRESHOLD:
        # The node table is only worth building (or cache-fetching) for
        # plans large enough to ride the bulk verifier.
        bulk_fit = _prevaluate_nodes_bulk(
            snap, plan, batch_ask, _node_table(snap)
        )

    def batch_res(node_id):
        vec = batch_ask.get(node_id)
        if vec is None:
            return None
        from nomad_tpu.structs import Resources

        return Resources(
            cpu=int(vec[0]), memory_mb=int(vec[1]),
            disk_mb=int(vec[2]), iops=int(vec[3]),
        )

    fits = {}
    if (getattr(bulk_fit, "all_fit", False) and not upd_nodes
            and not plan.node_update and not plan.node_allocation):
        # The verifier already proved every asked node live and fitting
        # (and the plan has no delta-free update nodes needing their own
        # liveness check): commit whole without materializing the
        # per-node answer map or the id-set union at all.
        result.alloc_batches = [b for b in plan.alloc_batches if b.n]
        result.update_batches = [b for b in plan.update_batches if b.n]
        return result
    node_ids = (set(plan.node_update) | set(plan.node_allocation)
                | batch_ask.node_ids | upd_nodes)
    if (bulk_fit and len(bulk_fit) == len(node_ids)
            and all(bulk_fit.values())):
        # Bulk answered every node and every node fits — the common case
        # of a fresh large placement. Skip the 10k-iteration merge loop
        # and per-batch filter entirely: the plan commits whole.
        result.node_update = {k: v for k, v in plan.node_update.items() if v}
        result.node_allocation = {
            k: v for k, v in plan.node_allocation.items() if v
        }
        result.alloc_batches = [b for b in plan.alloc_batches if b.n]
        result.update_batches = [b for b in plan.update_batches if b.n]
        return result
    for node_id in node_ids:
        fit = bulk_fit.get(node_id)
        if fit is None:
            if (node_id in upd_nodes
                    and not plan.node_allocation.get(node_id)
                    and node_id not in batch_ask.node_ids
                    and not plan.node_update.get(node_id)):
                fit = _node_live(snap, node_id)
            else:
                fit = evaluate_node_plan(snap, plan, node_id, batch_res(node_id))
                if fit and node_id in upd_nodes:
                    # evaluate_node_plan's evict-only shortcut skips the
                    # liveness check; re-stamped allocs need a live node.
                    fit = _node_live(snap, node_id)
        fits[node_id] = fit
        if not fit:
            # Stale scheduler data: force a refresh to the latest view.
            result.refresh_index = max(
                snap.get_index("nodes"), snap.get_index("allocs")
            )
            if plan.all_at_once:
                result.node_update = {}
                result.node_allocation = {}
                return result
            continue
        if plan.node_update.get(node_id):
            result.node_update[node_id] = plan.node_update[node_id]
        if plan.node_allocation.get(node_id):
            result.node_allocation[node_id] = plan.node_allocation[node_id]
    for b in plan.alloc_batches:
        kept = b.filter_nodes(fits)
        if kept.n:
            result.alloc_batches.append(kept)
    for b in plan.update_batches:
        kept = b.filter_nodes(fits)
        if kept.n:
            result.update_batches.append(kept)
    return result


def _object_allocs(result: PlanResult) -> list:
    """The object-row part of a committed plan. Columnar placement AND
    update batches stay columnar all the way into the state store
    (state/blocks.py; FSM applies update batches as block field swaps)."""
    allocs: list = []
    for update_list in result.node_update.values():
        allocs.extend(update_list)
    for alloc_list in result.node_allocation.values():
        allocs.extend(alloc_list)
    allocs.extend(result.failed_allocs)
    return allocs
