"""Sparrow express lane: leader-local sub-millisecond placement.

The full eval→broker→worker→plan-pipeline→raft path costs ~19ms p50 at
steady-10k — the wrong cost model for millisecond-scale tasks. Sparrow
(PAPERS.md) buys three orders of magnitude for short tasks by trading
global optimality for latency; Omega's shared-state posture supplies the
reconciliation story for running a second, faster placement path against
the same cell. This module is that second path:

- **Eligibility.** A job opts in via the job model (``Job.express``,
  batch type, small task count, no network asks, no distinct-hosts
  semantics, not an update of a live job). The admission front door
  classifies express submissions into their own rate lane
  (``admission.LANE_EXPRESS``) — and the SLO-coupled shedder treats the
  lane as batch-yielding: a shed batch door sheds express too (express
  is a latency lane, not a rate-limit bypass).

- **Synchronous placement.** An eligible submission places IN-LINE on the
  leader: seeded power-of-``choices`` sampling (the ``express.pick``
  stream — Sparrow's batch sampling) over the delta-rolled
  ``MirrorCache`` mirror's capacity view (totals, delta-maintained base
  usage), debited by the reservation ledger below. The caller gets
  "placed" back in well under a millisecond; no broker, no worker pool,
  no plan queue on the submit path.

- **Leased capacity reservations.** Each placement takes a bounded,
  TTL-leased reservation (:class:`ReservationLedger`) on the chosen
  nodes' capacity, debited from the same capacity view the slow path
  reads at plan-verify time (plan_apply/plan_pipeline fold the ledger's
  per-node debits into verification), so a slow-path plan cannot take
  capacity an express placement was promised while its raft entry is
  still in flight. Lease TTLs carry seeded jitter (the
  ``express.lease_jitter`` stream) so synchronized expiry can't stampede.

- **Asynchronous commit.** A committer thread replicates each placement
  through the ordinary machinery — job + completed eval through raft,
  then the allocations as an ``all_at_once`` plan through the optimistic
  plan pipeline (tagged ``Plan.express_lease`` so the pipeline skips
  broker bookkeeping and exempts the plan's OWN lease from the debits it
  verifies under). A verify-time failure — capacity taken after the
  lease was lost, a node died — is a typed, counted ``EXPRESS_BOUNCE``
  riding the pipeline's transaction-time conflict attribution
  (``PlanResult.conflict``): the committer re-places the SAME
  allocations (ids stable — exactly-once is per task) under a fresh
  lease and resubmits; past ``max_bounces`` (or on leadership loss) it
  reconciles through the slow path — a fresh PENDING evaluation that the
  ordinary scheduler places, forwarded to the current leader
  (``Express.Reconcile``). All-at-once plans make a bounce atomic:
  either every member commits in one entry or none do, so a task can
  never be half-placed across attempts.

Failure posture: the ledger is leader-local and volatile by design. On
leadership loss the lane demotes (leases cleared, counted as lost) and
every still-uncommitted entry reconciles to the new leader, whose own
ledger starts empty — correct, because its state view contains no
uncommitted express capacity; the reconciliation evals re-enter through
``restore_eval_broker``'s ordinary pending-eval requeue. The safety
invariant (fuzz-pinned in tests/test_express.py) is: express placements
NEVER violate capacity the slow path believes in — an express allocation
only becomes durable through verified plan commit — and every express
task places exactly once across bounces, lease expiry and failover.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu import prng, structs, telemetry, trace
from nomad_tpu.structs import (
    Allocation,
    AllocMetric,
    Evaluation,
    Job,
    Plan,
    Resources,
    generate_uuid,
)

# Evaluation.triggered_by for express placements (sync path) and for the
# slow-path reconciliation evals a bounced-out/failed-over entry falls
# back to (canonical definitions in structs.py — the generic scheduler's
# trigger allowlist reads the same constant).
EVAL_TRIGGER_EXPRESS = structs.EVAL_TRIGGER_EXPRESS
EVAL_TRIGGER_EXPRESS_RECONCILE = structs.EVAL_TRIGGER_EXPRESS_RECONCILE

# Typed committer outcomes (counters + the bounded decision ring; NOT
# event types — bounce counts depend on commit/solve interleaving, and
# events would make the canonical digest timing-dependent).
EXPRESS_COMMITTED = "EXPRESS_COMMITTED"
EXPRESS_BOUNCE = "EXPRESS_BOUNCE"
EXPRESS_RECONCILED = "EXPRESS_RECONCILED"
EXPRESS_LEASE_EXPIRED = "EXPRESS_LEASE_EXPIRED"

# Bounded committer-outcome ring depth (the admission decision-ring
# posture: enough to see a bounce storm's shape, never its own queue).
OUTCOME_RING = 256


@dataclass
class ExpressConfig:
    """Express-lane tunables. Default-OFF: with ``enabled=False`` the
    lane constructs but never places, draws nothing, and publishes
    nothing — the decision-invariance the banked steady-10k digests pin."""

    enabled: bool = False
    # Reservation lease TTL (seconds) and the jitter fraction added on
    # top (ttl * U[0, jitter) via the express.lease_jitter stream).
    lease_ttl: float = 2.0
    lease_jitter: float = 0.5
    # Bound on outstanding leases (≈ uncommitted express submissions).
    # At the cap new submissions fall back to the slow path, typed.
    max_leases: int = 4096
    # Sampling: up to ``probes`` seeded row draws per member, placing on
    # the best of the first ``choices`` that fit (Sparrow's power of two
    # choices; more probes = better packing, more latency).
    probes: int = 16
    choices: int = 2
    # Eligibility ceiling: larger jobs take the solver path, where the
    # device bin-pack earns its latency.
    max_tasks: int = 16
    # Bound on the committer backlog; at the cap submissions fall back
    # to the slow path (the front door already rate-bounds offered load;
    # this bounds the lane's own queue).
    max_pending: int = 512
    # Verify-time bounces before an entry reconciles via the slow path.
    max_bounces: int = 32

    @classmethod
    def parse(cls, spec: Optional[Dict[str, Any]]) -> "ExpressConfig":
        """Validated construction from the ``server { express { ... } }``
        config block — typos and nonsense ranges fail at parse time, the
        AdmissionConfig posture."""
        if spec is None:
            return cls()
        if not isinstance(spec, dict):
            raise ValueError("express config must be a mapping")
        known = set(cls.__dataclass_fields__)
        unknown = [k for k in spec if k not in known]
        if unknown:
            raise ValueError(
                f"unknown express config key(s): {sorted(unknown)} "
                f"(have: {sorted(known)})"
            )
        out = cls(**{
            k: (bool(v) if k == "enabled"
                else int(v) if k in ("max_leases", "probes", "choices",
                                     "max_tasks", "max_pending",
                                     "max_bounces")
                else float(v))
            for k, v in spec.items()
        })
        if out.lease_ttl <= 0:
            raise ValueError("express.lease_ttl must be > 0")
        if not 0 <= out.lease_jitter <= 4:
            raise ValueError("express.lease_jitter must be in [0, 4]")
        for knob, lo, hi in (("max_leases", 1, 1_000_000),
                             ("probes", 1, 4096),
                             ("choices", 1, 64),
                             ("max_tasks", 1, 4096),
                             ("max_pending", 1, 1_000_000),
                             ("max_bounces", 0, 10_000)):
            v = getattr(out, knob)
            if not lo <= v <= hi:
                raise ValueError(
                    f"express.{knob} must be in [{lo}, {hi}], got {v}"
                )
        if out.choices > out.probes:
            raise ValueError("express.choices must be <= express.probes")
        return out


class _IdPool:
    """Amortized uuid source: ONE urandom read (structs.generate_uuids)
    serves many ids. An os.urandom syscall can cost ~0.2ms under
    sandboxed kernels, and a submission needs several ids — drawn
    one-by-one they would eat most of the sub-millisecond budget."""

    __slots__ = ("_ids", "_lock")

    BATCH = 256  # ids per refill

    def __init__(self):
        import threading as _threading

        self._ids: List[str] = []
        self._lock = _threading.Lock()

    def take(self) -> str:
        from nomad_tpu.structs import generate_uuids

        with self._lock:
            if not self._ids:
                self._ids = generate_uuids(self.BATCH)
            return self._ids.pop()


class Lease:
    """One submission's leased capacity: per-node int64[4] debits plus a
    monotonic-clock expiry."""

    __slots__ = ("id", "eval_id", "debits", "expires", "granted_ttl")

    def __init__(self, eval_id: str, debits: Dict[str, np.ndarray],
                 expires: float, granted_ttl: float,
                 lease_id: str = ""):
        self.id = lease_id or generate_uuid()
        self.eval_id = eval_id
        self.debits = debits
        self.expires = expires
        self.granted_ttl = granted_ttl


class ReservationLedger:
    """Bounded ledger of TTL-leased capacity reservations.

    The slow path reads it at plan-verify time (``debit_map``); the
    express pick path reads it per candidate node (``node_debit``). All
    mutation is under one leaf lock — no other lock is ever taken while
    it is held (the lock-order gate pins this)."""

    def __init__(self, max_leases: int = 4096):
        self.max_leases = int(max_leases)
        self._lock = threading.Lock()
        self._leases: Dict[str, Lease] = {}
        # node id -> summed active debit (int64[4]); entries removed when
        # they fall to zero so debit_map stays O(touched nodes).
        self._by_node: Dict[str, np.ndarray] = {}
        self.granted = 0
        self.released = 0
        self.expired = 0
        self.rejected_full = 0
        self.peak_active = 0

    def reserve(self, eval_id: str, debits: Dict[str, np.ndarray],
                ttl: float, now: Optional[float] = None,
                lease_id: str = "") -> Optional[Lease]:
        """Grant one lease (None at the cap). ``debits`` maps node id to
        the summed int64[4] ask reserved on it."""
        if now is None:
            now = time.monotonic()
        lease = Lease(eval_id, {k: v.copy() for k, v in debits.items()},
                      now + ttl, ttl, lease_id=lease_id)
        with self._lock:
            if len(self._leases) >= self.max_leases:
                self.rejected_full += 1
                return None
            self._leases[lease.id] = lease
            for nid, vec in lease.debits.items():
                prev = self._by_node.get(nid)
                self._by_node[nid] = (
                    vec.copy() if prev is None else prev + vec
                )
            self.granted += 1
            self.peak_active = max(self.peak_active, len(self._leases))
        return lease

    def _drop_locked(self, lease: Lease) -> None:
        for nid, vec in lease.debits.items():
            cur = self._by_node.get(nid)
            if cur is None:
                continue
            cur = cur - vec
            if (cur <= 0).all():
                self._by_node.pop(nid, None)
            else:
                self._by_node[nid] = cur

    def release(self, lease_id: str) -> bool:
        """Idempotent release (False if already released/expired)."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return False
            self._drop_locked(lease)
            self.released += 1
        return True

    def expire_due(self, now: Optional[float] = None) -> List[Lease]:
        """Drop every lease past its TTL; returns them (the committer
        counts and the test clock can force expiry by passing ``now``)."""
        if now is None:
            now = time.monotonic()
        out: List[Lease] = []
        with self._lock:
            for lid in [lid for lid, l in self._leases.items()
                        if l.expires <= now]:
                lease = self._leases.pop(lid)
                self._drop_locked(lease)
                self.expired += 1
                out.append(lease)
        return out

    def clear(self) -> int:
        """Drop everything (leadership loss). Returns the count lost."""
        with self._lock:
            n = len(self._leases)
            self._leases.clear()
            self._by_node.clear()
        return n

    def holds(self, lease_id: str) -> bool:
        with self._lock:
            return lease_id in self._leases

    def active(self) -> int:
        with self._lock:
            return len(self._leases)

    def node_debit(self, node_id: str) -> Optional[np.ndarray]:
        """Summed active debit on one node (shared array — copy before
        mutation), or None."""
        with self._lock:
            return self._by_node.get(node_id)

    def debit_map(self, exclude: Tuple[str, ...] = ()) -> Dict[str, np.ndarray]:
        """{node id: summed int64[4] debit} over active leases, minus the
        ``exclude``d lease ids (a plan verifying its own lease must not
        double-count itself). Fresh arrays — callers may mutate."""
        with self._lock:
            if not self._leases:
                return {}
            out = {nid: vec.copy() for nid, vec in self._by_node.items()}
            for lid in exclude:
                lease = self._leases.get(lid)
                if lease is None:
                    continue
                for nid, vec in lease.debits.items():
                    cur = out.get(nid)
                    if cur is None:
                        continue
                    cur -= vec
                    if (cur <= 0).all():
                        out.pop(nid, None)
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            reserved = [int(x) for x in (
                sum(self._by_node.values(), np.zeros(4, dtype=np.int64))
            )] if self._by_node else [0, 0, 0, 0]
            return {
                "active": len(self._leases),
                "nodes_debited": len(self._by_node),
                "max_leases": self.max_leases,
                "granted": self.granted,
                "released": self.released,
                "expired": self.expired,
                "rejected_full": self.rejected_full,
                "peak_active": self.peak_active,
                "reserved_vector": reserved,
            }


class _MaskCtx:
    """Minimal context for mirror constraint masks (check_constraint only
    reads the regex compile cache)."""

    __slots__ = ("regexp_cache",)

    def __init__(self):
        self.regexp_cache: Dict[str, Any] = {}


def express_eligible(job: Job, config: ExpressConfig) -> bool:
    """Static (job-shape) half of eligibility; the lane's ``submit``
    additionally rejects updates of live jobs and falls back when no
    capacity sample fits. Express handles exactly the shapes the sync
    pick can answer: small batch jobs, no ports, no distinct-hosts."""
    if not config.enabled or not getattr(job, "express", False):
        return False
    if job.type != structs.JOB_TYPE_BATCH:
        return False
    total = sum(tg.count for tg in job.task_groups)
    if not 0 < total <= config.max_tasks:
        return False
    for c in job.constraints:
        if c.operand == structs.CONSTRAINT_DISTINCT_HOSTS:
            return False
    for tg in job.task_groups:
        for c in tg.constraints:
            if c.operand == structs.CONSTRAINT_DISTINCT_HOSTS:
                return False
        for task in tg.tasks:
            if task.resources is not None and task.resources.networks:
                return False  # port semantics need the sequential index
    return True


class _CapacityView:
    """One datacenter set's cached capacity view: the mirror's node list
    + totals next to the delta-rolled base usage. Built/refreshed OFF
    the submit path (the committer thread's cadence): rolling usage
    forward under a 10k-node service load costs milliseconds, which is
    the whole sub-ms budget. Staleness is bounded (VIEW_REFRESH) and
    safe: the ledger covers express-vs-express, verify is authoritative
    for everything else — a stale view costs at worst a bounce."""

    __slots__ = ("nodes", "mirror", "totals", "used", "at")

    def __init__(self, nodes, mirror, totals, used, at):
        self.nodes = nodes
        self.mirror = mirror
        self.totals = totals
        self.used = used
        self.at = at


class _PendingCommit:
    """One placed-but-uncommitted submission in the committer queue."""

    __slots__ = ("job", "ev", "allocs", "lease", "bounces", "durable",
                 "enqueued")

    def __init__(self, job: Job, ev: Evaluation, allocs: List[Allocation],
                 lease: Lease):
        self.job = job
        self.ev = ev
        self.allocs = allocs
        self.lease = lease
        self.bounces = 0
        # job+eval raft entries committed (survives bounce retries).
        self.durable = False
        self.enqueued = time.perf_counter()


class ExpressLane:
    """The leader-local express placement lane. One per server; consulted
    by ``Server.job_register`` after admission for express-eligible jobs.
    ``submit`` returns ``(eval_id, index)`` with the placement made
    in-line, or None — the caller then takes the ordinary slow path (a
    fallback, never an error: express is an optimization, the broker
    path is the contract)."""

    def __init__(self, server, config: Optional[ExpressConfig] = None):
        self.server = server
        self.config = config or ExpressConfig()
        self.ledger = ReservationLedger(self.config.max_leases)
        seed = getattr(server.config, "seed", 0)
        # Seeded decision streams (nomad_tpu/prng.py): candidate rows and
        # lease jitter replay per seed; draws are serialized under
        # _lock so the n-th draw is a pure function of the submission
        # sequence.
        self._pick = prng.stream(seed, "express.pick")
        self._jitter = prng.stream(seed, "express.lease_jitter")
        self._mask_ctx = _MaskCtx()
        self._ids = _IdPool()
        # Per-datacenter-set capacity views, swapped atomically by the
        # committer thread's refresh cadence (see _CapacityView).
        self._views: Dict[Tuple[str, ...], _CapacityView] = {}
        self._lock = threading.Lock()
        self._pending: "collections.deque[_PendingCommit]" = collections.deque()
        # Job id -> eval id of entries placed but not yet durably
        # handled (committed or reconciled): the duplicate-submission
        # guard across the async-commit window, where job_by_id can't
        # answer yet. A same-job retry gets the ORIGINAL eval id back —
        # the idempotent answer a client retrying a timed-out register
        # expects — instead of a second placement.
        self._inflight_jobs: Dict[str, str] = {}
        self._wake = threading.Condition(self._lock)
        self._stop = threading.Event()
        # Test seam: committer processes entries only while set (tests
        # clear it to hold a lease mid-commit; production never touches).
        self.commit_gate = threading.Event()
        self.commit_gate.set()
        self._thread: Optional[threading.Thread] = None
        # Books (mutated under _lock; read lock-free for exposition).
        self.placed = 0
        self.tasks_placed = 0
        self.committed = 0
        self.bounces = 0
        self.conflicts = 0
        self.reconciled = 0
        self.duplicates = 0
        self.fallbacks: Dict[str, int] = {}
        self.place_sample = telemetry.AggregateSample()
        self._outcomes: "collections.deque" = collections.deque(
            maxlen=OUTCOME_RING)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if not self.config.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._commit_loop, daemon=True, name="express-commit",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._wake:
            self._wake.notify_all()
        # Join the committer first: an entry it popped just before the
        # stop must finish (or fail into the drain below) rather than
        # race interpreter teardown on a daemon thread.
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        # Best-effort drain: placed-but-uncommitted entries reconcile to
        # durable pending evals before the lane goes dark — the callers
        # were already told "placed", and a clean (rolling-restart)
        # shutdown must not silently lose that work. Runs after _stop so
        # the committer can't double-pop; raft/forwarding is still up
        # (the server tears the lane down first).
        while True:
            with self._wake:
                if not self._pending:
                    break
                entry = self._pending.popleft()
            try:
                self._reconcile(entry, reason="shutdown")
            except Exception:
                # Per-entry isolation: one failed reconcile (a transient
                # forward error) must not abandon the REST of the
                # backlog — every entry is a caller already answered
                # "placed".
                telemetry.incr_counter(("express", "reconcile_error"))
                self.server.logger.exception(
                    "express shutdown drain failed for eval %s",
                    entry.ev.id)
            finally:
                self._job_done(entry.job.id)

    def demote(self) -> None:
        """Leadership lost: leases are meaningless against a stale view.
        Pending entries stay queued — the committer reconciles them to
        the current leader (their job/eval/alloc entries were never
        committed here, so the slow path places them exactly once)."""
        lost = self.ledger.clear()
        if lost:
            telemetry.incr_counter(("express", "leases_lost"), lost)

    # -- the submit path (synchronous, sub-millisecond) ----------------------

    def submit(self, job: Job, client_id: str = "",
               ) -> Optional[Tuple[str, int]]:
        """Place ``job`` in-line under a leased reservation and hand the
        raft commit to the committer. None = take the slow path."""
        if not express_eligible(job, self.config):
            return None
        t0 = time.perf_counter()
        state = self.server.state_store
        if state.job_by_id(job.id) is not None:
            # Updates of a live job need the reconciler's diff semantics.
            # Checked against the LIVE store (not the reused snapshot):
            # a double-submit inside the snapshot window must still fall
            # to the slow path's idempotent upsert.
            return self._fallback("job_exists")
        view = self._view(tuple(job.datacenters))
        eval_id = self._ids.take()
        # Decide under the lock, act outside it (_fallback re-takes the
        # lock to count). The in-flight map closes the async-commit
        # window: a same-job retry arriving before the first entry's
        # raft job_register lands gets the FIRST submission's eval id
        # back (idempotent retry) instead of a second placement —
        # committed state alone can't see the duplicate yet. An empty
        # value is the pre-enqueue placeholder: the winner is still
        # placing (sub-ms), so the retry parks on the lane condition
        # until the entry resolves to an enqueued eval id (answer with
        # it) or is withdrawn (the winner fell back — take the slow
        # path too; a phantom id that no one will ever commit must
        # never be handed out).
        with self._wake:
            declined = None
            if job.id in self._inflight_jobs:
                self.duplicates += 1
                deadline = time.monotonic() + 2.0
                while True:
                    dup_eval = self._inflight_jobs.get(job.id)
                    if dup_eval is None:
                        declined = "job_exists"  # winner withdrew
                        break
                    if dup_eval:
                        break
                    if time.monotonic() >= deadline:
                        declined = "job_exists"
                        break
                    self._wake.wait(timeout=0.05)
            elif len(self._pending) >= self.config.max_pending:
                declined = "backlog_full"
            else:
                dup_eval = None
                self._inflight_jobs[job.id] = ""
        if declined is not None:
            return self._fallback(declined)
        if dup_eval:
            telemetry.incr_counter(("express", "duplicate"))
            return dup_eval, self.server.raft.applied_index
        # Re-check committed state AFTER installing the placeholder: a
        # prior same-id entry releases its guard only once its commit is
        # state-visible, so the pre-guard job_by_id check above races a
        # commit-then-release interleaving — guard-absent + job-present
        # here is exactly that committed case, and placing would double
        # the job.
        if state.job_by_id(job.id) is not None:
            self._job_done(job.id)
            return self._fallback("job_exists")
        try:
            return self._submit_reserved(job, client_id, eval_id, view, t0)
        except BaseException:
            # The guard placeholder must not outlive a failed
            # submission: a leaked entry would park every later
            # register of this job id on the duplicate wait.
            self._job_done(job.id)
            raise

    def _submit_reserved(self, job: Job, client_id: str, eval_id: str,
                         view: "_CapacityView", t0: float,
                         ) -> Optional[Tuple[str, int]]:
        """The placement half of submit(), run with the duplicate-guard
        placeholder held (the caller releases it on any exception; the
        fallback paths here release it inline)."""
        tracer = trace.get_tracer()
        root = tracer.start_span(eval_id, "express.place", root=True,
                                 annotations={"job_id": job.id,
                                              "client_id": client_id})
        pick_span = tracer.start_span(eval_id, "express.pick", parent=root)
        placement = self._place(job, view)
        pick_span.finish()
        if placement is None:
            root.annotate("fallback", True).finish()
            self._job_done(job.id)
            return self._fallback("no_fit")
        assignments, debits = placement
        lease_span = tracer.start_span(eval_id, "express.lease", parent=root)
        lease = self.ledger.reserve(eval_id, debits, self._lease_ttl(),
                                    lease_id=self._ids.take())
        lease_span.finish()
        if lease is None:
            root.annotate("fallback", True).finish()
            self._job_done(job.id)
            return self._fallback("ledger_full")

        ev = Evaluation(
            id=eval_id,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_EXPRESS,
            job_id=job.id,
            status=structs.EVAL_STATUS_COMPLETE,
            status_description="express placement",
        )
        allocs = self._materialize(job, ev, assignments, self._ids)
        entry = _PendingCommit(job, ev, allocs, lease)
        placed_ms = (time.perf_counter() - t0) * 1000.0
        events = getattr(self.server.fsm, "events", None)
        if events is not None:
            # ONE deterministic event per express submission (digest
            # contract: bounce/commit timing never shows in the stream).
            # Published BEFORE the committer can see the entry, so the
            # per-key type sequence is structurally ExpressPlaced-first
            # — the async commit's EvalUpdated/PlanApplied share this
            # key and must never race ahead of it. placed_ms lets
            # lifecycle/slo consumers build the express timeline
            # without new hot-path instruments.
            events.publish(
                "Express", "ExpressPlaced", key=eval_id,
                payload={
                    "job_id": job.id,
                    "tasks": len(allocs),
                    "placed_ms": round(placed_ms, 4),
                },
            )
        with self._wake:
            self._pending.append(entry)
            self.placed += 1
            self.tasks_placed += len(allocs)
            # Resolve the duplicate-guard placeholder: parked retries
            # wake to the real eval id.
            self._inflight_jobs[job.id] = eval_id
            self._wake.notify_all()
        self.place_sample.ingest(placed_ms)
        telemetry.incr_counter(("express", "placed"))
        telemetry.add_sample(("express", "place"), placed_ms)
        root.annotate("tasks", len(allocs)).finish()
        return eval_id, self.server.raft.applied_index

    # Capacity-view refresh cadence (seconds). Driven by the committer
    # thread so the submit path NEVER pays a snapshot copy or a usage
    # roll; the pick tolerates this much staleness by construction (the
    # ledger covers our own in-flight placements, plan verify is
    # authoritative for everything else).
    VIEW_REFRESH = 0.05
    MAX_VIEWS = 16

    def _build_view(self, dcs: Tuple[str, ...]) -> _CapacityView:
        from nomad_tpu.tpu.mirror import GLOBAL_MIRROR_CACHE

        snap = self.server.state_store.snapshot()
        nodes, mirror = GLOBAL_MIRROR_CACHE.get(snap, list(dcs))
        totals, used = mirror.capacity_view(snap)
        view = _CapacityView(nodes, mirror, totals, used,
                             time.monotonic())
        # Under the lane lock: cold-path submits (RPC threads) and the
        # committer's refresh both insert/evict here, and a concurrent
        # double-eviction would KeyError out of a client's register.
        with self._lock:
            views = self._views
            views[dcs] = view
            while len(views) > self.MAX_VIEWS:
                views.pop(next(iter(views)))
        return view

    def _view(self, dcs: Tuple[str, ...]) -> _CapacityView:
        view = self._views.get(dcs)
        if view is None:
            view = self._build_view(dcs)  # cold path (first submission)
        return view

    def _refresh_views(self) -> None:
        """Committer-cadence refresh of every known view (off the submit
        path by design — see VIEW_REFRESH)."""
        now = time.monotonic()
        for dcs, view in list(self._views.items()):
            if now - view.at >= self.VIEW_REFRESH:
                try:
                    self._build_view(dcs)
                except Exception:
                    # A torn refresh must not kill the committer; the
                    # stale view keeps serving (bounded by verify).
                    telemetry.incr_counter(
                        ("express", "view_refresh_error"))
                    self.server.logger.exception(
                        "express capacity-view refresh failed")

    def await_inflight(self, job_id: str, timeout: float = 5.0) -> bool:
        """Block until no express entry for ``job_id`` is mid-async-
        commit (True) or ``timeout`` lapses (False). The slow path calls
        this before registering a job the express lane declined: a
        same-id submission may still be committing, and the slow
        scheduler's snapshot must contain its allocations or the job
        double-places. No-op (no lock contention beyond one check) in
        the common case."""
        if job_id not in self._inflight_jobs:
            return True
        deadline = time.monotonic() + timeout
        with self._wake:
            while job_id in self._inflight_jobs:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    telemetry.incr_counter(
                        ("express", "await_inflight_timeout"))
                    return False
                self._wake.wait(timeout=min(0.05, remaining))
        return True

    def _lease_ttl(self) -> float:
        """Configured TTL plus seeded jitter (the express.lease_jitter
        stream) — the ONE definition fresh leases and bounce re-leases
        share, drawn under the lane lock so the stream replays."""
        with self._lock:
            return self.config.lease_ttl * (
                1.0 + self.config.lease_jitter * self._jitter.random()
            )

    def _fallback(self, why: str) -> None:
        with self._lock:
            self.fallbacks[why] = self.fallbacks.get(why, 0) + 1
        telemetry.incr_counter(("express", "fallback", why))
        return None

    def _place(self, job: Job, view: _CapacityView,
               allocs: Optional[List[Allocation]] = None,
               ) -> Optional[Tuple[List[Tuple[object, str]],
                                   Dict[str, np.ndarray]]]:
        """Seeded sampled placement of every member against the cached
        capacity view (delta-rolled mirror + base usage, refreshed off
        the submit path). Returns (assignments, per-node debit map) or
        None when any member finds no fit within the probe budget.
        ``allocs`` re-places existing members (the bounce path) instead
        of expanding the job's groups."""
        nodes, mirror = view.nodes, view.mirror
        n = mirror.n
        if n == 0:
            return None
        totals, used = view.totals, view.used

        def tg_mask(tg):
            """Eligibility mask for one task group (driver + job/tg
            constraints) — cached per mirror, so warm submissions pay
            dict hits."""
            m = mirror.driver_mask({t.driver for t in tg.tasks})
            if job.constraints:
                m = m & mirror.constraint_mask(
                    self._mask_ctx, job.constraints)
            if tg.constraints:
                m = m & mirror.constraint_mask(
                    self._mask_ctx, tg.constraints)
            return m

        # (payload, mask, vec) per member: payload is the task group on
        # a fresh placement (materialized after) or the existing
        # Allocation on a bounce re-place (id stable, node rewritten) —
        # BOTH paths enforce the same eligibility masks.
        members: List[Tuple[object, Optional[np.ndarray], np.ndarray]] = []
        if allocs is not None:
            masks: Dict[str, Optional[np.ndarray]] = {}
            for a in allocs:
                if a.task_group not in masks:
                    tg = job.lookup_task_group(a.task_group)
                    masks[a.task_group] = (
                        tg_mask(tg) if tg is not None else None
                    )
                members.append((a, masks[a.task_group], np.asarray(
                    a.resources.as_vector() if a.resources else (0,) * 4,
                    dtype=np.int64)))
        else:
            for tg in job.task_groups:
                vec = np.asarray(_group_resources(tg).as_vector(),
                                 dtype=np.int64)
                cmask = tg_mask(tg)
                for _ in range(tg.count):
                    members.append((tg, cmask, vec))
        assignments: List[Tuple[object, str]] = []
        debits: Dict[str, np.ndarray] = {}
        node_debit = self.ledger.node_debit
        cfg = self.config
        with self._lock:  # serialize the seeded draws
            for member, mask, vec in members:
                best_row = -1
                best_free = None
                fits = 0
                for _probe in range(cfg.probes):
                    row = self._pick.randrange(n)
                    if mask is not None and not mask[row]:
                        continue
                    nid = nodes[row].id
                    free = totals[row].astype(np.int64) \
                        - used[row].astype(np.int64) - vec
                    lease_d = node_debit(nid)
                    if lease_d is not None:
                        free = free - lease_d
                    local = debits.get(nid)
                    if local is not None:
                        free = free - local
                    if (free < 0).any():
                        continue
                    fits += 1
                    score = int(free[0]) + int(free[1])
                    if best_free is None or score > best_free:
                        best_free = score
                        best_row = row
                    if fits >= cfg.choices:
                        break
                if best_row < 0:
                    return None
                nid = nodes[best_row].id
                prev = debits.get(nid)
                debits[nid] = vec.copy() if prev is None else prev + vec
                assignments.append((member, nid))
        return assignments, debits

    @staticmethod
    def _materialize(job: Job, ev: Evaluation,
                     assignments, ids: _IdPool) -> List[Allocation]:
        """Allocation objects for a fresh placement (ids minted HERE and
        stable for the entry's lifetime — the exactly-once key)."""
        out: List[Allocation] = []
        per_tg: Dict[str, int] = {}
        for (tg, nid) in assignments:
            i = per_tg.get(tg.name, 0)
            per_tg[tg.name] = i + 1
            res = _group_resources(tg)
            out.append(Allocation(
                id=ids.take(),
                eval_id=ev.id,
                name=f"{job.name}.{tg.name}[{i}]",
                node_id=nid,
                job_id=job.id,
                job=job,
                task_group=tg.name,
                resources=res,
                task_resources={
                    t.name: t.resources.copy()
                    for t in tg.tasks if t.resources is not None
                },
                metrics=AllocMetric(),
                desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
                client_status=structs.ALLOC_CLIENT_STATUS_PENDING,
            ))
        return out

    # -- the committer (asynchronous raft) -----------------------------------

    def _commit_loop(self) -> None:
        while not self._stop.is_set():
            self.commit_gate.wait(timeout=0.05)
            expired = self.ledger.expire_due()
            if expired:
                telemetry.incr_counter(
                    ("express", "lease_expired"), len(expired))
                self._outcome(EXPRESS_LEASE_EXPIRED,
                              eval_id=expired[0].eval_id,
                              count=len(expired))
            # Capacity views refresh HERE, on the committer's clock —
            # never on the submit path (see _CapacityView).
            self._refresh_views()
            with self._wake:
                if not self._pending:
                    self._wake.wait(timeout=0.05)
                if not self._pending or not self.commit_gate.is_set():
                    continue
                entry = self._pending.popleft()
            try:
                self._commit(entry)
            except Exception as e:
                # The placement was answered optimistically; losing the
                # entry here would break exactly-once. Reconcile through
                # the slow path — and count it: a committer that falls
                # back under no failure is a sick lane.
                telemetry.incr_counter(("express", "commit_error"))
                self.server.logger.exception(
                    "express commit failed for eval %s", entry.ev.id)
                try:
                    self._reconcile(entry, reason=f"commit_error: {e}")
                except Exception:
                    telemetry.incr_counter(("express", "reconcile_error"))
                    self.server.logger.exception(
                        "express reconcile failed for eval %s", entry.ev.id)
            finally:
                self._job_done(entry.job.id)

    def _commit(self, entry: _PendingCommit) -> None:
        from nomad_tpu.raft import NotLeaderError

        tracer = trace.get_tracer()
        span = tracer.start_span(entry.ev.id, "express.commit",
                                 parent=tracer.root_ctx(entry.ev.id))
        try:
            if not entry.durable:
                try:
                    self.server.raft.apply(
                        "job_register", {"job": entry.job}).result()
                    self.server.raft.apply(
                        "eval_update", {"evals": [entry.ev]}).result()
                except NotLeaderError:
                    self._reconcile(entry, reason="not_leader")
                    return
                entry.durable = True
            while True:
                if self.server.state_store.has_allocs_for_job(
                        entry.job.id):
                    # Another registration path placed this job while
                    # our commit was in flight (a concurrent slow-path
                    # submit of the same id is invisible to the
                    # duplicate guard): don't double-commit — the
                    # reconcile eval's ordinary scheduler dedupes
                    # against the live allocs (noop when the job is
                    # whole). A commit racing the other plan inside one
                    # pipeline cycle can still slip this check — the
                    # residual window of the leader-local trade; verify
                    # stays capacity-safe either way.
                    self._reconcile(entry,
                                    reason="concurrent_registration")
                    return
                plan = Plan(
                    eval_id=entry.ev.id,
                    priority=entry.ev.priority,
                    all_at_once=True,  # bounce atomically: never half-place
                    snapshot_index=self.server.raft.applied_index,
                    express_lease=entry.lease.id,
                )
                for a in entry.allocs:
                    plan.append_alloc(a)
                try:
                    result = self.server.plan_submit(plan)
                except NotLeaderError:
                    self._reconcile(entry, reason="not_leader")
                    return
                if result is not None and not result.refresh_index:
                    self.ledger.release(entry.lease.id)
                    with self._lock:
                        self.committed += 1
                    telemetry.incr_counter(("express", "committed"))
                    self._outcome(EXPRESS_COMMITTED, eval_id=entry.ev.id,
                                  tasks=len(entry.allocs),
                                  bounces=entry.bounces)
                    span.annotate("bounces", entry.bounces)
                    return
                # EXPRESS_BOUNCE: the all_at_once plan committed nothing.
                conflict = bool(result is not None and result.conflict)
                entry.bounces += 1
                lease_lost = not self.ledger.release(entry.lease.id)
                with self._lock:
                    self.bounces += 1
                    if conflict:
                        self.conflicts += 1
                telemetry.incr_counter(("express", "bounce"))
                if conflict:
                    telemetry.incr_counter(("express", "bounce_conflict"))
                self._outcome(EXPRESS_BOUNCE, eval_id=entry.ev.id,
                              conflict=conflict, lease_lost=lease_lost,
                              bounce=entry.bounces)
                if entry.bounces > self.config.max_bounces:
                    self._reconcile(entry, reason="max_bounces")
                    return
                # Re-place the SAME allocations (ids stable) under a
                # fresh lease against a FRESH view (a bounce means the
                # cached one lied; re-picking against it would re-bounce).
                view = self._build_view(tuple(entry.job.datacenters))
                placement = self._place(entry.job, view,
                                        allocs=entry.allocs)
                if placement is None:
                    self._reconcile(entry, reason="no_fit_on_bounce")
                    return
                assignments, debits = placement
                lease = self.ledger.reserve(entry.ev.id, debits,
                                            self._lease_ttl())
                if lease is None:
                    self._reconcile(entry, reason="ledger_full_on_bounce")
                    return
                entry.lease = lease
                for (alloc, nid) in assignments:
                    alloc.node_id = nid
        finally:
            span.finish()

    def _reconcile(self, entry: _PendingCommit, reason: str) -> None:
        """Slow-path reconciliation: hand the task to the ordinary
        scheduler via a PENDING eval on the CURRENT leader
        (``Server.express_reconcile`` applies locally on a leader and
        forwards ``Express.Reconcile`` otherwise). Nothing of this entry
        ever committed as allocations (all_at_once bounces are atomic;
        not_leader means even the job/eval entries may be absent), so the
        fresh eval places each task exactly once. The ORIGINAL express
        eval commits COMPLETE alongside, chained via next_eval — the
        submitter was handed that id and must see it reach a terminal
        status (quiesce/monitor loops poll it)."""
        self.ledger.release(entry.lease.id)
        ev = Evaluation(
            id=generate_uuid(),
            priority=entry.ev.priority,
            type=entry.job.type,
            triggered_by=EVAL_TRIGGER_EXPRESS_RECONCILE,
            job_id=entry.job.id,
            status=structs.EVAL_STATUS_PENDING,
            status_description=f"express reconcile ({reason})",
        )
        original = entry.ev.copy()
        original.status = structs.EVAL_STATUS_COMPLETE
        original.status_description = f"express reconciled ({reason})"
        original.next_eval = ev.id
        self.server.express_reconcile(entry.job, [original, ev])
        with self._lock:
            self.reconciled += 1
        telemetry.incr_counter(("express", "reconciled"))
        self._outcome(EXPRESS_RECONCILED, eval_id=entry.ev.id,
                      reason=reason, new_eval=ev.id)

    def _job_done(self, job_id: str) -> None:
        """Release the duplicate-submission guard for one job id (entry
        durably handled, or the submission fell back before enqueue).
        Wakes retries parked on the pre-enqueue placeholder."""
        with self._wake:
            self._inflight_jobs.pop(job_id, None)
            self._wake.notify_all()

    def _outcome(self, kind: str, **kw) -> None:
        kw["outcome"] = kind
        # nomadlint: allow(DET002) -- operator-facing decision-ring stamp
        # on /v1/agent/express; never interval math.
        kw["time"] = time.time()
        self._outcomes.append(kw)

    # -- exposition ----------------------------------------------------------

    def backlog(self) -> int:
        with self._lock:
            return len(self._pending)

    def summary(self) -> Dict[str, Any]:
        return {
            "enabled": self.config.enabled,
            "placed": self.placed,
            "tasks_placed": self.tasks_placed,
            "committed": self.committed,
            "bounces": self.bounces,
            "conflicts": self.conflicts,
            "reconciled": self.reconciled,
            "duplicates": self.duplicates,
            "fallbacks": dict(self.fallbacks),
            "backlog": self.backlog(),
            "leases": self.ledger.active(),
        }

    def snapshot(self) -> Dict[str, Any]:
        """The /v1/agent/express body (and the debug bundle's ``express``
        section): config, books, place-latency quantiles, the ledger, and
        the recent committer outcomes."""
        q = self.place_sample.quantiles()
        return {
            **self.summary(),
            "config": {
                "lease_ttl": self.config.lease_ttl,
                "lease_jitter": self.config.lease_jitter,
                "max_leases": self.config.max_leases,
                "probes": self.config.probes,
                "choices": self.config.choices,
                "max_tasks": self.config.max_tasks,
                "max_pending": self.config.max_pending,
                "max_bounces": self.config.max_bounces,
            },
            "place_ms": {
                "count": self.place_sample.count,
                "mean": round(self.place_sample.mean, 4),
                "max": round(self.place_sample.max, 4),
                **{k: round(v, 4) for k, v in q.items()},
            },
            "ledger": self.ledger.stats(),
            "recent_outcomes": list(self._outcomes),
        }


def _group_resources(tg) -> Resources:
    """Summed task-group resources (the alloc-level vector the verifier
    and the mirror usage read)."""
    total = Resources()
    for task in tg.tasks:
        total.add(task.resources)
    return total
