"""TimeTable: Raft index <-> wall clock mapping for GC cutoffs.

Reference: /root/reference/nomad/timetable.go (5-minute granularity, 72h
retention, fsm.go:24-28).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import List, Tuple

DEFAULT_GRANULARITY = 5 * 60.0
DEFAULT_LIMIT = 72 * 3600.0


class TimeTable:
    def __init__(
        self,
        granularity: float = DEFAULT_GRANULARITY,
        limit: float = DEFAULT_LIMIT,
    ):
        self.granularity = granularity
        self.limit = limit
        self._lock = threading.Lock()
        # Sorted list of (timestamp, index)
        self._table: List[Tuple[float, int]] = []

    def witness(self, index: int, when: float = None) -> None:
        """Record (index, time), coalescing within granularity
        (timetable.go Witness)."""
        if when is None:
            # nomadlint: allow(DET002) -- the table IS the raft-index ->
            # wall-clock mapping and serializes across restarts; a
            # monotonic stamp would be meaningless in the next process.
            when = time.time()
        with self._lock:
            if self._table and when - self._table[-1][0] < self.granularity:
                return
            self._table.append((when, index))
            # Prune beyond the retention limit
            cutoff = when - self.limit
            while self._table and self._table[0][0] < cutoff:
                self._table.pop(0)

    def nearest_index(self, when: float) -> int:
        """Largest index witnessed at or before ``when``
        (timetable.go NearestIndex)."""
        with self._lock:
            pos = bisect.bisect_right([t for t, _ in self._table], when)
            if pos == 0:
                return 0
            return self._table[pos - 1][1]

    def serialize(self) -> List[Tuple[float, int]]:
        with self._lock:
            return list(self._table)

    def deserialize(self, table: List[Tuple[float, int]]) -> None:
        with self._lock:
            self._table = list(table)
