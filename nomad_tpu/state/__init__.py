from nomad_tpu.state.store import StateSnapshot, StateStore, StateRestore, WatchItem

__all__ = ["StateStore", "StateSnapshot", "StateRestore", "WatchItem"]
