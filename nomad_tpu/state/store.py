"""In-memory MVCC state store with snapshots and watch/notify.

Fresh design with the capabilities of the reference's go-memdb-backed
StateStore (/root/reference/nomad/state/state_store.go:28-815, schema at
nomad/state/schema.go:10-188, notify at nomad/state/notify.go):

- tables: ``index``, ``nodes``, ``jobs``, ``evals``, ``allocs``
- secondary indexes: allocs by (job, node, eval), evals by job
  (jobs-by-scheduler-type is a scan; the jobs table stays small)
- copy-on-write ``snapshot()`` giving an immutable point-in-time view
- per-item watch registration powering blocking queries
- ``restore()`` bulk loader used by snapshot/FSM restore

Instead of radix trees we keep plain dicts whose *container* is copied on
snapshot; stored objects are immutable by convention (callers pass ownership
on upsert and must not mutate afterwards — the same contract go-memdb
enforces, state_store.go:25-27).
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from nomad_tpu.state.blocks import StoredAllocBlock
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    AllocBatch,
    Allocation,
    Evaluation,
    Job,
    Node,
    generate_uuid,
)

# A watch item is a (kind, key) tuple, e.g. ("table", "nodes"),
# ("alloc_node", node_id). Mirrors nomad/watch/watch.go:11-37.
WatchItem = Tuple[str, str]

# Bounded change-log horizons (entries retained after a trim; trims fire at
# twice this length). Consumers holding tensors built at index N ask "what
# changed since N" and delta-patch instead of rebuilding (the device mirror,
# nomad_tpu/tpu/mirror.py); a log that no longer reaches back to N returns
# None and the consumer falls back to a full rebuild. The node horizon is
# sized for steady heartbeat/registration churn at 10k nodes; the alloc log
# holds one entry PER WRITE (a plan commit is one entry carrying its touched
# node ids), so a smaller entry count covers many plans.
NODE_LOG_HORIZON = 4096
ALLOC_LOG_HORIZON = 1024


def _log_node_change(t: "_Tables", index: int, node_id: str,
                     kind: str) -> None:
    """Append one node-table delta (lock held by the caller). ``kind`` is
    "insert" (new key), "update" (existing key re-written in place, dict
    order preserved) or "remove" — the distinction the mirror's roll
    forward needs to prove dict-iteration order didn't move. Trims rebind
    the list so snapshots sharing the old reference stay consistent."""
    log = t.node_log
    log.append((index, node_id, kind))
    if len(log) > 2 * NODE_LOG_HORIZON:
        t.node_log_floor = log[-NODE_LOG_HORIZON - 1][0]
        t.node_log = log[-NODE_LOG_HORIZON:]


def _log_alloc_nodes(t: "_Tables", index: int, node_ids) -> None:
    """Append one allocs-table delta: the node ids whose usage this write
    may have changed (lock held by the caller). One entry per write — a
    100k-placement plan commit is a single entry sharing the batch's id
    list, not 10k appends."""
    if not node_ids:
        return
    log = t.alloc_log
    log.append((index, tuple(node_ids)))
    if len(log) > 2 * ALLOC_LOG_HORIZON:
        t.alloc_log_floor = log[-ALLOC_LOG_HORIZON - 1][0]
        t.alloc_log = log[-ALLOC_LOG_HORIZON:]


def partition_node_changes(changes, rows_get, resolve):
    """Interpret a node change-log slice for a delta consumer holding
    rows keyed by ``rows_get`` (node_id → row or None). ``resolve``
    returns a node's current form, or None when it left the consumer's
    set. THE one interpreter of the log's (index, node_id, kind)
    semantics, shared by the device mirror and the plan applier's node
    table so the two can never diverge on the same feed.

    Returns ``(patches, appends)`` — in-place row rewrites and dict-tail
    appends (sorted in re-insertion order, which IS the store's
    iteration order for new keys) — or None when the slice can't be
    expressed as a delta: a resident node left the set or had its dict
    key re-inserted (its row, or iteration order, moves), or a
    pre-existing key entered the set mid-order."""
    last_insert: Dict[str, int] = {}
    removed: Set[str] = set()
    order: List[str] = []
    seen: Set[str] = set()
    for pos, (_idx, node_id, kind) in enumerate(changes):
        if node_id not in seen:
            seen.add(node_id)
            order.append(node_id)
        if kind == "remove":
            removed.add(node_id)
        elif kind == "insert":
            last_insert[node_id] = pos
    patches: List[Tuple[int, Node]] = []
    appends: List[Tuple[int, Node]] = []
    for node_id in order:
        node = resolve(node_id)
        row = rows_get(node_id)
        if row is not None:
            if node is None or node_id in removed:
                return None
            patches.append((row, node))
        elif node is not None:
            pos = last_insert.get(node_id)
            if pos is None:
                return None
            appends.append((pos, node))
        # else: irrelevant to this consumer's set.
    appends.sort()
    return patches, appends


def item_table(name: str) -> WatchItem:
    return ("table", name)


def item_node(node_id: str) -> WatchItem:
    return ("node", node_id)


def item_job(job_id: str) -> WatchItem:
    return ("job", job_id)


def item_eval(eval_id: str) -> WatchItem:
    return ("eval", eval_id)


def item_alloc(alloc_id: str) -> WatchItem:
    """Single-alloc watch item. Granularity contract: individual
    operations (object-row writes, per-member promotion/deletion) fire
    this; BULK columnar transitions (block commit, whole-block in-place
    swap, whole-eval reap) fire only container items (job/eval/node) —
    per-member fan-out would cost O(placements) per commit. Endpoints that
    long-poll one alloc must watch its node or job item."""
    return ("alloc", alloc_id)


def item_alloc_node(node_id: str) -> WatchItem:
    return ("alloc_node", node_id)


def item_alloc_job(job_id: str) -> WatchItem:
    return ("alloc_job", job_id)


def item_alloc_eval(eval_id: str) -> WatchItem:
    return ("alloc_eval", eval_id)


class _WatchTicket:
    """One registration's receipt: the items watched and the bucket
    generations sampled at registration time. ``_Watch.wait`` returns once
    any of the buckets moves past its sampled generation (or on timeout).
    Opaque to callers; built by ``_Watch.register``."""

    __slots__ = ("items", "buckets", "gens", "multi", "multi_gen")

    def __init__(self, items, buckets, gens, multi, multi_gen):
        self.items = items
        self.buckets = buckets
        self.gens = gens
        self.multi = multi
        self.multi_gen = multi_gen


class _Watch:
    """Coalesced index-bucketed watch registry (reference analog:
    nomad/state/notify.go — but redesigned for 50k-watcher fan-out).

    The original design kept one ``threading.Event`` per watcher per item;
    a publish then iterated and ``set()`` every parked event under one
    registry lock — O(watchers) Python work on the WRITER (often the FSM
    apply thread). At 50k blocking watchers of a hot item that is a
    multi-millisecond wake storm per write, paid by the control plane's
    hottest path (measured in tests/test_wake_storm.py).

    Here every WatchItem hashes into one of ``NUM_BUCKETS`` buckets, each
    a (generation counter, Condition) pair. A publish bumps the touched
    buckets' generations and ``notify_all``s their conditions — O(touched
    items), independent of watcher count. Watchers sample their buckets'
    generations at registration and park on the bucket condition; a
    generation moving past the sample is the wake. Items sharing a bucket
    cause spurious wakes (the waiter re-probes its index and re-parks —
    the blocking_query loop already does exactly that), never missed
    ones.

    No-lost-wakeup protocol (the same register-then-recheck discipline
    blocking.py always carried): a waiter must ``register`` (sampling
    generations) BEFORE its final index probe. A writer mutates state
    BEFORE notifying. Then either the writer's notify lands after the
    sample (generation moves, waiter wakes) or it landed before (so the
    mutation is visible to the post-sample probe and the waiter never
    parks).

    Multi-item registrations spanning several buckets (rare: multi-topic
    event filters) cannot park on several conditions at once; they park
    on one shared side channel (``_multi_cond``) which every notify also
    bumps while such waiters exist.

    Registrations are BOUNDED: ``max_watchers`` > 0 makes ``register``
    raise a typed ``RejectError(WATCH_LIMIT)`` past the cap — the same
    cheap-rejection machinery the admission front door uses
    (nomad_tpu/server/admission.py), so a watcher flood degrades into
    fast 503s instead of unbounded registry growth.
    """

    NUM_BUCKETS = 64

    def __init__(self, max_watchers: int = 0) -> None:
        self._conds = tuple(
            threading.Condition() for _ in range(self.NUM_BUCKETS)
        )
        self._gens = [0] * self.NUM_BUCKETS
        self._multi_cond = threading.Condition()
        self._multi_gen = 0
        self._multi_waiters = 0
        # Registration metadata (watcher count, kind counts, cap).
        self._meta_lock = threading.Lock()
        self._kind_counts: Dict[str, int] = {}
        self._watchers = 0
        self.max_watchers = int(max_watchers)
        # Loss-free counters (ints under the GIL; read for stats/gauges).
        self.rejected = 0
        self.notifies = 0
        self.peak_watchers = 0
        # Wake-economy books (read_observe.py drains them; plain data —
        # this module must never import the observatory, OBS001):
        # per-bucket occupancy, total waiters woken by notifies, and
        # spurious wakes (callers bump after a woke-but-index-unmoved
        # re-probe — the bucket-sharing cost this registry trades for
        # O(touched-items) publishes).
        self.bucket_watchers = [0] * self.NUM_BUCKETS
        self.wakes_delivered = 0
        self.spurious_wakes = 0

    @staticmethod
    def _bucket(item: WatchItem) -> int:
        # crc32, not hash(): per-process salted str hashing would make
        # bucket spread (and thus spurious-wake behavior) vary run to run.
        return zlib.crc32(
            ("%s\x00%s" % item).encode()
        ) % _Watch.NUM_BUCKETS

    # -- registration ------------------------------------------------------

    def register(self, items: Iterable[WatchItem]) -> _WatchTicket:
        """Register a watcher on ``items``; returns the ticket ``wait``
        consumes. Must be called BEFORE the caller's final index probe
        (see the class protocol note). Raises RejectError(WATCH_LIMIT)
        when the registration cap is reached."""
        items = list(items)
        buckets = sorted({self._bucket(item) for item in items})
        with self._meta_lock:
            if self.max_watchers and self._watchers >= self.max_watchers:
                self.rejected += 1
                from nomad_tpu.structs import REJECT_WATCH_LIMIT, RejectError

                raise RejectError(
                    REJECT_WATCH_LIMIT,
                    f"blocking-watcher cap reached "
                    f"({self._watchers}/{self.max_watchers})",
                    retry_after=0.5,
                )
            self._watchers += 1
            if self._watchers > self.peak_watchers:
                self.peak_watchers = self._watchers
            for item in items:
                self._kind_counts[item[0]] = (
                    self._kind_counts.get(item[0], 0) + 1
                )
            for b in buckets:
                self.bucket_watchers[b] += 1
        multi = len(buckets) > 1
        multi_gen = 0
        if multi:
            # Count BEFORE sampling generations: a writer reads the count
            # after bumping bucket gens, so it either sees us (and bumps
            # the side channel) or bumped before our sample (and the
            # mutation is visible to our post-sample probe).
            with self._multi_cond:
                self._multi_waiters += 1
                multi_gen = self._multi_gen
        gens = []
        for b in buckets:
            with self._conds[b]:
                gens.append(self._gens[b])
        return _WatchTicket(items, buckets, gens, multi, multi_gen)

    def unregister(self, ticket: _WatchTicket) -> None:
        with self._meta_lock:
            self._watchers -= 1
            for item in ticket.items:
                n = self._kind_counts.get(item[0], 0) - 1
                if n <= 0:
                    self._kind_counts.pop(item[0], None)
                else:
                    self._kind_counts[item[0]] = n
            for b in ticket.buckets:
                self.bucket_watchers[b] -= 1
        if ticket.multi:
            with self._multi_cond:
                self._multi_waiters -= 1

    def wait(self, ticket: _WatchTicket,
             timeout: Optional[float] = None) -> bool:
        """Park until any of the ticket's buckets is notified past its
        sampled generation, or ``timeout`` lapses. Returns True when a
        (possibly spurious, bucket-shared) notification woke us, False on
        timeout. Callers re-probe their index either way."""
        import time as _time

        deadline = (
            _time.monotonic() + timeout if timeout is not None else None
        )
        if not ticket.multi:
            b = ticket.buckets[0]
            gen0 = ticket.gens[0]
            cond = self._conds[b]
            with cond:
                while self._gens[b] == gen0:
                    if deadline is None:
                        cond.wait()
                        continue
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return False
                    cond.wait(remaining)
            return True
        with self._multi_cond:
            while True:
                if self._multi_gen != ticket.multi_gen:
                    return True
                # Bucket generations read without their locks: plain int
                # reads under the GIL; the registration protocol covers
                # the race (see class docstring).
                if any(
                    self._gens[b] != g
                    for b, g in zip(ticket.buckets, ticket.gens)
                ):
                    return True
                if deadline is None:
                    self._multi_cond.wait()
                    continue
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._multi_cond.wait(remaining)

    # -- introspection ------------------------------------------------------

    def has_waiters_for(self, kind: str) -> bool:
        """True when any waiter is registered on an item of ``kind``.

        ORDERING CONTRACT for writers using this to skip item building:
        sample it AFTER the table mutation is visible. Then a waiter that
        registered too late for the (skipped) notify runs its first query
        against post-write state and doesn't need the wakeup; sampling
        BEFORE the write would lose the wakeup of a waiter registering
        during it."""
        return self._kind_counts.get(kind, 0) > 0

    def stats(self) -> Dict[str, object]:
        with self._meta_lock:
            bucket_watchers = list(self.bucket_watchers)
            watchers = self._watchers
        return {
            "watchers": watchers,
            "peak_watchers": self.peak_watchers,
            "max_watchers": self.max_watchers,
            "rejected": self.rejected,
            "notifies": self.notifies,
            "buckets": self.NUM_BUCKETS,
            "bucket_watchers": bucket_watchers,
            "wakes_delivered": self.wakes_delivered,
            "spurious_wakes": self.spurious_wakes,
            "multi_waiters": self._multi_waiters,
        }

    # -- notification -------------------------------------------------------

    def notify(self, items: Iterable[WatchItem]) -> None:
        # Unlocked emptiness probe: safe ONLY because blocking queries
        # re-check the index after registering (register-then-recheck in
        # blocking.py), so a waiter that races this read never depends on
        # the missed wakeup. A free-threaded build keeping that protocol
        # keeps the safety; move the check under the meta lock if the
        # protocol ever changes.
        if not self._watchers:
            return
        self.notifies += 1
        seen = 0
        for item in items:
            b = self._bucket(item)
            bit = 1 << b
            if seen & bit:
                continue
            seen |= bit
            # Fan-out accounting: every waiter parked on this bucket is
            # about to wake (plain int read under the GIL, the loss-free
            # counter posture above).
            self.wakes_delivered += self.bucket_watchers[b]
            cond = self._conds[b]
            with cond:
                self._gens[b] += 1
                cond.notify_all()
        if self._multi_waiters:
            self.wakes_delivered += self._multi_waiters
            with self._multi_cond:
                self._multi_gen += 1
                self._multi_cond.notify_all()

    def notify_all(self) -> None:
        """Wake every parked watcher. Fired when this store is replaced
        wholesale (raft snapshot install rebinds fsm.state) so blocking
        queries re-check against the live store instead of sleeping out
        their timeout on an orphaned one."""
        for b in range(self.NUM_BUCKETS):
            cond = self._conds[b]
            with cond:
                self._gens[b] += 1
                cond.notify_all()
        with self._multi_cond:
            self._multi_gen += 1
            self._multi_cond.notify_all()


class _Tables:
    """The raw table containers. Snapshots shallow-copy these dicts."""

    def __init__(self) -> None:
        self.indexes: Dict[str, int] = {}
        self.nodes: Dict[str, Node] = {}
        self.jobs: Dict[str, Job] = {}
        self.evals: Dict[str, Evaluation] = {}
        self.allocs: Dict[str, Allocation] = {}
        # Columnar allocation blocks (state/blocks.py): one row per
        # (eval, task group) block instead of one per placement. Blocks are
        # immutable — exclusion replaces the entry with a COW copy — so the
        # snapshot container-copy below stays cheap and consistent.
        self.blocks: Dict[str, StoredAllocBlock] = {}
        # Secondary indexes: id sets keyed by foreign key.
        self.evals_by_job: Dict[str, Set[str]] = {}
        self.allocs_by_job: Dict[str, Set[str]] = {}
        self.allocs_by_node: Dict[str, Set[str]] = {}
        self.allocs_by_eval: Dict[str, Set[str]] = {}
        self.blocks_by_job: Dict[str, Set[str]] = {}
        self.blocks_by_eval: Dict[str, Set[str]] = {}
        # Non-terminal OBJECT rows per job — the O(1) gate for block-level
        # reconciles (a rolling update accumulates terminal stop rows that
        # a scan-based gate would re-walk on every eval). Maintained by
        # _insert_alloc_row/_replace_alloc_row/the GC pop.
        self.live_objs_by_job: Dict[str, int] = {}
        # Bounded change logs (index-ascending). ``*_floor`` is the highest
        # index whose entries may have been trimmed away: a consumer
        # rolling forward from N has complete coverage iff N >= floor.
        self.node_log: List[Tuple[int, str, str]] = []
        self.node_log_floor: int = 0
        self.alloc_log: List[Tuple[int, Tuple[str, ...]]] = []
        self.alloc_log_floor: int = 0

    def copy(self) -> "_Tables":
        new = _Tables()
        new.indexes = dict(self.indexes)
        new.nodes = dict(self.nodes)
        new.jobs = dict(self.jobs)
        new.evals = dict(self.evals)
        new.allocs = dict(self.allocs)
        new.blocks = dict(self.blocks)
        new.evals_by_job = {k: set(v) for k, v in self.evals_by_job.items()}
        new.allocs_by_job = {k: set(v) for k, v in self.allocs_by_job.items()}
        new.allocs_by_node = {k: set(v) for k, v in self.allocs_by_node.items()}
        new.allocs_by_eval = {k: set(v) for k, v in self.allocs_by_eval.items()}
        new.blocks_by_job = {k: set(v) for k, v in self.blocks_by_job.items()}
        new.blocks_by_eval = {k: set(v) for k, v in self.blocks_by_eval.items()}
        new.live_objs_by_job = dict(self.live_objs_by_job)
        # Logs are SHARED by reference: between trims they're append-only
        # (list.append is atomic under the GIL, and readers filter by
        # index, so post-snapshot appends are invisible to them); a trim
        # rebinds the LIVE tables' attribute, leaving this copy's
        # reference — and its matching floor — intact.
        new.node_log = self.node_log
        new.node_log_floor = self.node_log_floor
        new.alloc_log = self.alloc_log
        new.alloc_log_floor = self.alloc_log_floor
        return new


class _StateView:
    """Read methods shared by the live store and snapshots. Implements the
    scheduler State interface (reference: scheduler/scheduler.go:55-71)."""

    _t: _Tables

    # -- nodes ------------------------------------------------------------

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._t.nodes.get(node_id)

    def nodes(self) -> List[Node]:
        return list(self._t.nodes.values())

    # -- jobs -------------------------------------------------------------

    def job_by_id(self, job_id: str) -> Optional[Job]:
        return self._t.jobs.get(job_id)

    def jobs(self) -> List[Job]:
        return list(self._t.jobs.values())

    def jobs_by_scheduler(self, scheduler_type: str) -> List[Job]:
        """Jobs by type, backing system-job fan-out on node updates
        (state_store.go schema "type" index; node_endpoint.go:459)."""
        return [j for j in self._t.jobs.values() if j.type == scheduler_type]

    # -- evals ------------------------------------------------------------

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._t.evals.get(eval_id)

    def evals(self) -> List[Evaluation]:
        return list(self._t.evals.values())

    def evals_by_job(self, job_id: str) -> List[Evaluation]:
        ids = self._t.evals_by_job.get(job_id, set())
        return [self._t.evals[i] for i in ids]

    # -- allocs -----------------------------------------------------------

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        alloc = self._t.allocs.get(alloc_id)
        if alloc is not None or not self._t.blocks:
            return alloc
        for blk in self._t.blocks.values():
            pos = blk.find(alloc_id)
            if pos is not None:
                return blk.materialize_pos(pos)
        return None

    def allocs(self) -> List[Allocation]:
        out = list(self._t.allocs.values())
        for blk in self._t.blocks.values():
            out.extend(blk.materialize())
        return out

    def alloc_count(self) -> int:
        """Cheap table cardinality (used by the solver's clean-state fast
        path to skip usage tensorization entirely)."""
        return len(self._t.allocs) + sum(
            blk.n_live for blk in self._t.blocks.values()
        )

    def alloc_blocks(self) -> List[StoredAllocBlock]:
        """Live columnar blocks — the no-materialization read for plan
        verification and the device mirror."""
        return list(self._t.blocks.values())

    def allocs_objects(self) -> List[Allocation]:
        """Object-table rows only (the complement of alloc_blocks())."""
        return list(self._t.allocs.values())

    def nodes_with_object_allocs(self) -> Set[str]:
        """Node ids holding at least one object-table alloc row — lets the
        vectorized plan verifier walk objects only where objects exist."""
        return {nid for nid, ids in self._t.allocs_by_node.items() if ids}

    def allocs_by_job(self, job_id: str) -> List[Allocation]:
        ids = self._t.allocs_by_job.get(job_id, set())
        out = [self._t.allocs[i] for i in ids]
        for bid in self._t.blocks_by_job.get(job_id, ()):
            out.extend(self._t.blocks[bid].materialize())
        return out

    def has_allocs_for_job(self, job_id: str) -> bool:
        """Existence check WITHOUT materializing columnar blocks — the
        guard fast paths (fresh-registration detection) need only the
        answer, not 100k Allocation objects."""
        if self._t.allocs_by_job.get(job_id):
            return True
        return bool(self._t.blocks_by_job.get(job_id))

    def job_has_object_allocs(self, job_id: str) -> bool:
        """Whether any NON-TERMINAL allocations of the job live as object
        rows (vs columnar blocks) — the O(1) gate for fully block-level
        reconciles (counter maintained at every row write). Terminal rows
        (stopped/evicted/failed) are invisible to the five-way diff, so a
        mid-rolling-update job whose stops accumulated as objects still
        reconciles block-wise."""
        return self._t.live_objs_by_job.get(job_id, 0) > 0

    def job_alloc_blocks(self, job_id: str) -> List["StoredAllocBlock"]:
        """The job's stored columnar blocks, un-materialized."""
        return [self._t.blocks[bid]
                for bid in self._t.blocks_by_job.get(job_id, ())]

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        out = self.allocs_by_node_objects(node_id)
        for blk in self._t.blocks.values():
            if blk.node_runs().get(node_id) is not None:
                out = out + blk.materialize_node(node_id)
        return out

    def allocs_by_node_objects(self, node_id: str) -> List[Allocation]:
        """Object-table rows only: callers that account block usage
        columnar (plan verification, mirror) read this plus alloc_blocks()
        instead of paying per-node materialization."""
        ids = self._t.allocs_by_node.get(node_id, set())
        return [self._t.allocs[i] for i in ids]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        ids = self._t.allocs_by_eval.get(eval_id, set())
        out = [self._t.allocs[i] for i in ids]
        for bid in self._t.blocks_by_eval.get(eval_id, ()):
            out.extend(self._t.blocks[bid].materialize())
        return out

    # -- change logs (delta consumers: the device mirror) -----------------

    def node_changes_since(self, index: int) -> Optional[
            List[Tuple[int, str, str]]]:
        """Node-table deltas ``(index, node_id, kind)`` with index in
        ``(index, this view's nodes index]``, oldest first — the feed for
        NodeMirror.apply_delta. Returns None when the bounded log no
        longer reaches back to ``index`` (the consumer must rebuild)."""
        t = self._t
        # Read the list BEFORE the floor: the trim writes floor first,
        # then rebinds the list, so this order can pessimize (old list,
        # new floor → spurious None) but never read a trimmed list
        # against a stale floor.
        log = t.node_log
        if index < t.node_log_floor:
            return None
        my = self.get_index("nodes")
        out: List[Tuple[int, str, str]] = []
        for i in range(len(log) - 1, -1, -1):
            e = log[i]
            if e[0] <= index:
                break
            if e[0] <= my:
                out.append(e)
        out.reverse()
        return out

    def alloc_node_changes_since(self, index: int) -> Optional[Set[str]]:
        """Node ids whose allocation usage may have changed after
        ``index`` (up to this view's allocs index), or None past the log
        horizon. Feeds the mirror's base-usage roll forward."""
        t = self._t
        # List-before-floor read order: see node_changes_since.
        log = t.alloc_log
        if index < t.alloc_log_floor:
            return None
        my = self.get_index("allocs")
        out: Set[str] = set()
        for i in range(len(log) - 1, -1, -1):
            e = log[i]
            if e[0] <= index:
                break
            if e[0] <= my:
                out.update(e[1])
        return out

    def alloc_object_by_id(self, alloc_id: str) -> Optional[Allocation]:
        """Object-table row only (no block materialization) — the cheap
        'was this id counted as an object row' probe the mirror's usage
        plan-delta needs."""
        return self._t.allocs.get(alloc_id)

    def allocs_by_job_objects(self, job_id: str) -> List[Allocation]:
        """Object-table rows of one job (complement of
        job_alloc_blocks()) — lets per-eval job/tg counting walk the
        job's own allocs instead of the whole cluster."""
        ids = self._t.allocs_by_job.get(job_id, ())
        return [self._t.allocs[i] for i in ids]

    # -- indexes ----------------------------------------------------------

    def get_index(self, table: str) -> int:
        """Latest commit index that modified ``table``
        (state_store.go Index table)."""
        return self._t.indexes.get(table, 0)

    def latest_index(self) -> int:
        return max(self._t.indexes.values(), default=0)


class StateSnapshot(_StateView):
    """Immutable point-in-time view (reference: state_store.go:54-66).

    Also supports *optimistic* local mutation (upsert_allocs) so the plan
    applier can pipeline verification of plan N+1 against the effects of
    plan N before Raft applies it (plan_apply.go:100-117); snapshots are
    private to their creator so this never races.
    """

    def __init__(self, tables: _Tables, store_uid: str = ""):
        self._t = tables
        # Identity of the originating live store: device-mirror caches key
        # on (store_uid, table index) so snapshots of one store share warm
        # tensors while distinct stores never collide (SURVEY.md §7
        # "state mirror keyed by a state-store generation").
        self.store_uid = store_uid
        # Set once this snapshot diverges from its store via optimistic
        # writes: its index-stamps then name content the shared change
        # logs don't describe, so generation-keyed caches (the mirror's
        # base usage) must neither trust deltas from it nor cache it.
        self.optimistic = False

    # The plan applier attaches allocs optimistically; reuse the same
    # write-side helpers against the snapshot's private tables.
    def upsert_allocs(self, index: int, allocs: List[Allocation]) -> None:
        self.optimistic = True
        _upsert_allocs(self._t, index, allocs)

    def upsert_alloc_blocks(self, index: int, batches) -> None:
        # Optimistic snapshot writes never notify: skip item building.
        self.optimistic = True
        _upsert_alloc_blocks(self._t, index, batches)

    def apply_update_batches(self, index: int, batches) -> None:
        self.optimistic = True
        _apply_update_batches(self._t, index, batches)


class StateRestore:
    """Bulk loader used by FSM snapshot restore
    (reference: state_store.go:767-815)."""

    def __init__(self, store: "StateStore"):
        self._store = store
        self._tables = _Tables()

    def node_restore(self, node: Node) -> None:
        self._tables.nodes[node.id] = node
        self._tables.indexes["nodes"] = max(
            self._tables.indexes.get("nodes", 0), node.modify_index
        )

    def job_restore(self, job: Job) -> None:
        self._tables.jobs[job.id] = job
        self._tables.indexes["jobs"] = max(
            self._tables.indexes.get("jobs", 0), job.modify_index
        )

    def eval_restore(self, ev: Evaluation) -> None:
        self._tables.evals[ev.id] = ev
        self._tables.evals_by_job.setdefault(ev.job_id, set()).add(ev.id)
        self._tables.indexes["evals"] = max(
            self._tables.indexes.get("evals", 0), ev.modify_index
        )

    def alloc_restore(self, alloc: Allocation) -> None:
        t = self._tables
        _insert_alloc_row(t, alloc)
        t.indexes["allocs"] = max(
            t.indexes.get("allocs", 0), alloc.modify_index
        )

    def block_restore(self, block: StoredAllocBlock) -> None:
        t = self._tables
        t.blocks[block.block_id] = block
        t.blocks_by_job.setdefault(block.job_id, set()).add(block.block_id)
        t.blocks_by_eval.setdefault(block.eval_id, set()).add(block.block_id)
        t.indexes["allocs"] = max(
            t.indexes.get("allocs", 0), block.modify_index
        )

    def index_restore(self, table: str, index: int) -> None:
        self._tables.indexes[table] = index

    def commit(self) -> None:
        self._store._install(self._tables)


def _find_block_member(t: _Tables, alloc_id: str):
    """(block_id, pos) of a live block member, or None."""
    for bid, blk in t.blocks.items():
        pos = blk.find(alloc_id)
        if pos is not None:
            return bid, pos
    return None


def _decr_live_objs(t: _Tables, job_id: str) -> None:
    n = t.live_objs_by_job.get(job_id, 0) - 1
    if n > 0:
        t.live_objs_by_job[job_id] = n
    else:
        t.live_objs_by_job.pop(job_id, None)


def _insert_alloc_row(t: _Tables, alloc: Allocation) -> None:
    prev = t.allocs.get(alloc.id)
    if prev is not None and not prev.terminal_status():
        _decr_live_objs(t, prev.job_id)
    if not alloc.terminal_status():
        t.live_objs_by_job[alloc.job_id] = (
            t.live_objs_by_job.get(alloc.job_id, 0) + 1
        )
    t.allocs[alloc.id] = alloc
    t.allocs_by_job.setdefault(alloc.job_id, set()).add(alloc.id)
    t.allocs_by_node.setdefault(alloc.node_id, set()).add(alloc.id)
    t.allocs_by_eval.setdefault(alloc.eval_id, set()).add(alloc.id)


def _exclude_block_members(t: _Tables, members: Dict[str, Set[int]]) -> None:
    """Replace blocks with COW copies excluding ``members`` ({block_id:
    positions}). A block whose exclusion set reaches half its size
    dissolves — remaining members become object rows — so per-member
    promotion cost stays O(n) over a block's whole life instead of the
    frozenset-union O(n^2)."""
    for bid, positions in members.items():
        blk = t.blocks[bid].with_excluded(positions)
        dissolve = blk.n_live == 0 or len(blk.excluded) * 2 >= blk.n
        if dissolve:
            for alloc in blk.materialize():
                _insert_alloc_row(t, alloc)
            del t.blocks[bid]
            for idx_map, key in ((t.blocks_by_job, blk.job_id),
                                 (t.blocks_by_eval, blk.eval_id)):
                ids = idx_map.get(key)
                if ids is not None:
                    ids.discard(bid)
                    if not ids:
                        del idx_map[key]
        else:
            t.blocks[bid] = blk


def _upsert_allocs(t: _Tables, index: int, allocs: List[Allocation],
                   touched: Optional[Set[str]] = None) -> None:
    # ``touched`` (when given) collects the node ids whose usage this
    # write may change — the live store's alloc change-log feed. Optimistic
    # snapshot writes pass None and stay out of the shared log.
    if touched is not None:
        for alloc in allocs:
            touched.add(alloc.node_id)
            existing = t.allocs.get(alloc.id)
            if existing is not None and existing.node_id != alloc.node_id:
                touched.add(existing.node_id)
    # An object row superseding a block member (eviction, re-placement,
    # client-side restamp) promotes it out of the block.
    if t.blocks:
        members: Dict[str, Set[int]] = {}
        for alloc in allocs:
            if alloc.id in t.allocs:
                continue
            found = _find_block_member(t, alloc.id)
            if found is not None:
                bid, pos = found
                members.setdefault(bid, set()).add(pos)
                if touched is not None:
                    # A superseded member's OLD node loses its block
                    # usage — a cross-node restamp must dirty both ends.
                    touched.add(t.blocks[bid].node_of_pos(pos))
                if alloc.create_index == 0:
                    alloc.create_index = t.blocks[bid].create_index
        if members:
            _exclude_block_members(t, members)
    for alloc in allocs:
        existing = t.allocs.get(alloc.id)
        if existing is None:
            if alloc.create_index == 0:
                alloc.create_index = index
        else:
            alloc.create_index = existing.create_index
            # De-index under stale foreign keys if they changed.
            if existing.node_id != alloc.node_id:
                t.allocs_by_node.get(existing.node_id, set()).discard(alloc.id)
            if existing.job_id != alloc.job_id:
                t.allocs_by_job.get(existing.job_id, set()).discard(alloc.id)
            if existing.eval_id != alloc.eval_id:
                t.allocs_by_eval.get(existing.eval_id, set()).discard(alloc.id)
        alloc.modify_index = index
        _insert_alloc_row(t, alloc)
    t.indexes["allocs"] = index


def _apply_update_batches(t: _Tables, index: int, batches,
                          watch: "_Watch" = None,
                          touched: Optional[Set[str]] = None) -> List[WatchItem]:
    """Columnar in-place updates: whole-block field swap when a batch
    covers all live members of a stored block; promotion for partial
    coverage; row re-stamp for object allocs. Returns watch items.
    Job/eval container items always fire; per-member node/alloc items
    (thousands per bulk update) build only when ``watch`` has waiters of
    that kind — sampled AFTER the mutation lands (Watch.has_waiters_for
    ordering contract)."""
    items: List[WatchItem] = [item_table("allocs")]
    swapped_blks = []
    stamped_rows = []
    for b in batches:
        members: Dict[str, Set[int]] = {}
        object_rows: List[Allocation] = []
        for alloc_or_id in (b.allocs or b.alloc_ids):
            aid = (alloc_or_id if isinstance(alloc_or_id, str)
                   else alloc_or_id.id)
            row = t.allocs.get(aid)
            if row is not None:
                object_rows.append(row)
                continue
            found = _find_block_member(t, aid)
            if found is not None:
                members.setdefault(found[0], set()).add(found[1])
            # Unknown ids: removed while the plan was in flight — exactly
            # the staleness plan evaluation tolerates.
        for bid, positions in members.items():
            blk = t.blocks[bid]
            if len(positions) == blk.n_live:
                # Whole block: O(1) field swap, re-keyed by eval/job.
                new_blk = blk.with_update(
                    b.job, b.resources, b.task_resources,
                    b.metrics, b.eval_id, index,
                )
                t.blocks[bid] = new_blk
                if new_blk.eval_id != blk.eval_id:
                    ids = t.blocks_by_eval.get(blk.eval_id)
                    if ids is not None:
                        ids.discard(bid)
                        if not ids:
                            del t.blocks_by_eval[blk.eval_id]
                    t.blocks_by_eval.setdefault(
                        new_blk.eval_id, set()).add(bid)
                if new_blk.job_id != blk.job_id:
                    ids = t.blocks_by_job.get(blk.job_id)
                    if ids is not None:
                        ids.discard(bid)
                        if not ids:
                            del t.blocks_by_job[blk.job_id]
                    t.blocks_by_job.setdefault(
                        new_blk.job_id, set()).add(bid)
                items.append(item_alloc_job(new_blk.job_id))
                items.append(item_alloc_eval(blk.eval_id))
                items.append(item_alloc_eval(new_blk.eval_id))
                swapped_blks.append(new_blk)
            else:
                for pos in positions:
                    object_rows.append(blk.materialize_pos(pos))
                _exclude_block_members(t, {bid: positions})
        for existing in object_rows:
            new = existing.copy()
            new.eval_id = b.eval_id
            new.job = b.job
            new.job_id = b.job.id if b.job is not None else new.job_id
            if b.resources is not None:
                new.resources = b.resources
            if b.task_resources:
                new.task_resources = b.task_resources
            new.metrics = b.metrics
            new.desired_status = ALLOC_DESIRED_STATUS_RUN
            new.desired_description = ""
            new.client_status = ALLOC_CLIENT_STATUS_PENDING
            new.modify_index = index
            if existing.id not in t.allocs:
                new.create_index = existing.create_index or index
            if existing.eval_id != new.eval_id:
                ids = t.allocs_by_eval.get(existing.eval_id)
                if ids is not None:
                    ids.discard(existing.id)
            _insert_alloc_row(t, new)
            stamped_rows.append(new)
    t.indexes["allocs"] = index
    if touched is not None:
        for blk in swapped_blks:
            touched.update(blk.node_ids)
        touched.update(r.node_id for r in stamped_rows)
    if stamped_rows:
        # Container (job/eval) items fire unconditionally, deduped
        # batch-wide: every row of a batch shares its eval id, and job
        # ids collapse to one unless b.job was None.
        items.extend(
            item_alloc_job(j) for j in sorted({r.job_id for r in stamped_rows})
        )
        items.extend(
            item_alloc_eval(e)
            for e in sorted({r.eval_id for r in stamped_rows})
        )
    if watch is not None:
        if watch.has_waiters_for("alloc_node"):
            for blk in swapped_blks:
                items.extend(item_alloc_node(n) for n in blk.node_ids)
            items.extend(item_alloc_node(r.node_id) for r in stamped_rows)
        if watch.has_waiters_for("alloc"):
            items.extend(item_alloc(r.id) for r in stamped_rows)
    return items


def _upsert_alloc_blocks(t: _Tables, index: int, batches,
                         watch: "_Watch" = None,
                         touched: Optional[Set[str]] = None) -> List[WatchItem]:
    """Commit columnar batches as stored blocks — O(runs), no object
    expansion. Returns the watch items to notify. Per-node items (a block
    touches thousands of nodes) are built only when ``watch`` has
    alloc_node waiters — sampled AFTER the mutation lands, so a waiter
    registering mid-commit either gets the notify or reads post-write
    state on its first query pass (Watch.has_waiters_for)."""
    items: List[WatchItem] = [item_table("allocs")]
    committed = []
    for batch in batches:
        if batch.n == 0:
            continue
        blk = StoredAllocBlock.from_batch(batch, index)
        t.blocks[blk.block_id] = blk
        t.blocks_by_job.setdefault(blk.job_id, set()).add(blk.block_id)
        t.blocks_by_eval.setdefault(blk.eval_id, set()).add(blk.block_id)
        items.append(item_alloc_job(blk.job_id))
        items.append(item_alloc_eval(blk.eval_id))
        committed.append(blk)
        if touched is not None:
            touched.update(blk.node_ids)
    t.indexes["allocs"] = index
    if watch is not None and watch.has_waiters_for("alloc_node"):
        for blk in committed:
            items.extend(item_alloc_node(nid) for nid in blk.node_ids)
    return items


class StateStore(_StateView):
    """The live, mutable state store. All writes stamp create/modify
    indexes and fire watch notifications (reference: state_store.go:91-760)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._t = _Tables()
        self.watch = _Watch()
        self.store_uid = generate_uuid()

    # -- snapshot/restore -------------------------------------------------

    def snapshot(self) -> StateSnapshot:
        with self._lock:
            return StateSnapshot(self._t.copy(), store_uid=self.store_uid)

    def restore(self) -> StateRestore:
        return StateRestore(self)

    def _install(self, tables: _Tables) -> None:
        with self._lock:
            # A wholesale install (restore) carries no change history:
            # floors at the installed indexes force every delta consumer
            # through one full rebuild instead of a bogus empty delta.
            tables.node_log_floor = tables.indexes.get("nodes", 0)
            tables.alloc_log_floor = tables.indexes.get("allocs", 0)
            self._t = tables
        self.watch.notify(
            [
                item_table("nodes"),
                item_table("jobs"),
                item_table("evals"),
                item_table("allocs"),
            ]
        )

    # -- nodes ------------------------------------------------------------

    def _upsert_node_locked(self, index: int, node: Node) -> str:
        """Index-stamp + insert (lock held) — the ONE definition of node
        upsert semantics, shared by the single and batch paths. Returns
        the change-log kind ("insert" for a new key, "update" for an
        in-place rewrite)."""
        existing = self._t.nodes.get(node.id)
        if existing is None:
            node.create_index = index
        else:
            node.create_index = existing.create_index
        node.modify_index = index
        self._t.nodes[node.id] = node
        return "insert" if existing is None else "update"

    def upsert_node(self, index: int, node: Node) -> None:
        """reference: state_store.go UpsertNode"""
        with self._lock:
            kind = self._upsert_node_locked(index, node)
            _log_node_change(self._t, index, node.id, kind)
            self._t.indexes["nodes"] = index
        self.watch.notify([item_table("nodes"), item_node(node.id)])

    def upsert_nodes(self, index: int, nodes: List[Node]) -> None:
        """Bulk node upsert: one lock hold and one table notification for a
        whole registration batch (the Node.BatchRegister path — simcluster
        registers 10k nodes in a few dozen raft entries). Per-node watch
        items are built only when someone is parked on one, the same
        granularity economy as the columnar alloc commits."""
        with self._lock:
            for node in nodes:
                kind = self._upsert_node_locked(index, node)
                _log_node_change(self._t, index, node.id, kind)
            self._t.indexes["nodes"] = index
        items = [item_table("nodes")]
        if self.watch.has_waiters_for("node"):
            items.extend(item_node(n.id) for n in nodes)
        self.watch.notify(items)

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            if node_id not in self._t.nodes:
                raise KeyError(f"node not found: {node_id}")
            del self._t.nodes[node_id]
            _log_node_change(self._t, index, node_id, "remove")
            self._t.indexes["nodes"] = index
        self.watch.notify([item_table("nodes"), item_node(node_id)])

    def update_node_status(self, index: int, node_id: str, status: str) -> None:
        with self._lock:
            existing = self._t.nodes.get(node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            node = existing.copy()
            node.status = status
            node.modify_index = index
            self._t.nodes[node_id] = node
            _log_node_change(self._t, index, node_id, "update")
            self._t.indexes["nodes"] = index
        self.watch.notify([item_table("nodes"), item_node(node_id)])

    def update_node_drain(self, index: int, node_id: str, drain: bool) -> None:
        with self._lock:
            existing = self._t.nodes.get(node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            node = existing.copy()
            node.drain = drain
            node.modify_index = index
            self._t.nodes[node_id] = node
            _log_node_change(self._t, index, node_id, "update")
            self._t.indexes["nodes"] = index
        self.watch.notify([item_table("nodes"), item_node(node_id)])

    # -- jobs -------------------------------------------------------------

    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            existing = self._t.jobs.get(job.id)
            if existing is None:
                job.create_index = index
            else:
                job.create_index = existing.create_index
            job.modify_index = index
            self._t.jobs[job.id] = job
            self._t.indexes["jobs"] = index
        self.watch.notify([item_table("jobs"), item_job(job.id)])

    def delete_job(self, index: int, job_id: str) -> None:
        with self._lock:
            if job_id not in self._t.jobs:
                raise KeyError(f"job not found: {job_id}")
            del self._t.jobs[job_id]
            self._t.indexes["jobs"] = index
        self.watch.notify([item_table("jobs"), item_job(job_id)])

    # -- evals ------------------------------------------------------------

    def upsert_evals(self, index: int, evals: List[Evaluation]) -> None:
        items: List[WatchItem] = [item_table("evals")]
        with self._lock:
            for ev in evals:
                existing = self._t.evals.get(ev.id)
                if existing is None:
                    ev.create_index = index
                else:
                    ev.create_index = existing.create_index
                ev.modify_index = index
                self._t.evals[ev.id] = ev
                self._t.evals_by_job.setdefault(ev.job_id, set()).add(ev.id)
                items.append(item_eval(ev.id))
            self._t.indexes["evals"] = index
        self.watch.notify(items)

    def delete_eval(self, index: int, eval_ids: List[str], alloc_ids: List[str]) -> None:
        """Delete evals + allocs together, used by GC
        (reference: state_store.go DeleteEval)."""
        items: List[WatchItem] = [item_table("evals"), item_table("allocs")]
        reaped_blocks: List[StoredAllocBlock] = []
        touched: Set[str] = set()
        with self._lock:
            t = self._t
            for eval_id in eval_ids:
                ev = t.evals.pop(eval_id, None)
                if ev is not None:
                    ids = t.evals_by_job.get(ev.job_id)
                    if ids is not None:
                        ids.discard(eval_id)
                        if not ids:
                            del t.evals_by_job[ev.job_id]
                    items.append(item_eval(eval_id))
                # A reaped eval takes its columnar blocks with it wholesale.
                for bid in list(t.blocks_by_eval.get(eval_id, ())):
                    blk = t.blocks.pop(bid, None)
                    if blk is None:
                        continue
                    ids = t.blocks_by_job.get(blk.job_id)
                    if ids is not None:
                        ids.discard(bid)
                        if not ids:
                            del t.blocks_by_job[blk.job_id]
                    items.append(item_alloc_job(blk.job_id))
                    items.append(item_alloc_eval(blk.eval_id))
                    reaped_blocks.append(blk)
                t.blocks_by_eval.pop(eval_id, None)
            block_members: Dict[str, Set[int]] = {}
            for alloc_id in alloc_ids:
                alloc = t.allocs.pop(alloc_id, None)
                if alloc is not None and not alloc.terminal_status():
                    _decr_live_objs(t, alloc.job_id)
                if alloc is None:
                    if t.blocks:
                        found = _find_block_member(t, alloc_id)
                        if found is not None:
                            bid, pos = found
                            block_members.setdefault(bid, set()).add(pos)
                            # Watchers see block-member deletions exactly
                            # like object-row deletions.
                            blk = t.blocks[bid]
                            touched.add(blk.node_of_pos(pos))
                            items.extend(
                                [
                                    item_alloc(alloc_id),
                                    item_alloc_job(blk.job_id),
                                    item_alloc_node(blk.node_of_pos(pos)),
                                    item_alloc_eval(blk.eval_id),
                                ]
                            )
                    continue
                for idx_map, key in (
                    (t.allocs_by_job, alloc.job_id),
                    (t.allocs_by_node, alloc.node_id),
                    (t.allocs_by_eval, alloc.eval_id),
                ):
                    ids = idx_map.get(key)
                    if ids is not None:
                        ids.discard(alloc_id)
                        if not ids:
                            del idx_map[key]
                touched.add(alloc.node_id)
                items.extend(
                    [
                        item_alloc(alloc_id),
                        item_alloc_job(alloc.job_id),
                        item_alloc_node(alloc.node_id),
                        item_alloc_eval(alloc.eval_id),
                    ]
                )
            if block_members:
                _exclude_block_members(t, block_members)
            for blk in reaped_blocks:
                touched.update(blk.node_ids)
            _log_alloc_nodes(t, index, touched)
            t.indexes["evals"] = index
            t.indexes["allocs"] = index
            # Gated member items, sampled AFTER the index stamps (the
            # has_waiters_for ordering contract): a late-registering
            # blocking query re-checks against the stamped index.
            if reaped_blocks and self.watch.has_waiters_for("alloc_node"):
                for blk in reaped_blocks:
                    items.extend(item_alloc_node(n) for n in blk.node_ids)
        self.watch.notify(items)

    # -- allocs -----------------------------------------------------------

    def upsert_allocs(self, index: int, allocs: List[Allocation]) -> None:
        items: List[WatchItem] = [item_table("allocs")]
        touched: Set[str] = set()
        with self._lock:
            _upsert_allocs(self._t, index, allocs, touched=touched)
            _log_alloc_nodes(self._t, index, touched)
            for alloc in allocs:
                items.extend(
                    [
                        item_alloc(alloc.id),
                        item_alloc_job(alloc.job_id),
                        item_alloc_node(alloc.node_id),
                        item_alloc_eval(alloc.eval_id),
                    ]
                )
        self.watch.notify(items)

    def upsert_alloc_blocks(self, index: int, batches: List[AllocBatch]) -> None:
        """Commit columnar placement batches natively (no per-Allocation
        expansion); blocking queries on the touched nodes/job/eval fire."""
        touched: Set[str] = set()
        with self._lock:
            items = _upsert_alloc_blocks(
                self._t, index, batches, watch=self.watch, touched=touched,
            )
            _log_alloc_nodes(self._t, index, touched)
        self.watch.notify(items)

    def apply_update_batches(self, index: int, batches) -> None:
        """Commit columnar in-place updates (AllocUpdateBatch). A batch
        covering ALL live members of a stored block applies as one block
        field swap (state/blocks.py with_update); partial coverage
        promotes the touched members; object rows re-stamp in place. The
        observable result is exactly the batch's materialize() expansion
        upserted row-wise."""
        touched: Set[str] = set()
        with self._lock:
            items = _apply_update_batches(
                self._t, index, batches, watch=self.watch, touched=touched,
            )
            _log_alloc_nodes(self._t, index, touched)
        self.watch.notify(items)

    def update_alloc_from_client(self, index: int, alloc: Allocation) -> None:
        self.update_allocs_from_client(index, [alloc])

    def update_allocs_from_client(self, index: int,
                                  allocs: List[Allocation]) -> None:
        """Client status updates: only client-side fields are trusted
        (reference: state_store.go UpdateAllocFromClient). Block members
        are promoted to object rows — their status now diverges from their
        block — with one COW exclusion per block per batch, not per
        member."""
        items: List[WatchItem] = [item_table("allocs")]
        with self._lock:
            t = self._t
            if t.blocks:
                members: Dict[str, Set[int]] = {}
                for alloc in allocs:
                    if alloc.id in t.allocs:
                        continue
                    found = _find_block_member(t, alloc.id)
                    if found is not None:
                        bid, pos = found
                        members.setdefault(bid, set()).add(pos)
                        _insert_alloc_row(t, t.blocks[bid].materialize_pos(pos))
                if members:
                    _exclude_block_members(t, members)
            missing: List[str] = []
            for alloc in allocs:
                existing = t.allocs.get(alloc.id)
                if existing is None:
                    # A GC'd alloc must not abort the batch: the updates
                    # already applied need their index bump and watch
                    # notifications regardless (raise after both).
                    missing.append(alloc.id)
                    continue
                new = existing.copy()
                new.client_status = alloc.client_status
                new.client_description = alloc.client_description
                new.modify_index = index
                # terminal_status() is desired-status-only (structs.go:
                # 1179-1188 parity), so a client-field update can never
                # move the live-object counter.
                t.allocs[alloc.id] = new
                items.extend(
                    [
                        item_alloc(new.id),
                        item_alloc_job(new.job_id),
                        item_alloc_node(new.node_id),
                        item_alloc_eval(new.eval_id),
                    ]
                )
            t.indexes["allocs"] = index
        self.watch.notify(items)
        if missing:
            raise KeyError(f"alloc not found: {', '.join(missing)}")
