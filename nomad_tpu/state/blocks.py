"""Columnar allocation blocks stored natively in the state store.

The reference stores every placement as an individual Allocation row
(/root/reference/nomad/state/state_store.go:91-760). At TPU solve scale a
single evaluation places 100k tasks; exploding the solver's columnar output
(AllocBatch) into objects at the FSM boundary made commit, snapshot copy,
and every subsequent read O(placements). A StoredAllocBlock keeps the
columnar form *inside* the store: one table row per (eval, task group)
block, Allocation objects materialized lazily — per node for client
fetches, per id for individual addressing.

Invariants:
- Blocks hold only non-terminal, desired=run allocations. Any write that
  individually addresses a block member (client status update, eviction,
  re-placement) *promotes* it: the member is excluded from the block and
  the superseding Allocation object lands in the object table.
- Stored blocks are immutable; exclusion produces a copy sharing the column
  arrays (copy-on-write), so snapshots that captured the old table keep a
  consistent view. Lazy caches (id→position, node→run) are shared across
  copies — the columns they index never change.

Semantically a block is exactly its ``materialize()`` expansion; the
differential tests in tests/test_alloc_batch.py and tests/test_state.py
hold the two forms equal.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterator, List, Optional, Tuple

from nomad_tpu.structs import AllocBatch, Allocation, generate_uuid


class StoredAllocBlock(AllocBatch):
    """An AllocBatch as committed state: indexes stamped, exclusions
    tracked, lazy lookup structures."""

    __slots__ = (
        "block_id", "job_id", "create_index", "modify_index", "excluded",
        "_id_pos", "_node_run", "_live_counts", "_materialized",
    )

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.block_id = ""
        self.job_id = self.job.id if self.job is not None else ""
        self.create_index = 0
        self.modify_index = 0
        self.excluded: FrozenSet[int] = frozenset()
        self._id_pos: Optional[Dict[str, int]] = None
        self._node_run: Optional[Dict[str, Tuple[int, int]]] = None
        self._live_counts: Optional[Dict[str, int]] = None
        self._materialized: Optional[List[Allocation]] = None

    @classmethod
    def from_batch(cls, batch: AllocBatch, index: int) -> "StoredAllocBlock":
        blk = cls(
            eval_id=batch.eval_id, job=batch.job, tg_name=batch.tg_name,
            resources=batch.resources, task_resources=batch.task_resources,
            metrics=batch.metrics, node_ids=batch.node_ids,
            node_counts=batch.node_counts, name_idx=batch.name_idx,
            ids_hex=batch._ids_hex or "", ids_seed=batch.ids_seed,
        )
        # Deterministic across replicas: every FSM applying this log entry
        # derives the same block id (the first member's alloc id —
        # alloc_id(0) expands only the seed's 16-byte prefix, so a
        # seed-form batch stays lazy through commit).
        blk.block_id = batch.alloc_id(0) if batch.n else generate_uuid()
        blk.create_index = index
        blk.modify_index = index
        return blk

    # -- liveness ---------------------------------------------------------

    @property
    def n_live(self) -> int:
        return self.n - len(self.excluded)

    def node_runs(self) -> Dict[str, Tuple[int, int]]:
        """node_id → (start, count) over the run-length encoding."""
        runs = self._node_run
        if runs is None:
            runs = {}
            pos = 0
            for nid, cnt in zip(self.node_ids, self.node_counts):
                runs[nid] = (pos, cnt)
                pos += cnt
            self._node_run = runs
        return runs

    def node_of_pos(self, pos: int) -> str:
        """Node id owning position ``pos`` of the run-length encoding."""
        scan = 0
        for nid, cnt in zip(self.node_ids, self.node_counts):
            if scan <= pos < scan + cnt:
                return nid
            scan += cnt
        return ""

    def live_counts_map(self) -> Dict[str, int]:
        """node_id → total live member count, duplicate runs summed
        (``node_runs`` keeps only a node's LAST run). Cached — blocks are
        immutable, exclusion replaces the object — so per-node usage
        recomputes (the mirror's base-usage roll forward) pay one O(runs)
        build per block, then dict hits."""
        counts = self._live_counts
        if counts is None:
            counts = {}
            for nid, cnt in self.live_node_counts():
                counts[nid] = counts.get(nid, 0) + cnt
            self._live_counts = counts
        return counts

    def live_node_counts(self) -> Iterator[Tuple[str, int]]:
        """(node_id, live placement count) per run — the columnar usage
        feed for plan verification and the device mirror."""
        if not self.excluded:
            yield from zip(self.node_ids, self.node_counts)
            return
        pos = 0
        for nid, cnt in zip(self.node_ids, self.node_counts):
            # nomadlint: allow(DET003) -- commutative membership count
            # (sum of 1s): the iteration order of the set cannot change
            # the result.
            live = cnt - sum(1 for p in self.excluded if pos <= p < pos + cnt)
            if live:
                yield nid, live
            pos += cnt

    # -- lookup -----------------------------------------------------------

    def find(self, alloc_id: str) -> Optional[int]:
        """Position of a member id, or None (excluded members don't count).
        The id→pos dict builds lazily on first individual addressing."""
        idx = self._id_pos
        if idx is None:
            idx = {self.alloc_id(i): i for i in range(self.n)}
            self._id_pos = idx
        pos = idx.get(alloc_id)
        if pos is None or pos in self.excluded:
            return None
        return pos

    # -- materialization (template/span logic inherited from AllocBatch) --

    def materialize_node(self, node_id: str) -> List[Allocation]:
        run = self.node_runs().get(node_id)
        if run is None:
            return []
        out: List[Allocation] = []
        start, cnt = run
        self._materialize_span(self._template(), node_id, start, start + cnt, out)
        return out

    def materialize_prefix(self, k: int) -> List[Allocation]:
        """Materialize the first ``k`` LIVE members (run-ordered, excluded
        positions skipped) — the rolling-update eviction slice. Span ends
        are bounded by remaining need so a dense single-node run never
        materializes past k: O(k + excluded-in-prefix + runs touched)."""
        out: List[Allocation] = []
        template = self._template()
        pos = 0
        for nid, cnt in zip(self.node_ids, self.node_counts):
            if len(out) >= k:
                break
            start, end_run = pos, pos + cnt
            while start < end_run and len(out) < k:
                # Each chunk asks for exactly the remaining need; excluded
                # positions inside it yield fewer, and the loop advances.
                end = min(end_run, start + (k - len(out)))
                self._materialize_span(template, nid, start, end, out)
                start = end
            pos = end_run
        return out

    def live_positions(self) -> List[int]:
        """Run-ordered positions of live (non-excluded) members."""
        if not self.excluded:
            return list(range(self.n))
        excluded = self.excluded
        return [i for i in range(self.n) if i not in excluded]

    def materialize_pos(self, pos: int) -> Allocation:
        out: List[Allocation] = []
        self._materialize_span(
            self._template(), self.node_of_pos(pos), pos, pos + 1, out
        )
        return out[0]

    def materialize(self) -> List[Allocation]:
        # Cached per block: the columns are immutable, and scheduler reads
        # of a committed job (diff against existing allocs) repeat — reads
        # must not pay the expansion more than once. COW exclusion copies
        # don't share the cache (their member set differs).
        cached = self._materialized
        if cached is None:
            cached = []
            template = self._template()
            pos = 0
            for nid, cnt in zip(self.node_ids, self.node_counts):
                self._materialize_span(template, nid, pos, pos + cnt, cached)
                pos += cnt
            self._materialized = cached
        return cached

    def with_update(self, job, resources, task_resources, metrics,
                    eval_id: str, index: int) -> "StoredAllocBlock":
        """A copy with the shared fields swapped — the whole-block in-place
        update (reference semantics: every member re-stamps with the new
        job version, util.go:316-398, but as ONE O(1) field swap instead
        of n row rewrites). Columns, ids, names, and placement stay;
        None/empty update fields preserve the old values, exactly like the
        per-row re-stamp (AllocUpdateBatch.materialize)."""
        blk = StoredAllocBlock(
            eval_id=eval_id, job=job if job is not None else self.job,
            tg_name=self.tg_name,
            resources=resources if resources is not None else self.resources,
            task_resources=task_resources or self.task_resources,
            metrics=metrics, node_ids=self.node_ids,
            node_counts=self.node_counts, name_idx=self.name_idx,
            ids_hex=self._ids_hex or "", ids_seed=self.ids_seed,
        )
        blk.block_id = self.block_id
        blk.job_id = job.id if job is not None else self.job_id
        blk.create_index = self.create_index
        blk.modify_index = index
        blk.excluded = self.excluded
        blk._id_pos = self._id_pos
        blk._node_run = self._node_run
        blk._live_counts = self._live_counts  # same members, same counts
        return blk

    # -- copy-on-write exclusion ------------------------------------------

    def with_excluded(self, positions) -> "StoredAllocBlock":
        """A copy of this block with ``positions`` additionally excluded.
        Columns and lazy caches are shared — they never change."""
        blk = StoredAllocBlock(
            eval_id=self.eval_id, job=self.job, tg_name=self.tg_name,
            resources=self.resources, task_resources=self.task_resources,
            metrics=self.metrics, node_ids=self.node_ids,
            node_counts=self.node_counts, name_idx=self.name_idx,
            ids_hex=self._ids_hex or "", ids_seed=self.ids_seed,
        )
        blk.block_id = self.block_id
        blk.job_id = self.job_id
        blk.create_index = self.create_index
        blk.modify_index = self.modify_index
        blk.excluded = self.excluded | frozenset(positions)
        blk._id_pos = self._id_pos
        blk._node_run = self._node_run
        return blk

    # -- persistence (FSM snapshot stream) --------------------------------

    _PICKLE_SLOTS = (
        "eval_id", "job", "tg_name", "resources", "task_resources",
        "metrics", "node_ids", "node_counts", "name_idx", "ids_seed",
        "block_id", "job_id", "create_index", "modify_index", "excluded",
    )

    def __getstate__(self):
        """Pickle the columns only: a block that has served one
        materialize() read carries an O(placements) object cache that must
        never re-inflate a raft snapshot. The id column follows the same
        rule — a seed-form block pickles its 16-byte seed and the restore
        re-derives; only a block built from explicit hex (wire compat)
        carries the expansion."""
        state = {k: getattr(self, k) for k in self._PICKLE_SLOTS}
        state["_ids_hex"] = None if self.ids_seed is not None \
            else self._ids_hex
        return state

    def __setstate__(self, state):
        for k in self._PICKLE_SLOTS:
            setattr(self, k, state.get(k))
        # Legacy pickles carried the expanded column under "ids_hex".
        self._ids_hex = state.get("_ids_hex", state.get("ids_hex"))
        if self._ids_hex is None and self.ids_seed is None:
            self._ids_hex = ""
        self._id_pos = None
        self._node_run = None
        self._live_counts = None
        self._materialized = None

    def to_wire(self) -> dict:
        d = super().to_wire()
        d["block_id"] = self.block_id
        d["create_index"] = self.create_index
        d["modify_index"] = self.modify_index
        d["excluded"] = sorted(self.excluded)
        return d

    @staticmethod
    def from_wire(d: dict) -> "StoredAllocBlock":
        base = AllocBatch.from_wire(d)
        blk = StoredAllocBlock(
            eval_id=base.eval_id, job=base.job, tg_name=base.tg_name,
            resources=base.resources, task_resources=base.task_resources,
            metrics=base.metrics, node_ids=base.node_ids,
            node_counts=base.node_counts, name_idx=base.name_idx,
            ids_hex=base._ids_hex or "", ids_seed=base.ids_seed,
        )
        blk.block_id = d.get("block_id") or generate_uuid()
        blk.create_index = int(d.get("create_index", 0))
        blk.modify_index = int(d.get("modify_index", 0))
        blk.excluded = frozenset(d.get("excluded") or ())
        return blk
