"""Capacity observatory: fragmentation accounting and stranded capacity.

Borg's utilization story (PAPERS.md) is cell compaction: the metric that
matters is not "how busy are the nodes" but "how much of the cell could
still host real work" — free capacity that exists in aggregate yet sits
on nodes too fragmented to fit an actual task shape is *stranded*, and
stranded-capacity % is the number the defragmentation arc (ROADMAP item
on continuous rescheduling) will be judged by. Until now nothing in the
agent measured it: the artifacts counted placements and latencies, and
``/v1/agent/*`` answered "how fast", never "how full, and how usable is
what's left".

:class:`CapacityAccountant` is the read-only observer that answers it.
Omega's shared-state posture (PAPERS.md): observers read cluster state
without perturbing decisions. The accountant is fed **incrementally from
the same state-store change streams the device mirror consumes**
(``state/store.py`` ``node_changes_since`` / ``alloc_node_changes_since``)
— on each poll only the dirty nodes' usage recomputes; a change set past
the bounded log horizon falls back to one full rebuild, counted, exactly
the mirror's roll-vs-rebuild economy. It holds NO hot-path hook, NO lock
any decision path takes, and the decision paths are statically barred
from importing it (nomadlint OBS001): the observatory can see the
schedulers, the schedulers cannot see the observatory.

What it keeps, per poll generation:

- per-node totals / reserved / used vectors (RESOURCE_DIMS order) plus a
  schedulable flag (ready, not draining) — the same per-row accounting
  the mirror's base usage starts from;
- per-lane usage: ``service`` / ``batch`` / ``system`` by job type, with
  express-flagged jobs split into their own ``express`` lane (the
  admission front door's lane taxonomy, carried through to capacity);
- **fragmentation histograms**: per dimension, how many schedulable
  nodes sit in each free-fraction decile — the shape of the cell's
  leftover capacity;
- **stranded-capacity %** against seeded reference task shapes: for a
  shape ``s``, free capacity on nodes that cannot host even ONE copy of
  ``s`` is stranded with respect to it. Headline per shape =
  stranded/free on the cpu dimension; per-dim detail attached. Also
  ``placeable_count``: how many copies of ``s`` the cell could still
  host (Σ over nodes of min_d(free_d // s_d)) — the defrag arc's
  "placeable capacity reclaimed per migration" numerator.
- **bin-pack density**: used / capacity-of-occupied-nodes per dimension
  — how tightly the placed work is packed (1.0 = every occupied node
  full; churn shreds this long before aggregate utilization moves).

Surfaces: ``/v1/agent/capacity`` (JSON + ``?format=prometheus``), SDK
``client.agent().capacity()``, periodic ``Capacity``-topic event
snapshots (observer topic — excluded from the canonical determinism
digest by construction, ``events.OBSERVER_TOPICS``), the debug bundle's
``capacity`` section, and ``nomad_capacity_*`` lines on the main
Prometheus scrape.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu import telemetry
from nomad_tpu.structs import NODE_STATUS_READY, RESOURCE_DIMS

# Lane taxonomy: the admission front door's batch/service distinction
# plus the express lane (an express-flagged batch job rides its own
# books there too) and system jobs.
LANES = ("service", "batch", "system", "express")

# Free-fraction deciles for the fragmentation histograms: bin i counts
# schedulable nodes with free/total in [i/10, (i+1)/10) (last bin closed).
FRAG_BINS = 10

# Seeded reference task shapes the stranded-capacity accounting measures
# against. Deliberately pinned (not sampled from live jobs): stranded %
# must be comparable across runs and against the banked defrag baseline,
# so the yardstick cannot drift with the workload. Override per
# deployment via the ``capacity { reference_shapes = [...] }`` block.
DEFAULT_REFERENCE_SHAPES: Tuple[Dict[str, int], ...] = (
    {"name": "small", "cpu": 100, "memory_mb": 128},
    {"name": "medium", "cpu": 500, "memory_mb": 512},
    {"name": "large", "cpu": 2000, "memory_mb": 2048},
)


def _shape_vec(shape: Dict[str, Any]) -> np.ndarray:
    return np.array(
        [int(shape.get(d, 0)) for d in RESOURCE_DIMS], dtype=np.int64
    )


@dataclass
class CapacityConfig:
    """The ``server { capacity { ... } }`` block, parse-time validated
    (the AdmissionConfig/ExpressConfig posture: typos and nonsense
    ranges fail config load, not first use)."""

    enabled: bool = True
    # Change-stream poll cadence. The observer tolerates any cadence —
    # a slow poll just rolls a bigger delta (or rebuilds past the log
    # horizon, counted).
    poll_interval: float = 1.0
    # Cadence of Capacity-topic event snapshots (0 disables). Observer
    # topic: excluded from the canonical event digest by construction.
    events_interval: float = 10.0
    reference_shapes: List[Dict[str, Any]] = field(
        default_factory=lambda: [dict(s) for s in DEFAULT_REFERENCE_SHAPES]
    )

    @classmethod
    def parse(cls, spec: Optional[Dict[str, Any]]) -> "CapacityConfig":
        if spec is None:
            return cls()
        if not isinstance(spec, dict):
            raise ValueError("capacity config must be a mapping")
        known = set(cls.__dataclass_fields__)
        unknown = [k for k in spec if k not in known]
        if unknown:
            raise ValueError(
                f"unknown capacity config key(s): {sorted(unknown)} "
                f"(have: {sorted(known)})"
            )
        out = cls(**{
            k: (bool(v) if k == "enabled"
                else list(v) if k == "reference_shapes"
                else float(v))
            for k, v in spec.items()
        })
        if out.poll_interval <= 0:
            raise ValueError("capacity.poll_interval must be > 0")
        if out.events_interval < 0:
            raise ValueError("capacity.events_interval must be >= 0")
        if not out.reference_shapes:
            raise ValueError("capacity.reference_shapes must be non-empty")
        for shape in out.reference_shapes:
            if not isinstance(shape, dict) or not shape.get("name"):
                raise ValueError(
                    "each reference shape needs at least a name, got "
                    f"{shape!r}"
                )
            vec = _shape_vec(shape)
            if not (vec > 0).any():
                raise ValueError(
                    f"reference shape {shape.get('name')!r} asks for "
                    "nothing (all dims 0)"
                )
        return out


def _lane_of(job) -> str:
    """The lane an allocation's usage books under: express-flagged jobs
    own their lane; otherwise the job type (service/batch/system)."""
    if job is None:
        return "batch"
    if getattr(job, "express", False):
        return "express"
    jtype = getattr(job, "type", "") or "batch"
    return jtype if jtype in LANES else "batch"


class CapacityAccountant:
    """Incremental per-node capacity books over a state store.

    Parallel numpy tables keyed by a node→row index (the mirror's
    layout): a node-change-log roll patches only the touched rows, an
    alloc-change-log roll recomputes usage only for the dirty nodes.
    All tables live under ``_lock``; readers (``snapshot()``) take the
    same lock — no decision path ever does.
    """

    def __init__(self, store_getter: Callable[[], Any],
                 config: Optional[CapacityConfig] = None,
                 events=None):
        self._store = store_getter
        self.config = config or CapacityConfig()
        self._events = events
        self._shapes = [
            (str(s["name"]), _shape_vec(s))
            for s in self.config.reference_shapes
        ]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Table state (under _lock). Rows are append-only within a
        # generation; removals free rows for reuse.
        self._reset_tables()
        # Roll-vs-rebuild economy (honest observability about the
        # observer itself).
        self.rolls = 0
        self.rebuilds = 0
        self.polls = 0
        self.events_published = 0

    # -- tables --------------------------------------------------------------

    def _reset_tables(self, cap: int = 64) -> None:
        self._uid = ""
        self._nodes_index = 0
        self._allocs_index = 0
        self._index: Dict[str, int] = {}
        self._free_rows: List[int] = []
        self._totals = np.zeros((cap, 4), dtype=np.int64)
        self._reserved = np.zeros((cap, 4), dtype=np.int64)
        self._sched = np.zeros(cap, dtype=bool)
        self._alive = np.zeros(cap, dtype=bool)
        # Per-lane usage + alloc counts (reserved is NOT a lane: it is
        # node-operator holdback, accounted separately).
        self._lane_used = {
            lane: np.zeros((cap, 4), dtype=np.int64) for lane in LANES
        }
        self._lane_count = {
            lane: np.zeros(cap, dtype=np.int64) for lane in LANES
        }

    def _grow(self) -> None:
        cap = self._totals.shape[0]
        new_cap = cap * 2

        def wide(a):
            out = np.zeros((new_cap,) + a.shape[1:], dtype=a.dtype)
            out[:cap] = a
            return out

        self._totals = wide(self._totals)
        self._reserved = wide(self._reserved)
        self._sched = wide(self._sched)
        self._alive = wide(self._alive)
        self._lane_used = {k: wide(v) for k, v in self._lane_used.items()}
        self._lane_count = {k: wide(v) for k, v in self._lane_count.items()}

    def _row_for(self, node_id: str) -> int:
        row = self._index.get(node_id)
        if row is not None:
            return row
        if self._free_rows:
            row = self._free_rows.pop()
        else:
            row = len(self._index) + len(self._free_rows)
            while row >= self._totals.shape[0]:
                self._grow()
        self._index[node_id] = row
        self._alive[row] = True
        return row

    def _set_node_row(self, node) -> None:
        row = self._row_for(node.id)
        self._totals[row] = (
            np.asarray(node.resources.as_vector(), dtype=np.int64)
            if node.resources is not None else 0
        )
        self._reserved[row] = (
            np.asarray(node.reserved.as_vector(), dtype=np.int64)
            if node.reserved is not None else 0
        )
        self._sched[row] = (
            node.status == NODE_STATUS_READY and not node.drain
        )

    def _drop_node_row(self, node_id: str) -> None:
        row = self._index.pop(node_id, None)
        if row is None:
            return
        self._alive[row] = False
        self._sched[row] = False
        self._totals[row] = 0
        self._reserved[row] = 0
        for lane in LANES:
            self._lane_used[lane][row] = 0
            self._lane_count[lane][row] = 0
        self._free_rows.append(row)

    # -- incremental refresh -------------------------------------------------

    def refresh(self) -> None:
        """One poll: roll the books forward through the store's change
        logs, or rebuild when the delta cannot be expressed (store
        replaced, log horizon passed). Safe to call from tests without
        the thread."""
        store = self._store()
        if store is None:
            return
        # Sample indexes BEFORE reading the logs: a concurrent write
        # after the sample lands in the next poll's delta, never lost.
        uid = getattr(store, "store_uid", "")
        nidx = store.get_index("nodes")
        aidx = store.get_index("allocs")
        with self._lock:
            self.polls += 1
            if not uid or uid != self._uid:
                self._rebuild_locked(store, uid, nidx, aidx)
                return
            if nidx == self._nodes_index and aidx == self._allocs_index:
                return
            node_changes = store.node_changes_since(self._nodes_index)
            dirty = store.alloc_node_changes_since(self._allocs_index)
            if node_changes is None or dirty is None:
                self._rebuild_locked(store, uid, nidx, aidx)
                return
            self.rolls += 1
            telemetry.incr_counter(("capacity", "rolls"))
            for _idx, node_id, kind in node_changes:
                if kind == "remove":
                    self._drop_node_row(node_id)
                    continue
                node = store.node_by_id(node_id)
                if node is None:
                    # Re-registered then removed inside the slice: the
                    # remove entry follows and drops the row.
                    continue
                self._set_node_row(node)
            if dirty:
                self._recompute_usage_locked(store, set(dirty))
            self._nodes_index = max(nidx, self._nodes_index)
            self._allocs_index = max(aidx, self._allocs_index)

    def _rebuild_locked(self, store, uid: str, nidx: int, aidx: int) -> None:
        self.rebuilds += 1
        telemetry.incr_counter(("capacity", "rebuilds"))
        self._reset_tables()
        self._uid = uid
        self._nodes_index = nidx
        self._allocs_index = aidx
        for node in store.nodes():
            self._set_node_row(node)
        self._recompute_usage_locked(store, None)

    def _recompute_usage_locked(self, store, dirty) -> None:
        """Recompute lane usage for ``dirty`` node ids (None = every
        resident node): zero the rows, then one pass over the object
        rows and one over the columnar blocks — O(dirty allocs + total
        block runs), the mirror's _usage_rows_bulk shape."""
        index_get = self._index.get
        if dirty is None:
            rows = [r for r in self._index.values()]
            dirty_ids = list(self._index)
        else:
            rows = []
            dirty_ids = []
            for nid in dirty:
                row = index_get(nid)
                if row is not None:
                    rows.append(row)
                    dirty_ids.append(nid)
        if not rows:
            return
        rows_arr = np.asarray(rows, dtype=np.int64)
        for lane in LANES:
            self._lane_used[lane][rows_arr] = 0
            self._lane_count[lane][rows_arr] = 0
        for nid, row in zip(dirty_ids, rows):
            for a in store.allocs_by_node_objects(nid):
                if a.terminal_status():
                    continue
                lane = _lane_of(a.job)
                if a.resources is not None:
                    self._lane_used[lane][row] += np.asarray(
                        a.resources.as_vector(), dtype=np.int64
                    )
                self._lane_count[lane][row] += 1
        in_dirty = np.zeros(self._totals.shape[0], dtype=bool)
        in_dirty[rows_arr] = True
        for blk in store.alloc_blocks():
            lane = _lane_of(blk.job)
            vec = (
                np.asarray(blk.resources.as_vector(), dtype=np.int64)
                if blk.resources is not None
                else np.zeros(4, dtype=np.int64)
            )
            for nid, cnt in blk.live_node_counts():
                row = index_get(nid)
                if row is None or not in_dirty[row]:
                    continue
                self._lane_used[lane][row] += vec * cnt
                self._lane_count[lane][row] += cnt

    # -- aggregates ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``/v1/agent/capacity`` body: per-dimension utilization,
        bin-pack density, per-lane usage, fragmentation histograms, and
        per-reference-shape stranded-capacity accounting — all computed
        over the SCHEDULABLE node set (ready, not draining)."""
        with self._lock:
            alive = self._alive
            sched = self._sched & alive
            n_alive = int(alive.sum())
            n_sched = int(sched.sum())
            totals = self._totals[sched]
            reserved = self._reserved[sched]
            used = reserved.copy()
            lanes_out: Dict[str, Any] = {}
            occupied_mask = np.zeros(totals.shape[0], dtype=bool)
            for lane in LANES:
                lu = self._lane_used[lane][sched]
                lc = self._lane_count[lane][sched]
                used += lu
                occupied_mask |= lc > 0
                lanes_out[lane] = {
                    "allocs": int(lc.sum()),
                    "used": {d: int(v) for d, v in
                             zip(RESOURCE_DIMS, lu.sum(axis=0))},
                }
            total_sum = totals.sum(axis=0)
            used_sum = used.sum(axis=0)
            free = np.maximum(totals - used, 0)
            free_sum = free.sum(axis=0)

            util = {
                d: round(float(u) / float(t), 6) if t else 0.0
                for d, u, t in zip(RESOURCE_DIMS, used_sum, total_sum)
            }
            # Bin-pack density: how full are the nodes that host work at
            # all. Churn strands capacity by spreading remnants across
            # many half-empty nodes — density drops while aggregate
            # utilization barely moves.
            occ_totals = totals[occupied_mask].sum(axis=0)
            occ_used = used[occupied_mask].sum(axis=0)
            density = {
                d: round(float(u) / float(t), 6) if t else 0.0
                for d, u, t in zip(RESOURCE_DIMS, occ_used, occ_totals)
            }

            # Fragmentation histograms: free-fraction deciles per dim
            # over schedulable nodes with capacity in that dim.
            frag: Dict[str, List[int]] = {}
            for di, dim in enumerate(RESOURCE_DIMS):
                has = totals[:, di] > 0
                if not has.any():
                    frag[dim] = [0] * FRAG_BINS
                    continue
                frac = free[has, di] / totals[has, di]
                bins = np.minimum(
                    (frac * FRAG_BINS).astype(np.int64), FRAG_BINS - 1
                )
                frag[dim] = np.bincount(
                    bins, minlength=FRAG_BINS
                ).tolist()

            # Stranded capacity per reference shape: free capacity on
            # nodes that cannot host even one copy of the shape.
            stranded_out = []
            for name, svec in self._shapes:
                ask_dims = svec > 0
                fits = np.all(
                    free[:, ask_dims] >= svec[ask_dims], axis=1
                ) if totals.shape[0] else np.zeros(0, dtype=bool)
                stranded_free = free[~fits].sum(axis=0)
                per_dim = {
                    d: round(float(s) / float(f), 6) if f else 0.0
                    for d, s, f in zip(RESOURCE_DIMS, stranded_free,
                                       free_sum)
                }
                # Copies of the shape the cell could still host.
                if totals.shape[0] and fits.any():
                    per_node = np.min(
                        free[fits][:, ask_dims] // svec[ask_dims], axis=1
                    )
                    placeable = int(per_node.sum())
                else:
                    placeable = 0
                stranded_out.append({
                    "shape": name,
                    "ask": {d: int(v) for d, v in zip(RESOURCE_DIMS, svec)
                            if v},
                    # Headline: the cpu dimension (first RESOURCE_DIM,
                    # the scarce currency of the sim workloads); per-dim
                    # detail alongside.
                    "stranded_pct": per_dim[RESOURCE_DIMS[0]],
                    "stranded_pct_by_dim": per_dim,
                    "placeable_count": placeable,
                    "nodes_fitting": int(fits.sum()),
                })

            return {
                "generation": {
                    "store_uid": self._uid,
                    "nodes_index": self._nodes_index,
                    "allocs_index": self._allocs_index,
                },
                "nodes": {
                    "total": n_alive,
                    "schedulable": n_sched,
                    "occupied": int(occupied_mask.sum()),
                },
                "dims": list(RESOURCE_DIMS),
                "total": {d: int(v) for d, v in
                          zip(RESOURCE_DIMS, total_sum)},
                "used": {d: int(v) for d, v in zip(RESOURCE_DIMS, used_sum)},
                "free": {d: int(v) for d, v in zip(RESOURCE_DIMS, free_sum)},
                "reserved": {d: int(v) for d, v in
                             zip(RESOURCE_DIMS, reserved.sum(axis=0))},
                "utilization": util,
                "binpack_density": density,
                "lanes": lanes_out,
                "fragmentation": {"bins": FRAG_BINS, "free_fraction": frag},
                "stranded": stranded_out,
                "accountant": {
                    "polls": self.polls,
                    "rolls": self.rolls,
                    "rebuilds": self.rebuilds,
                    "events_published": self.events_published,
                },
            }

    def summary(self) -> Dict[str, Any]:
        """Compact agent-info line: headline utilization + worst shape's
        stranded %."""
        snap = self.snapshot()
        worst = max(
            (s["stranded_pct"] for s in snap["stranded"]), default=0.0
        )
        return {
            "utilization": snap["utilization"],
            "stranded_pct_worst": worst,
            "nodes": snap["nodes"],
        }

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if not self.config.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="capacity-accountant"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        import time as _time

        next_event = (
            _time.monotonic() + self.config.events_interval
            if self.config.events_interval else None
        )
        while not self._stop.wait(self.config.poll_interval):
            try:
                self.refresh()
                if (next_event is not None
                        and _time.monotonic() >= next_event):
                    next_event = (
                        _time.monotonic() + self.config.events_interval
                    )
                    self.publish_event()
            except Exception:
                # The observer must never take the agent down; the poll
                # loop retries next tick. Counted, not silent.
                telemetry.incr_counter(("capacity", "poll_errors"))

    def publish_event(self) -> None:
        """One Capacity-topic snapshot event (trimmed payload). Observer
        topic: excluded from canonical event digests by construction
        (events.OBSERVER_TOPICS), so publishing cadence can never perturb
        the determinism contract."""
        if self._events is None:
            return
        snap = self.snapshot()
        self._events.publish(
            "Capacity", "CapacitySnapshot", key="capacity",
            payload={
                "utilization": snap["utilization"],
                "binpack_density": snap["binpack_density"],
                "stranded": [
                    {"shape": s["shape"],
                     "stranded_pct": s["stranded_pct"],
                     "placeable_count": s["placeable_count"]}
                    for s in snap["stranded"]
                ],
                "nodes": snap["nodes"],
            },
        )
        self.events_published += 1
