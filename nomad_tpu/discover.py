"""Locate the runnable nomad-tpu entrypoint for re-exec.

Reference: /root/reference/helper/discover/discover.go — finds the nomad
binary (argv[0], $GOPATH/bin, CWD) so the spawn daemon can re-exec it.
Here the "binary" is the interpreter + module invocation; drivers use this
to build the ``spawn-daemon`` command line regardless of how the agent was
started (console script, ``python -m nomad_tpu``, or a test process).
"""

from __future__ import annotations

import os
import shutil
import sys
from typing import List


def nomad_command() -> List[str]:
    """Command prefix that reaches the nomad-tpu CLI from a fresh process."""
    # A console script on PATH wins (discover.go checks the executable path
    # first); fall back to the module entrypoint of this interpreter.
    script = shutil.which("nomad-tpu")
    if script and os.access(script, os.X_OK):
        return [script]
    return [sys.executable, "-m", "nomad_tpu"]


def spawn_daemon_command(spec_json: str) -> List[str]:
    """Command line for the spawn-daemon plumbing command
    (command/spawn_daemon.go re-exec via helper/discover)."""
    return nomad_command() + ["spawn-daemon", spec_json]
