"""Core data model for the scheduler.

This is a fresh, Python-idiomatic data model with the same capabilities as the
reference's ``nomad/structs/structs.go`` (see SURVEY.md §2.2). Field-for-field
parity is intentional where the scheduler semantics depend on it (resource
dimensions, statuses, plan shape); representation is not (dataclasses instead
of msgpack-tagged Go structs).

Reference citations (``file:line`` into /root/reference):
- Node:            nomad/structs/structs.go:447-543
- Resources:       nomad/structs/structs.go:547-621
- Job/TaskGroup/Task: nomad/structs/structs.go:742-1075
- Constraint:      nomad/structs/structs.go:1077-1112
- Allocation:      nomad/structs/structs.go:1129-1222
- AllocMetric:     nomad/structs/structs.go:1227-1307
- Evaluation:      nomad/structs/structs.go:1341-1457
- Plan/PlanResult: nomad/structs/structs.go:1462-1575
- fit/score funcs: nomad/structs/funcs.go:9-124
"""

from __future__ import annotations

import copy as _copy
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Constants
# ---------------------------------------------------------------------------

NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

JOB_TYPE_CORE = "_core"
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_COMPLETE = "complete"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50

# Blocking-query wait ceiling (rpc.go:283-291 maxQueryTime): the server
# clamps client-supplied ?wait to this; transport hops (uplink provider,
# SDK socket) allow MAX_QUERY_TIME + MAX_QUERY_TIME_PAD so a max-length
# poll always outlives the server's clamp, never the other way around.
MAX_QUERY_TIME = 300.0
MAX_QUERY_TIME_PAD = 30.0
JOB_MAX_PRIORITY = 100
CORE_JOB_PRIORITY = JOB_MAX_PRIORITY * 2

ALLOC_DESIRED_STATUS_RUN = "run"
ALLOC_DESIRED_STATUS_STOP = "stop"
ALLOC_DESIRED_STATUS_EVICT = "evict"
ALLOC_DESIRED_STATUS_FAILED = "failed"

ALLOC_CLIENT_STATUS_PENDING = "pending"
ALLOC_CLIENT_STATUS_RUNNING = "running"
ALLOC_CLIENT_STATUS_DEAD = "dead"
ALLOC_CLIENT_STATUS_FAILED = "failed"

EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"

EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
# Express lane (nomad_tpu/server/express.py): the in-line placement's
# COMPLETE eval, and the PENDING eval a bounced-out/failed-over entry
# reconciles through (the generic scheduler accepts the latter).
EVAL_TRIGGER_EXPRESS = "express"
EVAL_TRIGGER_EXPRESS_RECONCILE = "express-reconcile"

CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"

CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"

# The dense resource dimensions the TPU solver packs into a vector.
# Order matters: it is the column order of node/ask tensors in nomad_tpu.ops.
RESOURCE_DIMS = ("cpu", "memory_mb", "disk_mb", "iops")


def generate_uuid() -> str:
    """Random UUID (reference: nomad/structs/funcs.go:126-139).

    Formatted from os.urandom directly — ~3x faster than uuid.uuid4() and
    hot at bench scale (one per Allocation, 100k per big eval)."""
    h = os.urandom(16).hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def generate_uuids(n: int) -> List[str]:
    """Batch of ``n`` UUIDs from one urandom read. One uuid per Allocation is
    hot at bench scale (100k per big eval); batching is ~4x generate_uuid."""
    h = os.urandom(16 * n).hex()
    return [
        f"{h[i:i + 8]}-{h[i + 8:i + 12]}-{h[i + 12:i + 16]}"
        f"-{h[i + 16:i + 20]}-{h[i + 20:i + 32]}"
        for i in range(0, 32 * n, 32)
    ]


# ---------------------------------------------------------------------------
# Errors
# ---------------------------------------------------------------------------


class ValidationError(Exception):
    """Aggregated validation failure (reference uses go-multierror)."""

    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


# ---------------------------------------------------------------------------
# Typed admission/backpressure rejection (nomad_tpu/server/admission.py)
# ---------------------------------------------------------------------------

# Rejection reasons. The front door's whole contract is that a rejection
# is CHEAP and TYPED: the caller learns why it was turned away and when to
# come back, and — critically — that the request provably executed NO
# server-side side effect, so replaying it is always safe.
REJECT_QUEUE_FULL = "QUEUE_FULL"      # acceptance queue at its cap
REJECT_RATE_LIMITED = "RATE_LIMITED"  # per-client token-bucket lane empty
REJECT_SHED = "SHED"                  # SLO-coupled load shedding
REJECT_WATCH_LIMIT = "WATCH_LIMIT"    # blocking-query watcher cap reached
# Stale-lane staleness bound exceeded: the serving follower's last leader
# contact is older than the client's max_stale bound. Retriable by
# construction — a read has no side effects and another server (or the
# same one after its next heartbeat) can satisfy the bound.
REJECT_STALE_BOUND = "STALE_BOUND"

# The wire marker RejectError stringifies to. It must survive the RPC
# error envelope (handlers' exceptions cross as "RejectError: <str(e)>"
# inside a RemoteError) and nested forwarding prefixes, so parse_reject
# regex-searches rather than anchors.
_REJECT_RE = re.compile(
    r"REJECT\[([A-Z_]+) retry_after=([0-9.]+)\](?::\s*(.*))?"
)


class RejectError(Exception):
    """Typed, cheap rejection from the admission/backpressure machinery.

    Carries the reason and a retry-after hint (seconds). Raised BEFORE any
    raft apply / queue mutation, so a rejected request had zero side
    effects and the client may replay it after the hint — the property the
    SDK's retry discipline (backoff.retry_undelivered, api/client.py)
    relies on. Stringifies to a greppable ``REJECT[...]`` marker that
    ``parse_reject`` recovers on the far side of an RPC/HTTP boundary.
    """

    def __init__(self, reason: str, message: str = "",
                 retry_after: float = 0.0):
        self.reason = reason
        self.retry_after = max(0.0, float(retry_after))
        self.message = message
        super().__init__(
            f"REJECT[{reason} retry_after={self.retry_after:.3f}]"
            + (f": {message}" if message else "")
        )


def parse_reject(text: str) -> Optional[RejectError]:
    """Recover a typed RejectError from an error string that crossed a
    transport boundary (RemoteError message, HTTP error body). Returns
    None when the text carries no REJECT marker."""
    m = _REJECT_RE.search(text or "")
    if m is None:
        return None
    try:
        retry_after = float(m.group(2))
    except ValueError:
        retry_after = 0.0
    return RejectError(m.group(1), (m.group(3) or "").strip(),
                       retry_after=retry_after)


# ---------------------------------------------------------------------------
# Resources & network
# ---------------------------------------------------------------------------


@dataclass
class NetworkResource:
    """Network ask/offer (reference: nomad/structs/structs.go:625-703)."""

    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: List[int] = field(default_factory=list)
    dynamic_ports: List[str] = field(default_factory=list)
    # True once this is an *offer* with assigned dynamic ports appended to
    # reserved_ports (set by NetworkIndex.assign_network); raw asks are False.
    offered: bool = False

    def copy(self) -> "NetworkResource":
        new = _copy.copy(self)
        new.reserved_ports = list(self.reserved_ports)
        new.dynamic_ports = list(self.dynamic_ports)
        return new

    def add(self, delta: "NetworkResource") -> None:
        if delta.reserved_ports:
            self.reserved_ports.extend(delta.reserved_ports)
        self.mbits += delta.mbits
        self.dynamic_ports.extend(delta.dynamic_ports)

    def map_dynamic_ports(self) -> Dict[str, int]:
        """Label -> assigned port for dynamic ports; the offer process appends
        assigned dynamic ports to reserved_ports (structs.go:659-696).
        Returns {} on a raw (unoffered) ask — there is nothing assigned yet."""
        if not self.offered:
            return {}
        ports = self.reserved_ports[len(self.reserved_ports) - len(self.dynamic_ports):]
        return {label: ports[i] for i, label in enumerate(self.dynamic_ports)}

    def list_static_ports(self) -> List[int]:
        return self.reserved_ports[: len(self.reserved_ports) - len(self.dynamic_ports)]


@dataclass
class Resources:
    """Schedulable resources (reference: nomad/structs/structs.go:547-621)."""

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    iops: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    def copy(self) -> "Resources":
        new = _copy.copy(self)
        new.networks = [n.copy() for n in self.networks]
        return new

    def net_index(self, n: NetworkResource) -> int:
        for idx, net in enumerate(self.networks):
            if net.device == n.device:
                return idx
        return -1

    def superset(self, other: "Resources") -> Tuple[bool, str]:
        """Dimension-wise >= check, network handled by NetworkIndex
        (structs.go:577-594)."""
        if self.cpu < other.cpu:
            return False, "cpu exhausted"
        if self.memory_mb < other.memory_mb:
            return False, "memory exhausted"
        if self.disk_mb < other.disk_mb:
            return False, "disk exhausted"
        if self.iops < other.iops:
            return False, "iops exhausted"
        return True, ""

    def add(self, delta: Optional["Resources"]) -> None:
        if delta is None:
            return
        self.cpu += delta.cpu
        self.memory_mb += delta.memory_mb
        self.disk_mb += delta.disk_mb
        self.iops += delta.iops
        for n in delta.networks:
            idx = self.net_index(n)
            if idx == -1:
                self.networks.append(n.copy())
            else:
                self.networks[idx].add(n)

    def as_vector(self) -> Tuple[int, int, int, int]:
        """Dense vector in RESOURCE_DIMS order for the TPU solver."""
        return (self.cpu, self.memory_mb, self.disk_mb, self.iops)


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


def should_drain_node(status: str) -> bool:
    """Whether a node status forces migrations (structs.go:423-434)."""
    if status in (NODE_STATUS_INIT, NODE_STATUS_READY):
        return False
    if status == NODE_STATUS_DOWN:
        return True
    raise ValueError(f"unhandled node status {status}")


def valid_node_status(status: str) -> bool:
    return status in (NODE_STATUS_INIT, NODE_STATUS_READY, NODE_STATUS_DOWN)


@dataclass
class Node:
    """A schedulable client node (reference: nomad/structs/structs.go:447-543)."""

    id: str = ""
    datacenter: str = ""
    name: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    resources: Optional[Resources] = None
    reserved: Optional[Resources] = None
    links: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_class: str = ""
    drain: bool = False
    status: str = ""
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def copy(self) -> "Node":
        new = _copy.copy(self)
        new.attributes = dict(self.attributes)
        new.links = dict(self.links)
        new.meta = dict(self.meta)
        new.resources = self.resources.copy() if self.resources else None
        new.reserved = self.reserved.copy() if self.reserved else None
        return new

    def stub(self) -> Dict[str, Any]:
        """Summarized view for list endpoints (structs.go:516-529)."""
        return {
            "id": self.id,
            "datacenter": self.datacenter,
            "name": self.name,
            "node_class": self.node_class,
            "drain": self.drain,
            "status": self.status,
            "status_description": self.status_description,
            "create_index": self.create_index,
            "modify_index": self.modify_index,
        }


# ---------------------------------------------------------------------------
# Job / TaskGroup / Task / Constraint
# ---------------------------------------------------------------------------


@dataclass
class UpdateStrategy:
    """Rolling update control (reference: structs.go:897-908).
    ``stagger`` is in seconds (the reference uses time.Duration)."""

    stagger: float = 0.0
    max_parallel: int = 0

    def rolling(self) -> bool:
        return self.stagger > 0 and self.max_parallel > 0


@dataclass
class RestartPolicy:
    """Client-side task restart policy (reference: structs.go:912-935).
    Durations are seconds."""

    attempts: int = 0
    interval: float = 0.0
    delay: float = 0.0

    def validate(self) -> None:
        if self.attempts * self.delay > self.interval:
            raise ValidationError(
                [
                    f"can't restart task group {self.attempts} times in an interval "
                    f"of {self.interval}s with a delay of {self.delay}s"
                ]
            )


DEFAULT_SERVICE_RESTART_POLICY = RestartPolicy(attempts=2, interval=600.0, delay=15.0)
DEFAULT_BATCH_RESTART_POLICY = RestartPolicy(attempts=15, interval=7 * 24 * 3600.0, delay=15.0)


def new_restart_policy(job_type: str) -> Optional[RestartPolicy]:
    if job_type in (JOB_TYPE_SERVICE, JOB_TYPE_SYSTEM):
        return _copy.copy(DEFAULT_SERVICE_RESTART_POLICY)
    if job_type == JOB_TYPE_BATCH:
        return _copy.copy(DEFAULT_BATCH_RESTART_POLICY)
    return None


@dataclass
class Constraint:
    """Placement restriction (reference: structs.go:1077-1112)."""

    l_target: str = ""
    r_target: str = ""
    operand: str = ""

    def __str__(self) -> str:
        return f"{self.l_target} {self.operand} {self.r_target}"

    def validate(self) -> None:
        errors: List[str] = []
        if not self.operand:
            errors.append("missing constraint operand")
        if self.operand == CONSTRAINT_REGEX:
            try:
                re.compile(self.r_target)
            except re.error as e:
                errors.append(f"regular expression failed to compile: {e}")
        elif self.operand == CONSTRAINT_VERSION:
            from nomad_tpu.version import parse_constraints

            try:
                parse_constraints(self.r_target)
            except ValueError as e:
                errors.append(f"version constraint is invalid: {e}")
        if errors:
            raise ValidationError(errors)


@dataclass
class Task:
    """A single schedulable process (reference: structs.go:1027-1075)."""

    name: str = ""
    driver: str = ""
    config: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    constraints: List[Constraint] = field(default_factory=list)
    resources: Optional[Resources] = None
    meta: Dict[str, str] = field(default_factory=dict)

    def validate(self) -> None:
        errors: List[str] = []
        if not self.name:
            errors.append("missing task name")
        if not self.driver:
            errors.append("missing task driver")
        if self.resources is None:
            errors.append("missing task resources")
        for idx, constr in enumerate(self.constraints):
            try:
                constr.validate()
            except ValidationError as e:
                errors.append(f"constraint {idx + 1} validation failed: {e}")
        if errors:
            raise ValidationError(errors)


@dataclass
class TaskGroup:
    """Atomic unit of placement (reference: structs.go:940-1024)."""

    name: str = ""
    count: int = 1
    constraints: List[Constraint] = field(default_factory=list)
    restart_policy: Optional[RestartPolicy] = None
    tasks: List[Task] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None

    def validate(self) -> None:
        errors: List[str] = []
        if not self.name:
            errors.append("missing task group name")
        if self.count <= 0:
            errors.append("task group count must be positive")
        if not self.tasks:
            errors.append("missing tasks for task group")
        for idx, constr in enumerate(self.constraints):
            try:
                constr.validate()
            except ValidationError as e:
                errors.append(f"constraint {idx + 1} validation failed: {e}")
        if self.restart_policy is not None:
            try:
                self.restart_policy.validate()
            except ValidationError as e:
                errors.append(str(e))
        else:
            errors.append(f"task group {self.name} should have a restart policy")
        seen: Dict[str, int] = {}
        for idx, task in enumerate(self.tasks):
            if not task.name:
                errors.append(f"task {idx + 1} missing name")
            elif task.name in seen:
                errors.append(
                    f"task {idx + 1} redefines '{task.name}' from task {seen[task.name] + 1}"
                )
            else:
                seen[task.name] = idx
        for idx, task in enumerate(self.tasks):
            try:
                task.validate()
            except ValidationError as e:
                errors.append(f"task {idx + 1} validation failed: {e}")
        if errors:
            raise ValidationError(errors)


@dataclass
class Job:
    """Scope of a scheduling request (reference: structs.go:742-894)."""

    region: str = ""
    id: str = ""
    name: str = ""
    type: str = ""
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    # Express-lane opt-in (nomad_tpu/server/express.py): short-lived
    # batch work that prefers sub-millisecond leader-local placement
    # over globally-optimal solving. Eligibility is checked server-side
    # (batch type, small count, no ports); ineligible or lane-off
    # submissions take the ordinary path — the flag is a hint, not a
    # contract change.
    express: bool = False
    datacenters: List[str] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: UpdateStrategy = field(default_factory=UpdateStrategy)
    meta: Dict[str, str] = field(default_factory=dict)
    status: str = ""
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def validate(self) -> None:
        errors: List[str] = []
        if not self.region:
            errors.append("missing job region")
        if not self.id:
            errors.append("missing job ID")
        elif " " in self.id:
            errors.append("job ID contains a space")
        if not self.name:
            errors.append("missing job name")
        if not self.type:
            errors.append("missing job type")
        if self.priority < JOB_MIN_PRIORITY or self.priority > JOB_MAX_PRIORITY:
            errors.append(
                f"job priority must be between [{JOB_MIN_PRIORITY}, {JOB_MAX_PRIORITY}]"
            )
        if not self.datacenters:
            errors.append("missing job datacenters")
        if not self.task_groups:
            errors.append("missing job task groups")
        for idx, constr in enumerate(self.constraints):
            try:
                constr.validate()
            except ValidationError as e:
                errors.append(f"constraint {idx + 1} validation failed: {e}")
        seen: Dict[str, int] = {}
        for idx, tg in enumerate(self.task_groups):
            if not tg.name:
                errors.append(f"job task group {idx + 1} missing name")
            elif tg.name in seen:
                errors.append(
                    f"job task group {idx + 1} redefines '{tg.name}' from group {seen[tg.name] + 1}"
                )
            else:
                seen[tg.name] = idx
            if self.type == JOB_TYPE_SYSTEM and tg.count != 1:
                errors.append(
                    f"job task group {idx + 1} has count {tg.count}; "
                    "only count of 1 is supported with system scheduler"
                )
        for idx, tg in enumerate(self.task_groups):
            try:
                tg.validate()
            except ValidationError as e:
                errors.append(f"task group {idx + 1} validation failed: {e}")
        if errors:
            raise ValidationError(errors)

    def stub(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "type": self.type,
            "priority": self.priority,
            "status": self.status,
            "status_description": self.status_description,
            "create_index": self.create_index,
            "modify_index": self.modify_index,
        }


# ---------------------------------------------------------------------------
# Allocation
# ---------------------------------------------------------------------------


@dataclass
class AllocMetric:
    """Per-placement scheduling observability (reference: structs.go:1227-1307)."""

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    scores: Dict[str, float] = field(default_factory=dict)
    allocation_time: float = 0.0  # seconds
    coalesced_failures: int = 0

    def evaluate_node(self, n: int = 1) -> None:
        self.nodes_evaluated += n

    def filter_node(self, node: Optional[Node], constraint: str, n: int = 1) -> None:
        self.nodes_filtered += n
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = (
                self.class_filtered.get(node.node_class, 0) + n
            )
        if constraint:
            self.constraint_filtered[constraint] = (
                self.constraint_filtered.get(constraint, 0) + n
            )

    def exhausted_node(self, node: Optional[Node], dimension: str, n: int = 1) -> None:
        self.nodes_exhausted += n
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = (
                self.class_exhausted.get(node.node_class, 0) + n
            )
        if dimension:
            self.dimension_exhausted[dimension] = (
                self.dimension_exhausted.get(dimension, 0) + n
            )

    def score_node(self, node: Node, name: str, score: float) -> None:
        self.scores[f"{node.id}.{name}"] = score


@dataclass
class Allocation:
    """Placement of a task group on a node (reference: structs.go:1129-1222)."""

    id: str = ""
    eval_id: str = ""
    name: str = ""
    node_id: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    resources: Optional[Resources] = None
    task_resources: Dict[str, Resources] = field(default_factory=dict)
    metrics: Optional[AllocMetric] = None
    desired_status: str = ""
    desired_description: str = ""
    client_status: str = ""
    client_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def terminal_status(self) -> bool:
        """Based on desired status, like the reference (structs.go:1179-1188)."""
        return self.desired_status in (
            ALLOC_DESIRED_STATUS_STOP,
            ALLOC_DESIRED_STATUS_EVICT,
            ALLOC_DESIRED_STATUS_FAILED,
        )

    def copy(self) -> "Allocation":
        """Shallow copy mirroring Go's ``*newAlloc = *alloc``."""
        return _copy.copy(self)

    def stub(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "eval_id": self.eval_id,
            "name": self.name,
            "node_id": self.node_id,
            "job_id": self.job_id,
            "task_group": self.task_group,
            "desired_status": self.desired_status,
            "desired_description": self.desired_description,
            "client_status": self.client_status,
            "client_description": self.client_description,
            "create_index": self.create_index,
            "modify_index": self.modify_index,
        }


# ---------------------------------------------------------------------------
# Evaluation / Plan
# ---------------------------------------------------------------------------


@dataclass
class Evaluation:
    """Unit of scheduler work (reference: structs.go:1341-1457)."""

    id: str = ""
    priority: int = 0
    type: str = ""
    triggered_by: str = ""
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    status: str = ""
    status_description: str = ""
    wait: float = 0.0  # seconds
    next_eval: str = ""
    previous_eval: str = ""
    create_index: int = 0
    modify_index: int = 0

    def terminal_status(self) -> bool:
        return self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED)

    def copy(self) -> "Evaluation":
        return _copy.copy(self)

    def should_enqueue(self) -> bool:
        if self.status == EVAL_STATUS_PENDING:
            return True
        if self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED):
            return False
        raise ValueError(f"unhandled evaluation ({self.id}) status {self.status}")

    def make_plan(self, job: Optional[Job]) -> "Plan":
        plan = Plan(
            eval_id=self.id,
            priority=self.priority,
            node_update={},
            node_allocation={},
        )
        if job is not None:
            plan.all_at_once = job.all_at_once
        return plan

    def next_rolling_eval(self, wait: float) -> "Evaluation":
        return Evaluation(
            id=generate_uuid(),
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait=wait,
            previous_eval=self.id,
        )


class AllocBatch:
    """Columnar block of placements sharing one (eval, job, task group).

    The TPU-native alternative to per-Allocation object flow: a big solve
    returns per-node placement counts, and this block carries them through
    plan verification and commit as arrays — node runs, name indices, and a
    single hex block for ids — materializing Allocation objects only at the
    FSM/state boundary. The reference has no analog (every placement is an
    individual Allocation, structs.go:1129-1222); semantically a batch is
    exactly its ``materialize()`` expansion.

    Layout:
    - ``node_ids``/``node_counts``: run-length encoded placements per node,
      in solve-output order.
    - ``name_idx``: per-placement index into the task group's count
      expansion (util.go:19-34 names ``job.tg[i]``), aligned with the
      run expansion order.
    - ``ids_hex``: 32 hex chars per placement; alloc ids are formatted
      lazily from slices. The hex itself is DERIVED, not stored: a batch
      built with ``ids_seed`` (a 128-bit int) expands the seed through a
      deterministic SHAKE-256 stream on first read — id i is always bytes
      [16i, 16i+16) of the stream, so every replica's FSM derives
      identical ids from the 16-byte seed that rode the wire/log instead
      of a multi-MB hex column. The scheduler's hot path never reads ids
      (plan verify is columnar), so at headline scale the entropy+hex
      cost (~4ms/100k ids) simply never happens until a client syncs.
    """

    __slots__ = (
        "eval_id", "job", "tg_name", "resources", "task_resources",
        "metrics", "node_ids", "node_counts", "name_idx", "_ids_hex",
        "ids_seed", "src_ids_ref", "src_rows",
    )

    def __init__(self, eval_id="", job=None, tg_name="", resources=None,
                 task_resources=None, metrics=None, node_ids=None,
                 node_counts=None, name_idx=None, ids_hex="",
                 ids_seed=None):
        self.eval_id = eval_id
        self.job = job
        self.tg_name = tg_name
        self.resources = resources
        self.task_resources = task_resources or {}
        self.metrics = metrics
        self.node_ids: List[str] = node_ids or []
        self.node_counts: List[int] = node_counts or []
        # Always an int64 ndarray: every consumer (block reconcile, name
        # materialization) may index or .max() it, and construction paths
        # (filter_nodes partial keep, from_wire) otherwise hand in lists.
        import numpy as _np

        self.name_idx = (
            None if name_idx is None
            else _np.asarray(name_idx, dtype=_np.int64)
        )
        self.ids_seed = ids_seed
        # Explicit hex wins (wire compat, partial-keep slices); a seed
        # without hex stays lazy until something actually reads ids.
        self._ids_hex = ids_hex if ids_hex or ids_seed is None else None
        # Optional solver-mirror row hint (NOT serialized): the mirror's
        # id array plus row indices into it, aligned with node_ids. Lets
        # the plan verifier resolve node runs as array gathers; any path
        # that can't keep the alignment (wire, partial keep) leaves it
        # None and the verifier falls back to id lookups.
        self.src_ids_ref = None
        self.src_rows = None

    @property
    def n(self) -> int:
        return len(self.name_idx) if self.name_idx is not None else 0

    @property
    def src_hint(self):
        """(mirror id array, row indices) when the solver recorded where
        this batch's node runs live in its mirror, else None."""
        if self.src_rows is None or self.src_ids_ref is None:
            return None
        return (self.src_ids_ref, self.src_rows)

    @property
    def ids_hex(self) -> str:
        h = self._ids_hex
        if h is None:
            h = self._derive_ids_hex(self.n)
            self._ids_hex = h
        return h

    def _derive_ids_hex(self, count: int) -> str:
        """Expand the seed into ``count`` 32-hex-char ids via SHAKE-256.
        An XOF's output is a stream — shorter digests are prefixes of
        longer ones — and FIPS-202 pins the stream bit-for-bit forever,
        so replicas (and future interpreter/library versions) derive
        identical ids from a logged seed. A PRNG would be faster but
        numpy guarantees no cross-version stream stability, which a
        durable id column cannot tolerate."""
        import hashlib

        seed = int(self.ids_seed).to_bytes(16, "little", signed=False)
        return hashlib.shake_256(seed).hexdigest(16 * count)

    @property
    def ids_lazy(self) -> bool:
        """True while the id column is still an unexpanded seed."""
        return self._ids_hex is None

    def alloc_id(self, i: int) -> str:
        if self._ids_hex is None and i == 0:
            # First-member id (the deterministic block id) without
            # expanding the whole column: an XOF's 16-byte digest is a
            # prefix of any longer digest from the same input.
            h = self._derive_ids_hex(1)
        else:
            h = self.ids_hex[32 * i: 32 * i + 32]
        return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"

    def resource_vector(self) -> List[int]:
        if self.resources is None:
            return [0, 0, 0, 0]
        return self.resources.as_vector()

    def filter_nodes(self, fit: Dict[str, bool]) -> "AllocBatch":
        """Committable subset: keep only runs on nodes with fit=True.
        Per-placement columns stay aligned because runs are contiguous."""
        if all(fit.get(nid, False) for nid in self.node_ids):
            return self
        node_ids: List[str] = []
        node_counts: List[int] = []
        keep_slices = []
        pos = 0
        for nid, cnt in zip(self.node_ids, self.node_counts):
            if fit.get(nid, False):
                node_ids.append(nid)
                node_counts.append(cnt)
                keep_slices.append((pos, pos + cnt))
            pos += cnt
        name_idx = [v for s, e in keep_slices for v in self.name_idx[s:e]]
        ids_hex = "".join(
            self.ids_hex[32 * s: 32 * e] for s, e in keep_slices
        )
        return AllocBatch(
            eval_id=self.eval_id, job=self.job, tg_name=self.tg_name,
            resources=self.resources, task_resources=self.task_resources,
            metrics=self.metrics, node_ids=node_ids, node_counts=node_counts,
            name_idx=name_idx, ids_hex=ids_hex,
        )

    # Stored-form overrides (state/blocks.py StoredAllocBlock): a plain
    # batch has no commit indexes and no excluded members.
    create_index = 0
    modify_index = 0
    excluded: frozenset = frozenset()

    def _template(self) -> dict:
        job_name = self.job.name if self.job is not None else ""
        job_id = self.job.id if self.job is not None else ""
        return {
            "id": "", "eval_id": self.eval_id, "name": "", "node_id": "",
            "job_id": job_id, "job": self.job, "task_group": self.tg_name,
            "resources": self.resources,
            "task_resources": self.task_resources, "metrics": self.metrics,
            "desired_status": ALLOC_DESIRED_STATUS_RUN,
            "desired_description": "",
            "client_status": ALLOC_CLIENT_STATUS_PENDING,
            "client_description": "",
            "create_index": self.create_index,
            "modify_index": self.modify_index,
            "_job_name": job_name,
        }

    def _materialize_span(self, template: dict, node_id: str, start: int,
                          end: int, out: List["Allocation"]) -> None:
        """Expand positions [start, end) on one node, skipping excluded
        members. The single template-and-expand implementation shared by
        the wire batch and the stored block."""
        new = object.__new__
        copy_t = template.copy
        prefix = f"{template['_job_name']}.{self.tg_name}["
        excluded = self.excluded
        for i in range(start, end):
            if i in excluded:
                continue
            d = copy_t()
            del d["_job_name"]
            d["id"] = self.alloc_id(i)
            d["name"] = f"{prefix}{self.name_idx[i]}]"
            d["node_id"] = node_id
            alloc = new(Allocation)
            alloc.__dict__ = d
            out.append(alloc)

    def materialize(self) -> List["Allocation"]:
        """Expand to Allocation objects (the FSM/state-boundary form)."""
        out: List[Allocation] = []
        template = self._template()
        pos = 0
        for nid, cnt in zip(self.node_ids, self.node_counts):
            self._materialize_span(template, nid, pos, pos + cnt, out)
            pos += cnt
        return out

    def to_wire(self) -> dict:
        from nomad_tpu.api.codec import to_dict

        d = {
            "eval_id": self.eval_id,
            "job": to_dict(self.job),
            "tg_name": self.tg_name,
            "resources": to_dict(self.resources),
            "task_resources": to_dict(self.task_resources),
            "metrics": to_dict(self.metrics),
            "node_ids": list(self.node_ids),
            "node_counts": [int(c) for c in self.node_counts],
            "name_idx": [int(i) for i in self.name_idx],
        }
        if self._ids_hex is None:
            # Still seed-form: 32 hex chars ride the wire instead of the
            # 32·n-char expanded column; the receiver derives identically.
            d["ids_seed"] = "{:032x}".format(self.ids_seed)
        else:
            d["ids_hex"] = self._ids_hex
        return d

    @staticmethod
    def from_wire(d: dict) -> "AllocBatch":
        from nomad_tpu.api.codec import from_dict

        seed = d.get("ids_seed")
        return AllocBatch(
            eval_id=d.get("eval_id", ""),
            job=from_dict(Job, d.get("job")),
            tg_name=d.get("tg_name", ""),
            resources=from_dict(Resources, d.get("resources")),
            metrics=from_dict(AllocMetric, d.get("metrics")),
            task_resources={
                k: from_dict(Resources, v)
                for k, v in (d.get("task_resources") or {}).items()
            },
            node_ids=d.get("node_ids") or [],
            node_counts=d.get("node_counts") or [],
            name_idx=d.get("name_idx") or [],
            ids_hex=d.get("ids_hex", ""),
            ids_seed=int(seed, 16) if seed is not None else None,
        )


class AllocUpdateBatch:
    """Columnar in-place update block: re-stamp existing allocations with a
    new job version without per-allocation device selects or object churn
    in the scheduler (reference semantics: util.go:316-398 inplaceUpdate).
    tasksUpdated (util.go:265-302) deliberately ignores cpu/mem changes,
    so an in-place update may grow or shrink the allocation: feasibility
    is the per-node sum of (new - old) resource deltas against current
    usage, checked vectorized by the scheduler and re-checked by plan
    evaluation.

    Locally the batch holds references to the existing allocations; on the
    wire it carries only their ids (the receiving server re-resolves them
    against its own state), plus the shared replacement fields.
    """

    __slots__ = ("eval_id", "job", "tg_name", "resources", "task_resources",
                 "metrics", "allocs", "alloc_ids",
                 "src_node_ids", "src_node_counts", "src_resources")

    def __init__(self, eval_id="", job=None, tg_name="", resources=None,
                 task_resources=None, metrics=None, allocs=None,
                 alloc_ids=None, src_node_ids=None, src_node_counts=None,
                 src_resources=None):
        self.eval_id = eval_id
        self.job = job
        self.tg_name = tg_name
        self.resources = resources
        self.task_resources = task_resources or {}
        self.metrics = metrics
        self.allocs: List[Allocation] = allocs or []
        # Wire-side form: ids only, resolved via snapshot at materialize.
        self.alloc_ids: List[str] = alloc_ids or [
            a.id for a in (allocs or [])
        ]
        # Block-columnar source form (the fully object-free path): when a
        # whole StoredAllocBlock updates in place, the batch carries the
        # block's node run-length encoding and the SHARED old Resources —
        # plan evaluation computes per-node deltas from these columns and
        # never materializes a member. alloc_ids stay populated (position
        # order) for the store's member addressing.
        self.src_node_ids: List[str] = src_node_ids or []
        self.src_node_counts: List[int] = src_node_counts or []
        self.src_resources: Optional[Resources] = src_resources

    @property
    def n(self) -> int:
        return len(self.alloc_ids)

    def node_ids(self) -> List[str]:
        return [a.node_id for a in self.allocs]

    def resource_vector(self) -> List[int]:
        if self.resources is None:
            return [0, 0, 0, 0]
        return self.resources.as_vector()

    def resolve(self, snap) -> None:
        """Rebind alloc references from ids against a state snapshot (the
        wire path). Unknown ids are dropped — they were removed while the
        plan was in flight, exactly the staleness plan evaluation guards.
        The block-columnar form needs no rebinding: its delta accounting
        reads the source columns and the store addresses members by id."""
        if self.src_node_ids:
            return
        if self.allocs and len(self.allocs) == len(self.alloc_ids):
            return
        out = []
        for aid in self.alloc_ids:
            a = snap.alloc_by_id(aid)
            if a is not None:
                out.append(a)
        self.allocs = out
        self.alloc_ids = [a.id for a in out]

    def filter_nodes(self, fit: Dict[str, bool]) -> "AllocUpdateBatch":
        if self.src_node_ids:
            if all(fit.get(nid, False) for nid in self.src_node_ids):
                return self
            # Drop unfit nodes' runs: alloc_ids are in position order, so
            # each run owns a contiguous id slice.
            keep_ids: List[str] = []
            keep_nids: List[str] = []
            keep_counts: List[int] = []
            pos = 0
            for nid, cnt in zip(self.src_node_ids, self.src_node_counts):
                if fit.get(nid, False):
                    keep_ids.extend(self.alloc_ids[pos:pos + cnt])
                    keep_nids.append(nid)
                    keep_counts.append(cnt)
                pos += cnt
            return AllocUpdateBatch(
                eval_id=self.eval_id, job=self.job, tg_name=self.tg_name,
                resources=self.resources,
                task_resources=self.task_resources,
                metrics=self.metrics, alloc_ids=keep_ids,
                src_node_ids=keep_nids, src_node_counts=keep_counts,
                src_resources=self.src_resources,
            )
        if all(fit.get(a.node_id, False) for a in self.allocs):
            return self
        kept = [a for a in self.allocs if fit.get(a.node_id, False)]
        return AllocUpdateBatch(
            eval_id=self.eval_id, job=self.job, tg_name=self.tg_name,
            resources=self.resources, task_resources=self.task_resources,
            metrics=self.metrics, allocs=kept,
        )

    def materialize(self) -> List["Allocation"]:
        out = []
        for alloc in self.allocs:
            new_alloc = alloc.copy()
            new_alloc.eval_id = self.eval_id
            new_alloc.job = self.job
            if self.resources is not None:
                new_alloc.resources = self.resources
            if self.task_resources:
                new_alloc.task_resources = self.task_resources
            new_alloc.metrics = self.metrics
            new_alloc.desired_status = ALLOC_DESIRED_STATUS_RUN
            new_alloc.desired_description = ""
            new_alloc.client_status = ALLOC_CLIENT_STATUS_PENDING
            out.append(new_alloc)
        return out

    def to_wire(self) -> dict:
        from nomad_tpu.api.codec import to_dict

        return {
            "kind": "update",
            "eval_id": self.eval_id,
            "job": to_dict(self.job),
            "tg_name": self.tg_name,
            "resources": to_dict(self.resources),
            "task_resources": to_dict(self.task_resources),
            "metrics": to_dict(self.metrics),
            "alloc_ids": list(self.alloc_ids),
            "src_node_ids": list(self.src_node_ids),
            "src_node_counts": list(self.src_node_counts),
            "src_resources": to_dict(self.src_resources),
        }

    @staticmethod
    def from_wire(d: dict) -> "AllocUpdateBatch":
        from nomad_tpu.api.codec import from_dict

        return AllocUpdateBatch(
            eval_id=d.get("eval_id", ""),
            job=from_dict(Job, d.get("job")),
            tg_name=d.get("tg_name", ""),
            resources=from_dict(Resources, d.get("resources")),
            task_resources={
                k: from_dict(Resources, v)
                for k, v in (d.get("task_resources") or {}).items()
            },
            metrics=from_dict(AllocMetric, d.get("metrics")),
            alloc_ids=d.get("alloc_ids") or [],
            src_node_ids=d.get("src_node_ids") or [],
            src_node_counts=d.get("src_node_counts") or [],
            src_resources=from_dict(Resources, d.get("src_resources")),
        )


@dataclass
class Plan:
    """Commit plan for task allocations (reference: structs.go:1462-1532).

    ``alloc_batches`` extends the reference's per-node Allocation lists with
    columnar placement blocks (AllocBatch) for large solves;
    ``update_batches`` carries columnar in-place updates."""

    eval_id: str = ""
    eval_token: str = ""
    # Trace span context of the submitting worker (nomad_tpu.trace): rides
    # the Plan.Submit envelope so the leader's applier parents its plan.*
    # spans on the worker's submit span across the RPC boundary.
    span_ctx: Dict[str, str] = field(default_factory=dict)
    priority: int = 0
    all_at_once: bool = False
    # Raft applied index of the snapshot the submitting worker evaluated
    # against — the optimistic-concurrency transaction timestamp (Omega
    # posture): the plan pipeline attributes a verification failure as a
    # CONFLICT iff capacity committed after this index overlaps the
    # plan's touched nodes. 0 = unknown (legacy/wire submitters): no
    # attribution, plain stale-data refresh semantics.
    snapshot_index: int = 0
    # Express-lane provenance (nomad_tpu/server/express.py): the id of
    # the leased capacity reservation this plan's placements were
    # promised under. Non-empty marks an express async-commit plan: the
    # pipeline skips broker bookkeeping for it (the eval never rode the
    # broker) and plan verification exempts THIS lease from the ledger
    # debits it folds in (a plan must not double-count its own
    # reservation against itself).
    express_lease: str = ""
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    failed_allocs: List[Allocation] = field(default_factory=list)
    alloc_batches: List[AllocBatch] = field(default_factory=list)
    update_batches: List[AllocUpdateBatch] = field(default_factory=list)

    def append_update(self, alloc: Allocation, status: str, desc: str) -> None:
        new_alloc = alloc.copy()
        new_alloc.desired_status = status
        new_alloc.desired_description = desc
        self.node_update.setdefault(alloc.node_id, []).append(new_alloc)

    def pop_update(self, alloc: Allocation) -> None:
        existing = self.node_update.get(alloc.node_id, [])
        if existing and existing[-1].id == alloc.id:
            existing.pop()
            if not existing:
                self.node_update.pop(alloc.node_id, None)

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_batch(self, batch: AllocBatch) -> None:
        self.alloc_batches.append(batch)

    def append_update_batch(self, batch: AllocUpdateBatch) -> None:
        self.update_batches.append(batch)

    def append_failed(self, alloc: Allocation) -> None:
        self.failed_allocs.append(alloc)

    def is_noop(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.failed_allocs
            and not self.alloc_batches
            and not self.update_batches
        )


@dataclass
class PlanResult:
    """Result of a plan submitted to the leader (reference: structs.go:1534-1575)."""

    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    failed_allocs: List[Allocation] = field(default_factory=list)
    alloc_batches: List[AllocBatch] = field(default_factory=list)
    update_batches: List[AllocUpdateBatch] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0
    # Transaction-time conflict attribution (plan_pipeline): the refresh
    # was caused by capacity another plan committed after this plan's
    # snapshot (same pipeline batch or since) — as opposed to data that
    # was already stale in the submitter's own snapshot.
    conflict: bool = False

    def is_noop(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.failed_allocs
            and not self.alloc_batches
            and not self.update_batches
        )

    def full_commit(self, plan: Plan) -> Tuple[bool, int, int]:
        expected = 0
        actual = 0
        for node_id, alloc_list in plan.node_allocation.items():
            expected += len(alloc_list)
            actual += len(self.node_allocation.get(node_id, []))
        expected += sum(b.n for b in plan.alloc_batches)
        actual += sum(b.n for b in self.alloc_batches)
        expected += sum(b.n for b in plan.update_batches)
        actual += sum(b.n for b in self.update_batches)
        return actual == expected, expected, actual


# ---------------------------------------------------------------------------
# Fit & score functions (reference: nomad/structs/funcs.go)
# ---------------------------------------------------------------------------


def remove_allocs(allocs: List[Allocation], remove: List[Allocation]) -> List[Allocation]:
    """Remove allocs with matching IDs (funcs.go:9-29). Non-destructive."""
    remove_set = {a.id for a in remove}
    return [a for a in allocs if a.id not in remove_set]


def filter_terminal_allocs(allocs: List[Allocation]) -> List[Allocation]:
    """Drop terminal-state allocations (funcs.go:31-42). Non-destructive."""
    return [a for a in allocs if not a.terminal_status()]


def allocs_fit(
    node: Node,
    allocs: List[Allocation],
    net_idx: Optional["NetworkIndex"] = None,
) -> Tuple[bool, str, Resources]:
    """Check if a set of allocations fits on a node: resource superset +
    port-collision + bandwidth overcommit (funcs.go:44-87).

    Returns (fit, exhausted_dimension, used_resources).
    """
    from nomad_tpu.network import NetworkIndex

    used = Resources()
    if node.reserved is not None:
        used.add(node.reserved)
    for alloc in allocs:
        used.add(alloc.resources)

    ok, dimension = node.resources.superset(used)
    if not ok:
        return False, dimension, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node) or net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    return True, "", used


def score_fit(node: Node, util: Resources) -> float:
    """Google "BestFit v3" bin-packing score (funcs.go:89-124).

    0 at empty node, 18 at perfect fit; higher is better. The TPU solver
    computes exactly this in nomad_tpu.ops.fit.score_fit_kernel, so the two
    paths are numerically comparable.
    """
    node_cpu = float(node.resources.cpu)
    node_mem = float(node.resources.memory_mb)
    if node.reserved is not None:
        node_cpu -= float(node.reserved.cpu)
        node_mem -= float(node.reserved.memory_mb)

    # A fully-reserved dimension has no schedulable capacity; treat as
    # -inf free so 10**x underflows to 0 and the score clamps, matching
    # Go's Inf-tolerant division + math.Pow instead of raising.
    free_pct_cpu = 1.0 - (float(util.cpu) / node_cpu) if node_cpu > 0 else float("-inf")
    free_pct_ram = (
        1.0 - (float(util.memory_mb) / node_mem) if node_mem > 0 else float("-inf")
    )
    total = 10.0**free_pct_cpu + 10.0**free_pct_ram
    score = 20.0 - total
    return min(18.0, max(0.0, score))
