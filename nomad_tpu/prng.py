"""Name-salted seeded PRNG streams: the project's ONE sanctioned source
of randomness in decision paths.

The pattern (born in faults.py, enforced tree-wide by nomadlint DET001):
every consumer owns a ``random.Random`` seeded from ``seed ^
crc32(name)``, so

- two streams with different names are independent — adding a draw at
  one site never shifts another site's decision sequence, and
- for a fixed seed the n-th draw of a named stream is the same run after
  run — the seed-replay contract SIMLOAD digests and fuzz families pin.

The process-global ``random`` module gives neither property: every
caller shares one cursor, so any new draw anywhere reorders everyone
else's decisions.
"""

from __future__ import annotations

import zlib
from random import Random


def salt(name: str) -> int:
    return zlib.crc32(name.encode())


def stream(seed: int, name: str) -> Random:
    """A seeded stream salted by ``name`` — independent per (seed, name)."""
    return Random(int(seed) ^ salt(name))


def fraction(name: str, *salts: object) -> float:
    """Stateless deterministic uniform-ish fraction in [0, 1) from a name
    plus salts — for jitter that must spread entities apart (heartbeat
    TTLs) without any stream state or draw-ordinal coupling."""
    h = zlib.crc32("|".join([name, *map(str, salts)]).encode())
    return h / 2**32
