// Native kernels for the host control plane.
//
// The reference's control plane is compiled Go; the hot host-side loops of
// this framework get the same treatment as a small C++ library loaded via
// ctypes (no pybind dependency). The first consumer is the plan verifier —
// the leader's serialization point (reference: nomad/plan_apply.go:164-277
// evaluatePlan/evaluateNodePlan + nomad/structs/funcs.go:44-87 AllocsFit):
// per-node resource accumulation over every allocation in a plan, then a
// vectorized superset check. At 100k allocations per plan this loop is the
// plans/sec ceiling of the whole cluster.
//
// All buffers are caller-owned contiguous arrays (numpy-compatible):
//   resources are int32 rows of width D (cpu, memory_mb, disk_mb, iops).

#include <cstdint>

extern "C" {

// out[idx[i], :] += vals[i, :] for i in [0, n). idx values must be < n_out.
void nt_scatter_add_i32(const int32_t* idx, const int32_t* vals,
                        int64_t n, int64_t d,
                        int32_t* out, int64_t n_out) {
    for (int64_t i = 0; i < n; ++i) {
        const int64_t row = idx[i];
        if (row < 0 || row >= n_out) continue;
        int32_t* dst = out + row * d;
        const int32_t* src = vals + i * d;
        for (int64_t j = 0; j < d; ++j) dst[j] += src[j];
    }
}

// Per-row superset check: fit[i] = all(used[i, :] <= total[i, :]).
// exhausted[i] = first failing dimension index, or -1 when fitting.
void nt_fit_check_i32(const int32_t* used, const int32_t* total,
                      int64_t n, int64_t d,
                      uint8_t* fit, int32_t* exhausted) {
    for (int64_t i = 0; i < n; ++i) {
        const int32_t* u = used + i * d;
        const int32_t* t = total + i * d;
        int32_t bad = -1;
        for (int64_t j = 0; j < d; ++j) {
            if (u[j] > t[j]) { bad = (int32_t)j; break; }
        }
        fit[i] = bad < 0 ? 1 : 0;
        exhausted[i] = bad;
    }
}

// Count occurrences of each index: out[idx[i]] += 1 (alloc-per-node counts).
void nt_bincount_i32(const int32_t* idx, int64_t n,
                     int32_t* out, int64_t n_out) {
    for (int64_t i = 0; i < n; ++i) {
        const int64_t row = idx[i];
        if (row >= 0 && row < n_out) out[row] += 1;
    }
}

}  // extern "C"
