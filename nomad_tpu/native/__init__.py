"""ctypes loader for the native control-plane kernels.

Builds ``libnomad_native.so`` on demand with the in-tree Makefile (g++) the
first time a kernel is requested, memoizes the handle, and degrades to
numpy equivalents when no toolchain or prebuilt library is available — the
numpy path is the correctness oracle in tests.

API surface (all take/return numpy arrays):
  scatter_add(idx, vals, n_out)  -> [n_out, D] int32 row sums
  fit_check(used, total)         -> (fit bool[N], exhausted_dim int32[N])
  bincount(idx, n_out)           -> int32[n_out]
  available()                    -> bool (native .so loaded)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libnomad_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO):
            try:
                subprocess.run(
                    ["make", "-C", _DIR],
                    capture_output=True, timeout=120, check=True,
                )
            except (OSError, subprocess.SubprocessError):
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.nt_scatter_add_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ]
        lib.nt_fit_check_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.nt_bincount_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def scatter_add(idx: np.ndarray, vals: np.ndarray, n_out: int) -> np.ndarray:
    """Row-sum ``vals`` grouped by ``idx`` into an [n_out, D] matrix."""
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    vals = np.ascontiguousarray(vals, dtype=np.int32)
    n, d = vals.shape
    out = np.zeros((n_out, d), dtype=np.int32)
    lib = _load()
    if lib is not None and n:
        lib.nt_scatter_add_i32(
            _i32p(idx), _i32p(vals), n, d, _i32p(out), n_out
        )
        return out
    # numpy fallback: bincount per dimension (np.add.at is far slower)
    for j in range(d):
        out[:, j] = np.bincount(idx, weights=vals[:, j], minlength=n_out)[
            :n_out
        ].astype(np.int32)
    return out


def fit_check(used: np.ndarray, total: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row superset check (funcs.go:56-71): (fit, first exhausted dim)."""
    used = np.ascontiguousarray(used, dtype=np.int32)
    total = np.ascontiguousarray(total, dtype=np.int32)
    n, d = used.shape
    lib = _load()
    if lib is not None and n:
        fit = np.empty(n, dtype=np.uint8)
        exhausted = np.empty(n, dtype=np.int32)
        lib.nt_fit_check_i32(
            _i32p(used), _i32p(total), n, d, _u8p(fit), _i32p(exhausted)
        )
        return fit.astype(bool), exhausted
    over = used > total
    fit = ~over.any(axis=1)
    exhausted = np.where(fit, -1, over.argmax(axis=1)).astype(np.int32)
    return fit, exhausted


def bincount(idx: np.ndarray, n_out: int) -> np.ndarray:
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    lib = _load()
    if lib is not None and idx.size:
        out = np.zeros(n_out, dtype=np.int32)
        lib.nt_bincount_i32(_i32p(idx), idx.size, _i32p(out), n_out)
        return out
    return np.bincount(idx, minlength=n_out)[:n_out].astype(np.int32)
