"""Log entry payload codec.

FSM payloads carry data-model objects; log entries must cross the wire.
The reference tags msgpack bodies with a 1-byte MessageType
(nomad/structs/structs.go:1586-1591); here each message type maps its
payload fields to dataclass types and round-trips through the JSON codec.
"""

from __future__ import annotations

from typing import Any, Dict

from nomad_tpu.api.codec import from_dict, to_dict
from nomad_tpu.structs import (
    AllocBatch,
    Allocation,
    AllocUpdateBatch,
    Evaluation,
    Job,
    Node,
)

# msg_type -> {payload_field: element_dataclass or None for plain values}
_SCHEMAS: Dict[str, Dict[str, Any]] = {
    "node_register": {"node": Node},
    "node_batch_register": {"nodes": [Node]},
    "node_deregister": {"node_id": None},
    "node_status_update": {"node_id": None, "status": None},
    "node_drain_update": {"node_id": None, "drain": None},
    "job_register": {"job": Job},
    "job_deregister": {"job_id": None},
    "eval_update": {"evals": [Evaluation]},
    "eval_delete": {"evals": None, "allocs": None},
    "alloc_update": {"allocs": [Allocation], "alloc_batches": "blocks",
                     "update_batches": "ubatches"},
    "alloc_client_update": {"allocs": [Allocation]},
}


def encode_payload(msg_type: str, payload: dict) -> dict:
    out = {}
    for k, v in payload.items():
        spec = _SCHEMAS.get(msg_type, {}).get(k)
        if spec in ("blocks", "ubatches"):
            # Columnar batches carry their own compact wire form — runs/id
            # lists + shared fields, never per-Allocation rows.
            out[k] = [b.to_wire() for b in v]
        else:
            out[k] = to_dict(v)
    return out


def decode_payload(msg_type: str, payload: dict) -> dict:
    schema = _SCHEMAS.get(msg_type)
    if schema is None:
        return payload
    out = {}
    for key, value in payload.items():
        spec = schema.get(key)
        if spec is None:
            out[key] = value
        elif spec == "blocks":
            # Decode to plain batches; the FSM stamps indexes and the
            # deterministic block id at upsert (state/blocks.py from_batch).
            out[key] = [AllocBatch.from_wire(v) for v in value]
        elif spec == "ubatches":
            # Wire form carries member ids; the FSM resolves them against
            # its own store at apply (deterministic across replicas).
            out[key] = [AllocUpdateBatch.from_wire(v) for v in value]
        elif isinstance(spec, list):
            out[key] = [from_dict(spec[0], v) for v in value]
        else:
            out[key] = from_dict(spec, value)
    return out
