"""Raft consensus: replicated log for multi-server state.

Reference: hashicorp/raft wired at /root/reference/nomad/server.go:397-500
with the FSM at nomad/fsm.go. This is a from-scratch Raft (leader election,
log replication, commitment, follower catch-up) speaking the framework's
RPC layer; it exposes the same ``apply``/``applied_index`` interface as the
in-process replication layer so the rest of the server is unchanged.
"""

from nomad_tpu.raft.node import NotLeaderError, RaftConfig, RaftNode

__all__ = ["RaftNode", "RaftConfig", "NotLeaderError"]
