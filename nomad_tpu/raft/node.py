"""The Raft node: election, replication, commitment.

A compact, correct Raft core (Ongaro & Ousterhout's algorithm) over the
framework RPC layer. Scope notes vs the paper:
- log compaction/InstallSnapshot: not yet (logs are bounded by GC upstream;
  snapshot shipping lands with WAN federation)
- membership change: static peer set per cluster (the reference's
  bootstrap_expect posture, nomad/serf.go:76-134)

Persistence: term/vote/log journal to ``data_dir`` when set, replayed on
restart; in-memory otherwise (the reference's DevMode InmemStore,
server.go:420-427).
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from nomad_tpu.raft.log_codec import decode_payload, encode_payload
from nomad_tpu.rpc import ConnPool, RPCError, RPCServer, RemoteError

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(Exception):
    def __init__(self, leader_addr: str = ""):
        super().__init__(
            f"not the leader (leader: {leader_addr or 'unknown'})"
        )
        self.leader_addr = leader_addr


@dataclass
class RaftConfig:
    node_id: str = ""
    # node_id -> rpc addr for every member, including self
    peers: Dict[str, str] = field(default_factory=dict)
    heartbeat_interval: float = 0.05
    election_timeout_min: float = 0.15
    election_timeout_max: float = 0.30
    data_dir: str = ""
    # Do not run elections until this many members are known — the
    # reference's bootstrap_expect posture (nomad/serf.go:76-134
    # maybeBootstrap: servers idle until the expected count joins).
    bootstrap_expect: int = 1


@dataclass
class _Entry:
    term: int
    msg_type: str
    payload: dict  # encoded (wire) form

    def to_wire(self) -> dict:
        return {"term": self.term, "type": self.msg_type, "payload": self.payload}

    @staticmethod
    def from_wire(d: dict) -> "_Entry":
        return _Entry(d["term"], d["type"], d["payload"])


class RaftNode:
    """One Raft participant. Exposes the replication-layer interface the
    server uses: apply(msg_type, payload) -> Future[index], applied_index,
    plus on_leadership_change notifications."""

    def __init__(self, config: RaftConfig, fsm, rpc: RPCServer,
                 pool: Optional[ConnPool] = None,
                 logger: Optional[logging.Logger] = None):
        self.config = config
        self.fsm = fsm
        self.rpc = rpc
        self.pool = pool or ConnPool(timeout=2.0)
        self.logger = logger or logging.getLogger(
            f"nomad_tpu.raft.{config.node_id}"
        )

        # Persistent state
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[_Entry] = []  # 1-indexed via helpers

        # Volatile
        self.commit_index = 0
        self.last_applied = 0
        self.role = FOLLOWER
        self.leader_id: Optional[str] = None
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}

        self._lock = threading.RLock()
        self._apply_futures: Dict[int, Future] = {}
        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._random_deadline()
        self._shutdown = threading.Event()
        self._replicate_now = threading.Event()
        self.on_leadership_change: Optional[Callable[[bool], None]] = None

        self._load_persistent()
        rpc.register("Raft.RequestVote", self._handle_request_vote)
        rpc.register("Raft.AppendEntries", self._handle_append_entries)

        self._threads: List[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        # Construction (e.g. jit warmup elsewhere in the server) may predate
        # start by a while; don't let the first election fire instantly.
        with self._lock:
            self._election_deadline = self._random_deadline()
        for target, name in ((self._election_loop, "raft-election"),
                             (self._leader_loop, "raft-leader")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"{name}-{self.config.node_id}")
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._shutdown.set()
        self._replicate_now.set()
        self.pool.shutdown()

    # -- public interface ---------------------------------------------------

    @property
    def applied_index(self) -> int:
        with self._lock:
            return self.last_applied

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER

    @property
    def leader_addr(self) -> str:
        with self._lock:
            if self.leader_id is None:
                return ""
            return self.config.peers.get(self.leader_id, "")

    def apply(self, msg_type: str, payload: dict) -> Future:
        """Append + replicate + commit + FSM-apply. Resolves with the log
        index; raises NotLeaderError through the future on followers."""
        future: Future = Future()
        with self._lock:
            if self.role != LEADER:
                future.set_exception(NotLeaderError(self.leader_addr))
                return future
            entry = _Entry(
                self.current_term, msg_type, encode_payload(msg_type, payload)
            )
            self.log.append(entry)
            index = len(self.log)
            self._apply_futures[index] = future
            self._persist_entry(index, entry)
            if len(self.config.peers) == 1:
                self._advance_commit_locked()
        self._replicate_now.set()
        return future

    def barrier(self, timeout: float = 5.0) -> int:
        """Commit a no-op and wait for it — the leader's read barrier."""
        future = self.apply("_noop", {})
        return future.result(timeout)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self.role,
                "term": self.current_term,
                "leader_id": self.leader_id,
                "commit_index": self.commit_index,
                "applied_index": self.last_applied,
                "last_log_index": len(self.log),
                "num_peers": len(self.config.peers) - 1,
            }

    # -- persistence --------------------------------------------------------

    def _paths(self) -> Tuple[str, str]:
        d = self.config.data_dir
        return os.path.join(d, "raft-meta.json"), os.path.join(d, "raft-log.jsonl")

    def _persist_meta(self) -> None:
        if not self.config.data_dir:
            return
        meta_path, _ = self._paths()
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.current_term, "voted_for": self.voted_for}, f)
        os.replace(tmp, meta_path)

    def _persist_entry(self, index: int, entry: _Entry) -> None:
        if not self.config.data_dir:
            return
        _, log_path = self._paths()
        with open(log_path, "a") as f:
            f.write(json.dumps({"index": index, **entry.to_wire()}) + "\n")

    def _truncate_persisted_log(self) -> None:
        if not self.config.data_dir:
            return
        _, log_path = self._paths()
        with open(log_path, "w") as f:
            for i, entry in enumerate(self.log, start=1):
                f.write(json.dumps({"index": i, **entry.to_wire()}) + "\n")

    def _load_persistent(self) -> None:
        if not self.config.data_dir:
            return
        os.makedirs(self.config.data_dir, exist_ok=True)
        meta_path, log_path = self._paths()
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            self.current_term = meta.get("term", 0)
            self.voted_for = meta.get("voted_for")
        except (OSError, ValueError):
            pass
        try:
            with open(log_path) as f:
                for line in f:
                    d = json.loads(line)
                    self.log.append(_Entry.from_wire(d))
        except (OSError, ValueError):
            pass

    # -- helpers ------------------------------------------------------------

    def _random_deadline(self) -> float:
        return time.monotonic() + random.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _last_log(self) -> Tuple[int, int]:
        if not self.log:
            return 0, 0
        return len(self.log), self.log[-1].term

    def _other_peers(self) -> Dict[str, str]:
        return {
            pid: addr
            for pid, addr in self.config.peers.items()
            if pid != self.config.node_id
        }

    def _become_follower(self, term: int, leader_id: Optional[str]) -> None:
        was_leader = self.role == LEADER
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_meta()
        self.role = FOLLOWER
        if leader_id is not None:
            self.leader_id = leader_id
        if was_leader and self.on_leadership_change:
            threading.Thread(
                target=self.on_leadership_change, args=(False,), daemon=True
            ).start()
        # Fail outstanding leader futures
        for future in self._apply_futures.values():
            if not future.done():
                future.set_exception(NotLeaderError(self.leader_addr))
        self._apply_futures.clear()

    # -- election (paper §5.2) ----------------------------------------------

    def _election_loop(self) -> None:
        while not self._shutdown.is_set():
            time.sleep(0.01)
            with self._lock:
                if self.role == LEADER:
                    continue
                if len(self.config.peers) < self.config.bootstrap_expect:
                    # Not yet bootstrapped: wait for peers to join.
                    self._election_deadline = self._random_deadline()
                    continue
                if time.monotonic() < self._election_deadline:
                    continue
                # Start an election
                self.role = CANDIDATE
                self.current_term += 1
                self.voted_for = self.config.node_id
                self._persist_meta()
                term = self.current_term
                last_idx, last_term = self._last_log()
                self._election_deadline = self._random_deadline()
            self._run_election(term, last_idx, last_term)

    def _run_election(self, term: int, last_idx: int, last_term: int) -> None:
        votes = 1
        needed = len(self.config.peers) // 2 + 1
        votes_lock = threading.Lock()
        done = threading.Event()

        def request(pid: str, addr: str) -> None:
            nonlocal votes
            try:
                resp = self.pool.call(addr, "Raft.RequestVote", {
                    "term": term,
                    "candidate_id": self.config.node_id,
                    "last_log_index": last_idx,
                    "last_log_term": last_term,
                }, timeout=1.0)
            except (RPCError, RemoteError):
                return
            with self._lock:
                if resp["term"] > self.current_term:
                    self._become_follower(resp["term"], None)
                    done.set()
                    return
            if resp.get("vote_granted"):
                with votes_lock:
                    votes += 1
                    if votes >= needed:
                        done.set()

        threads = [
            threading.Thread(target=request, args=(pid, addr), daemon=True)
            for pid, addr in self._other_peers().items()
        ]
        for t in threads:
            t.start()
        if needed == 1:
            done.set()
        done.wait(timeout=self.config.election_timeout_max)

        with self._lock:
            if self.role != CANDIDATE or self.current_term != term:
                return
            with votes_lock:
                won = votes >= needed
            if not won:
                return
            # Become leader (paper §5.3)
            self.role = LEADER
            self.leader_id = self.config.node_id
            last_idx, _ = self._last_log()
            for pid in self._other_peers():
                self.next_index[pid] = last_idx + 1
                self.match_index[pid] = 0
            self.logger.info(
                "raft: node %s won election for term %d",
                self.config.node_id, term,
            )
        # Commit a no-op immediately: a leader may only count replicas for
        # current-term entries (paper §5.4.2), so this is what commits any
        # prior-term tail — including a freshly replayed log.
        self.apply("_noop", {})
        if self.on_leadership_change:
            threading.Thread(
                target=self.on_leadership_change, args=(True,), daemon=True
            ).start()
        self._replicate_now.set()

    def _handle_request_vote(self, args: dict) -> dict:
        with self._lock:
            term = args["term"]
            if term > self.current_term:
                self._become_follower(term, None)
            granted = False
            if term == self.current_term and self.voted_for in (
                None, args["candidate_id"]
            ):
                last_idx, last_term = self._last_log()
                up_to_date = (args["last_log_term"], args["last_log_index"]) >= (
                    last_term, last_idx
                )
                if up_to_date:
                    granted = True
                    self.voted_for = args["candidate_id"]
                    self._persist_meta()
                    self._election_deadline = self._random_deadline()
            return {"term": self.current_term, "vote_granted": granted}

    # -- replication (paper §5.3) --------------------------------------------

    def _leader_loop(self) -> None:
        while not self._shutdown.is_set():
            fired = self._replicate_now.wait(self.config.heartbeat_interval)
            self._replicate_now.clear()
            with self._lock:
                if self.role != LEADER:
                    continue
            self._broadcast_append()
            del fired

    def _broadcast_append(self) -> None:
        peers = self._other_peers()
        if not peers:
            with self._lock:
                self._advance_commit_locked()
            return
        threads = [
            threading.Thread(
                target=self._replicate_to, args=(pid, addr), daemon=True
            )
            for pid, addr in peers.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1.0)

    def _replicate_to(self, pid: str, addr: str) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            next_idx = self.next_index.get(pid, 1)
            prev_idx = next_idx - 1
            prev_term = self.log[prev_idx - 1].term if prev_idx > 0 else 0
            entries = [e.to_wire() for e in self.log[next_idx - 1:]]
            commit = self.commit_index
        try:
            resp = self.pool.call(addr, "Raft.AppendEntries", {
                "term": term,
                "leader_id": self.config.node_id,
                "prev_log_index": prev_idx,
                "prev_log_term": prev_term,
                "entries": entries,
                "leader_commit": commit,
            }, timeout=1.0)
        except (RPCError, RemoteError):
            return
        with self._lock:
            if resp["term"] > self.current_term:
                self._become_follower(resp["term"], None)
                return
            if self.role != LEADER or self.current_term != term:
                return
            if resp.get("success"):
                self.match_index[pid] = prev_idx + len(entries)
                self.next_index[pid] = self.match_index[pid] + 1
                self._advance_commit_locked()
            else:
                # Back off and retry (fast backtrack via follower hint)
                hint = resp.get("conflict_index")
                self.next_index[pid] = max(
                    1, hint if hint else self.next_index.get(pid, 2) - 1
                )
                self._replicate_now.set()

    def _advance_commit_locked(self) -> None:
        """Advance commit index over majority-matched entries of the current
        term (paper §5.4.2), then apply."""
        last_idx, _ = self._last_log()
        for n in range(last_idx, self.commit_index, -1):
            if self.log[n - 1].term != self.current_term:
                break
            votes = 1 + sum(
                1 for pid in self._other_peers() if self.match_index.get(pid, 0) >= n
            )
            if votes >= len(self.config.peers) // 2 + 1:
                self.commit_index = n
                break
        self._apply_committed_locked()

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            index = self.last_applied + 1
            entry = self.log[index - 1]
            try:
                if entry.msg_type != "_noop":
                    self.fsm.apply(
                        index, entry.msg_type,
                        decode_payload(entry.msg_type, entry.payload),
                    )
                error = None
            except Exception as e:  # deterministic FSM error
                error = e
            self.last_applied = index
            future = self._apply_futures.pop(index, None)
            if future is not None and not future.done():
                if error is None:
                    future.set_result(index)
                else:
                    future.set_exception(error)

    def _handle_append_entries(self, args: dict) -> dict:
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            # Valid leader for this term
            if term > self.current_term or self.role != FOLLOWER:
                self._become_follower(term, args["leader_id"])
            self.leader_id = args["leader_id"]
            self._election_deadline = self._random_deadline()

            prev_idx = args["prev_log_index"]
            prev_term = args["prev_log_term"]
            if prev_idx > 0:
                if len(self.log) < prev_idx:
                    return {"term": self.current_term, "success": False,
                            "conflict_index": len(self.log) + 1}
                if self.log[prev_idx - 1].term != prev_term:
                    # Find the first index of the conflicting term
                    conflict_term = self.log[prev_idx - 1].term
                    first = prev_idx
                    while first > 1 and self.log[first - 2].term == conflict_term:
                        first -= 1
                    return {"term": self.current_term, "success": False,
                            "conflict_index": first}

            # Append any new entries, truncating conflicts
            changed = False
            for i, wire in enumerate(args["entries"]):
                idx = prev_idx + 1 + i
                entry = _Entry.from_wire(wire)
                if len(self.log) >= idx:
                    if self.log[idx - 1].term != entry.term:
                        del self.log[idx - 1:]
                        self.log.append(entry)
                        changed = True
                else:
                    self.log.append(entry)
                    changed = True
            if changed:
                self._truncate_persisted_log()

            if args["leader_commit"] > self.commit_index:
                self.commit_index = min(args["leader_commit"], len(self.log))
                self._apply_committed_locked()
            return {"term": self.current_term, "success": True}
