"""The Raft node: election, replication, commitment.

A compact, correct Raft core (Ongaro & Ousterhout's algorithm) over the
framework RPC layer. Scope notes vs the paper:
- log compaction via FSM snapshots (paper §7): each node snapshots its own
  FSM every ``snapshot_threshold`` applied entries and truncates the log
  prefix, keeping ``trailing_logs`` entries past the snapshot so followers
  behind by less than the tail catch up via ordinary AppendEntries (the
  reference raft library's TrailingLogs behavior); followers further back
  take the InstallSnapshot RPC. The reference keeps its log in BoltDB and
  snapshots through raft.FileSnapshotStore retaining 2
  (nomad/server.go:437,453); we retain ``snapshot_retain`` snapshot files
  the same way.
- membership change: single-server add/remove committed through the log
  as ``_config`` entries (add_peer/remove_peer, one change at a time).
  The cluster layer drives them from gossip events the way the
  reference's leader reconciles Serf members with Raft peers
  (nomad/serf.go:76-134, nomad/leader.go:263-343). A server that applies
  its own removal stops starting elections (no removed-server disruption)
  until a leader contacts it again after a re-add.

Persistence: term/vote/log journal + snapshot files to ``data_dir`` when
set; on restart the newest valid snapshot is restored into the FSM and the
log tail replayed (fsm.go:313-410 posture). In-memory otherwise (the
reference's DevMode InmemStore, server.go:420-427). Journal lines carry a
crc32 prefix (``<crc32:08x> <json body>``): a torn or bit-flipped tail is
truncated back to the last whole checksummed entry on load — counted
(``raft.journal.truncated_tail``), never a crash — and the clean prefix is
rewritten so the next append lands on a valid journal. Legacy unprefixed
lines still load (json-parse is their only check).

Log indexing is absolute: ``self.log[k]`` holds entry ``log_offset+k+1``,
where ``log_offset <= snapshot_index`` (the gap is the retained trailing
tail; they are equal right after restore or InstallSnapshot).
"""

from __future__ import annotations

import base64
import glob
import json
import logging
import os
import random
import threading
import time
import zlib
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from nomad_tpu import faults, telemetry
from nomad_tpu.raft.log_codec import decode_payload, encode_payload
from nomad_tpu.rpc import ConnPool, RPCError, RPCServer, RemoteError

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(Exception):
    def __init__(self, leader_addr: str = ""):
        super().__init__(
            f"not the leader (leader: {leader_addr or 'unknown'})"
        )
        self.leader_addr = leader_addr


@dataclass
class RaftConfig:
    node_id: str = ""
    # node_id -> rpc addr for every member, including self
    peers: Dict[str, str] = field(default_factory=dict)
    heartbeat_interval: float = 0.05
    election_timeout_min: float = 0.15
    election_timeout_max: float = 0.30
    data_dir: str = ""
    # Do not run elections until this many members are known — the
    # reference's bootstrap_expect posture (nomad/serf.go:76-134
    # maybeBootstrap: servers idle until the expected count joins).
    bootstrap_expect: int = 1
    # Take an FSM snapshot and truncate the log prefix after this many
    # applied entries past the last snapshot (raft.FileSnapshotStore
    # posture, nomad/server.go:453). Snapshot files retained: snapshot_retain.
    snapshot_threshold: int = 8192
    snapshot_retain: int = 2
    # Entries retained past the snapshot index at compaction so slightly
    # lagging followers replicate normally instead of taking a full
    # InstallSnapshot (hashicorp/raft TrailingLogs posture).
    trailing_logs: int = 1024
    # InstallSnapshot transfer chunk size (raw snapshot bytes per RPC,
    # paper §7's offset/done framing): a multi-MB FSM snapshot must not
    # ride one RPC — each chunk resets the follower's election timer and
    # interleaves with live AppendEntries instead of stalling behind one
    # giant frame.
    snapshot_chunk_bytes: int = 256 * 1024
    # Leader read lease as a fraction of election_timeout_min: a quorum
    # ack within the last (fraction × election_timeout_min) seconds lets
    # read_index() confirm leadership from the books instead of a fresh
    # quorum round — the lease rides the existing heartbeat traffic. The
    # fraction < 1 is the clock-skew guard: a peer that acked at time T
    # waits at least election_timeout_min of ITS clock past T before
    # electing anyone, so serving within a strict fraction of that window
    # tolerates bounded timer drift (clamped to 0.9 defensively).
    read_lease_fraction: float = 0.75


@dataclass
class _Entry:
    term: int
    msg_type: str
    payload: dict  # encoded (wire) form
    # Serialized (wire/journal) size; 0 when never measured. Kept on the
    # entry so the log's byte economy (raft_observe.py) is a cheap sum,
    # not a re-serialization per poll.
    wire_bytes: int = 0

    def to_wire(self) -> dict:
        return {"term": self.term, "type": self.msg_type, "payload": self.payload}

    @staticmethod
    def from_wire(d: dict) -> "_Entry":
        # wire_bytes is stamped by the caller where it is cheap to know
        # (the journal line's length at load, one dumps per ACTUALLY
        # APPENDED entry on the follower path) — measuring here would
        # also charge re-sent entries that never append.
        return _Entry(d["term"], d["type"], d["payload"])


def _atomic_write(path: str, text: str) -> None:
    """Crash-consistent file replace: write tmp, flush+fsync, rename, fsync
    the directory so the rename itself is durable."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


class RaftNode:
    """One Raft participant. Exposes the replication-layer interface the
    server uses: apply(msg_type, payload) -> Future[index], applied_index,
    plus on_leadership_change notifications."""

    def __init__(self, config: RaftConfig, fsm, rpc: RPCServer,
                 pool: Optional[ConnPool] = None,
                 logger: Optional[logging.Logger] = None):
        self.config = config
        self.fsm = fsm
        self.rpc = rpc
        self.pool = pool or ConnPool(timeout=2.0)
        self.logger = logger or logging.getLogger(
            f"nomad_tpu.raft.{config.node_id}"
        )

        # Persistent state
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[_Entry] = []  # log[k] is entry log_offset+k+1
        # Compaction state: everything at or below snapshot_index is covered
        # by the FSM snapshot; the log itself starts after log_offset, which
        # trails snapshot_index by up to trailing_logs entries so lagging
        # followers can catch up without a full snapshot transfer.
        self.snapshot_index = 0
        self.snapshot_term = 0
        self.log_offset = 0
        self.log_offset_term = 0
        self._snap_data: Optional[bytes] = None
        self._compacting = False

        # Volatile
        self.commit_index = 0
        self.last_applied = 0
        self.role = FOLLOWER
        self.leader_id: Optional[str] = None
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        # Set when this node applies its own removal from the peer set; a
        # removed server must not start elections (it would disrupt the
        # cluster with ever-higher terms). Cleared when a leader contacts
        # us again (re-added via a later _config entry).
        self.removed = False

        self._lock = threading.RLock()
        self._apply_futures: Dict[int, Future] = {}
        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._random_deadline()
        self._shutdown = threading.Event()
        self._replicate_now = threading.Event()
        self.on_leadership_change: Optional[Callable[[bool], None]] = None

        # -- observability books (plain data, mutated under _lock; read
        # by nomad_tpu/raft_observe.py — this module never imports the
        # observer, the OBS001 direction) -------------------------------
        # Per-entry write-path anchor records: index -> open record with
        # monotonic stamps (submit/persisted/first_ack/committed/
        # fsm_start/fsm_end/resolved); finalized records move to a
        # bounded ring the observatory drains by sequence number.
        self._wp_open: Dict[int, dict] = {}
        self._wp_done: "deque" = deque(maxlen=1024)
        self._wp_seq = 0
        self._peer_ack_at: Dict[str, float] = {}
        # Read-index / lease books (server/read_path.py's linearizable
        # lane): calls, how each confirmed (lease hit riding heartbeat
        # acks vs an explicit quorum round), and refusals. Last accepted
        # leader contact (follower side) feeds the stale lane's measured
        # staleness age.
        self._last_leader_contact: Optional[float] = None
        self.read_index_calls = 0
        self.read_lease_hits = 0
        self.read_quorum_confirms = 0
        self.read_index_refused = 0
        self.commit_advances = 0
        self.entries_appended = 0
        self.bytes_appended = 0
        self.entries_truncated = 0
        self.compactions = 0
        self.compaction_wall_ms = 0.0
        self.snapshot_persist_ms = 0.0
        self.snapshot_last_bytes = 0
        self.snapshot_disk_bytes = 0
        self.snapshots_installed = 0
        self.snapshots_sent = 0
        self.snapshot_chunks_sent = 0
        self.snapshot_chunks_received = 0
        # In-flight chunked InstallSnapshot reassembly (follower side):
        # buffer plus its (index, term) identity; an offset or identity
        # mismatch discards the transfer and the leader restarts it.
        self._snap_chunks: Optional[bytearray] = None
        self._snap_chunks_key: Optional[Tuple[int, int]] = None
        # Per-peer replication in-flight guard (leader side). A chunked
        # snapshot transfer outlives _broadcast_append's 1s join, and
        # without the guard every later heartbeat tick would start a
        # SECOND stream to the same peer whose offset-0 chunk resets the
        # follower's reassembly buffer — the competing transfers then
        # fail each other's offset checks forever and the follower never
        # installs. One stream per peer at a time; heartbeats to that
        # peer are unnecessary while it streams (every chunk resets the
        # follower's election timer).
        self._replicating_peers: set = set()
        # Restart-replay timeline: populated by _load_persistent (cold
        # start), advanced by the replaying applies, closed out by
        # leadership + mark_serving(). All ms fields are relative to
        # construction time.
        self._recovery_t0 = time.monotonic()
        self._replay_started: Optional[float] = None
        self.recovery: Dict[str, Any] = {
            "cold_start": False,
            "snapshot_restore_ms": 0.0,
            "snapshot_index": 0,
            "snapshot_bytes": 0,
            "log_entries_loaded": 0,
            "journal_truncated_tail": 0,
            "replay_target": 0,
            "entries_replayed": 0,
            "replayed_by_type": {},
            "replay_wall_ms": None,
            "time_to_leader_ms": None,
            "time_to_serving_ms": None,
        }

        self._load_persistent()
        rpc.register("Raft.RequestVote", self._handle_request_vote)
        rpc.register("Raft.AppendEntries", self._handle_append_entries)
        rpc.register("Raft.InstallSnapshot", self._handle_install_snapshot)
        rpc.register("Raft.ReadIndex", self._handle_read_index)

        self._threads: List[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        # Construction (e.g. jit warmup elsewhere in the server) may predate
        # start by a while; don't let the first election fire instantly.
        with self._lock:
            self._election_deadline = self._random_deadline()
        for target, name in ((self._election_loop, "raft-election"),
                             (self._leader_loop, "raft-leader")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"{name}-{self.config.node_id}")
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._shutdown.set()
        self._replicate_now.set()
        self.pool.shutdown()

    # -- public interface ---------------------------------------------------

    @property
    def applied_index(self) -> int:
        with self._lock:
            return self.last_applied

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER

    @property
    def leader_addr(self) -> str:
        with self._lock:
            if self.leader_id is None:
                return ""
            return self.config.peers.get(self.leader_id, "")

    def apply(self, msg_type: str, payload: dict) -> Future:
        """Append + replicate + commit + FSM-apply. Resolves with the log
        index; raises NotLeaderError through the future on followers."""
        future: Future = Future()
        t_submit = time.monotonic()
        with self._lock:
            if self.role != LEADER:
                future.set_exception(NotLeaderError(self.leader_addr))
                return future
            entry = _Entry(
                self.current_term, msg_type, encode_payload(msg_type, payload)
            )
            self.log.append(entry)
            index = self.log_offset + len(self.log)
            self._apply_futures[index] = future
            # Serialize ONCE: the journal line doubles as the entry's
            # byte measurement (in-memory mode pays the same dumps the
            # durable mode always paid — measurement, not decisions).
            line = json.dumps({"index": index, **entry.to_wire()})
            entry.wire_bytes = len(line)
            self._persist_entry_line(line)
            self.entries_appended += 1
            self.bytes_appended += entry.wire_bytes
            self._wp_open[index] = {
                "index": index,
                "msg_type": msg_type,
                "bytes": entry.wire_bytes,
                "anchors": {"submit": t_submit,
                            "persisted": time.monotonic()},
            }
            if len(self._wp_open) > 4096:
                # Bound the open table: a stalled commit must not grow it
                # unboundedly. Insertion order is index order, so the
                # first key IS the oldest record — O(1), no key scan
                # under the lock exactly when the leader is struggling.
                self._wp_open.pop(next(iter(self._wp_open)))
            if len(self.config.peers) == 1:
                self._advance_commit_locked()
        self._replicate_now.set()
        return future

    def barrier(self, timeout: float = 5.0) -> int:
        """Commit a no-op and wait for it — the leader's read barrier."""
        future = self.apply("_noop", {})
        return future.result(timeout)

    # -- linearizable reads without a log write (dissertation §6.4) ---------

    def lease_window_s(self) -> float:
        """How long a quorum ack keeps the leader's read lease valid.
        Strictly inside election_timeout_min (see RaftConfig
        .read_lease_fraction — the clock-skew guard)."""
        fraction = min(max(self.config.read_lease_fraction, 0.0), 0.9)
        return self.config.election_timeout_min * fraction

    def last_contact_s(self) -> Optional[float]:
        """Age of the last accepted leader contact (AppendEntries or
        InstallSnapshot chunk that passed the term check). 0.0 on the
        leader itself; None when this node has never heard from a
        leader — the stale lane's measured staleness age."""
        with self._lock:
            if self.role == LEADER:
                return 0.0
            if self._last_leader_contact is None:
                return None
            return max(time.monotonic() - self._last_leader_contact, 0.0)

    def _lease_valid_locked(self, now: float) -> bool:
        """Quorum of peers acked within the lease window (self counts).
        Acks are only ever recorded for the CURRENT term
        (_replicate_to_locked_out re-checks term before stamping), so a
        fresh quorum proves no higher term could have been committed
        when the newest qualifying ack landed."""
        window = self.lease_window_s()
        need = len(self.config.peers) // 2 + 1
        fresh = 1 + sum(
            1 for pid in self._other_peers()
            if now - self._peer_ack_at.get(pid, float("-inf")) <= window
        )
        return fresh >= need

    def read_index(self, timeout: float = 2.0) -> int:
        """Linearizable read point WITHOUT a log write (the ReadIndex
        protocol): capture the commit index, confirm leadership, return
        the index once both hold. The caller serves the read after its
        applied index reaches the returned value. Confirmation is free
        when the heartbeat-riding lease is fresh; otherwise one explicit
        quorum wait (acks newer than the request) — still no log entry.
        Raises NotLeaderError on a non-leader or a deposed leader, and
        TimeoutError when no quorum confirms in time."""
        deadline = time.monotonic() + timeout
        with self._lock:
            if self.role != LEADER:
                self.read_index_refused += 1
                raise NotLeaderError(self.leader_addr)
            self.read_index_calls += 1
            term_ok = (self.commit_index > self.log_offset
                       or self.commit_index > 0) and (
                self._term_at(self.commit_index) == self.current_term)
        if not term_ok:
            # Right after election the current-term no-op may not have
            # committed yet, so commit_index can lag commits a prior
            # leader made that we haven't learned of (§5.4.2). Commit a
            # barrier no-op — the one case the linearizable lane ever
            # touches the log, once per term.
            self.barrier(max(deadline - time.monotonic(), 0.001))
        with self._lock:
            if self.role != LEADER:
                self.read_index_refused += 1
                raise NotLeaderError(self.leader_addr)
            read_idx = self.commit_index
            if self._lease_valid_locked(time.monotonic()):
                self.read_lease_hits += 1
                return read_idx
        # Lease expired (quiet cluster, stalled heartbeats, or a
        # partitioned leader): one explicit confirmation round. A quorum
        # of acks newer than t_req proves this node's leadership — and
        # therefore read_idx's currency — at the time of the request.
        t_req = time.monotonic()
        self._replicate_now.set()
        while True:
            with self._lock:
                if self.role != LEADER:
                    self.read_index_refused += 1
                    raise NotLeaderError(self.leader_addr)
                need = len(self.config.peers) // 2 + 1
                fresh = 1 + sum(
                    1 for pid in self._other_peers()
                    if self._peer_ack_at.get(pid, 0.0) >= t_req
                )
                if fresh >= need:
                    self.read_quorum_confirms += 1
                    return read_idx
            if time.monotonic() >= deadline:
                with self._lock:
                    self.read_index_refused += 1
                raise TimeoutError(
                    f"read_index: no leadership confirmation in "
                    f"{timeout:.3f}s"
                )
            time.sleep(0.002)
            self._replicate_now.set()

    def _handle_read_index(self, args: dict) -> dict:
        """Raft.ReadIndex RPC: a follower's linearizable lane asks the
        leader for a confirmed read index (no log write). Raises through
        the RPC envelope on a non-leader; the forwarding layer retries
        against the new leader."""
        timeout = min(max(float(args.get("timeout") or 1.0), 0.001), 5.0)
        index = self.read_index(timeout=timeout)
        with self._lock:
            return {"index": index, "term": self.current_term}

    # -- membership change (single-server, committed through the log) -------

    def seed_peers(self, peers: Dict[str, str]) -> bool:
        """Pre-bootstrap membership seeding (the reference's maybeBootstrap,
        serf.go:76-134): while nothing has ever committed, gossip-discovered
        members go straight into the peer table so the first election can
        reach bootstrap_expect. Once the cluster has state, membership
        moves only via committed _config entries. Returns True if seeded."""
        with self._lock:
            if self.commit_index > 0:
                return False
            self.config.peers.update(peers)
            return True

    def add_peer(self, pid: str, addr: str) -> Future:
        """Leader-only: commit the addition of a peer. Takes effect (on
        every node, incl. replication targets and quorum math) when the
        entry applies."""
        return self.apply("_config", {"op": "add", "id": pid, "addr": addr})

    def remove_peer(self, pid: str) -> Future:
        """Leader-only: commit the removal of a peer (a leader never
        removes itself — transfer leadership by crashing instead)."""
        if pid == self.config.node_id:
            future: Future = Future()
            future.set_exception(
                ValueError("a leader cannot remove itself")
            )
            return future
        return self.apply("_config", {"op": "remove", "id": pid})

    def _apply_config_locked(self, payload: dict) -> None:
        op, pid = payload.get("op"), payload.get("id")
        if op == "add":
            addr = payload.get("addr", "")
            if self.config.peers.get(pid) != addr:
                self.config.peers[pid] = addr
                self.logger.info(
                    "raft: node %s peer set += %s (%d members)",
                    self.config.node_id, pid, len(self.config.peers),
                )
        elif op == "remove":
            if pid == self.config.node_id:
                self.removed = True
                self.role = FOLLOWER
                self.logger.info(
                    "raft: node %s removed from the cluster; standing down",
                    self.config.node_id,
                )
            if self.config.peers.pop(pid, None) is not None:
                self.logger.info(
                    "raft: node %s peer set -= %s (%d members)",
                    self.config.node_id, pid, len(self.config.peers),
                )
            self.next_index.pop(pid, None)
            self.match_index.pop(pid, None)
        self._persist_meta()  # the peer table is durable state

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self.role,
                "term": self.current_term,
                "leader_id": self.leader_id,
                "commit_index": self.commit_index,
                "applied_index": self.last_applied,
                "last_log_index": self.log_offset + len(self.log),
                "snapshot_index": self.snapshot_index,
                "num_peers": len(self.config.peers) - 1,
            }

    # -- observability surface (read by nomad_tpu/raft_observe.py) -----------

    def mark_serving(self) -> None:
        """Close the recovery timeline: leadership is established and the
        broker restored — the node serves again. Called by the cluster
        layer's establish-leadership path; idempotent (first call
        wins)."""
        with self._lock:
            if self.recovery["time_to_serving_ms"] is None:
                self.recovery["time_to_serving_ms"] = round(
                    (time.monotonic() - self._recovery_t0) * 1000.0, 3
                )

    def write_path_records(self, since: int):
        """(sequence, finalized write-path records newer than ``since``)
        — the raft observatory's drain. Records fall off the bounded
        ring; the sequence gap tells the consumer exactly how many it
        missed (counted there, never silent)."""
        with self._lock:
            seq = self._wp_seq
            n = seq - int(since)
            if n <= 0:
                return seq, []
            n = min(n, len(self._wp_done))
            return seq, list(self._wp_done)[-n:]

    def observe_stats(self) -> Dict[str, Any]:
        """One locked read of the replication/log/snapshot books (plain
        data for nomad_tpu/raft_observe.py — per-follower lag, log byte
        economy, compaction counters). Disk sizes are point-in-time
        stamps taken at write, so no I/O happens under the lock."""
        with self._lock:
            now = time.monotonic()
            last_idx = self.log_offset + len(self.log)
            peers = {}
            for pid in sorted(self._other_peers()):
                match = self.match_index.get(pid, 0)
                ack = self._peer_ack_at.get(pid)
                peers[pid] = {
                    "match_index": match,
                    "next_index": self.next_index.get(pid, 0),
                    "lag_entries": max(last_idx - match, 0),
                    "last_ack_age_s": (
                        round(now - ack, 3) if ack is not None else None
                    ),
                }
            return {
                "node_id": self.config.node_id,
                "state": self.role,
                "term": self.current_term,
                "leader_id": self.leader_id,
                "commit_index": self.commit_index,
                "applied_index": self.last_applied,
                "last_log_index": last_idx,
                "commit_advances": self.commit_advances,
                "inflight_writes": len(self._wp_open),
                "peers": peers,
                "log": {
                    "entries": len(self.log),
                    "bytes": sum(e.wire_bytes for e in self.log),
                    "offset": self.log_offset,
                    "appended_entries": self.entries_appended,
                    "appended_bytes": self.bytes_appended,
                    "truncated_entries": self.entries_truncated,
                    # The trailing_logs economy: entries kept IN the log
                    # although the snapshot already covers them, so
                    # slightly-lagging followers replicate normally.
                    "retained_below_snapshot": max(
                        self.snapshot_index - self.log_offset, 0
                    ),
                },
                "read_index": {
                    "calls": self.read_index_calls,
                    "lease_hits": self.read_lease_hits,
                    "quorum_confirms": self.read_quorum_confirms,
                    "refused": self.read_index_refused,
                    "lease_window_s": round(self.lease_window_s(), 4),
                    "last_contact_s": (
                        None if self._last_leader_contact is None
                        or self.role == LEADER
                        else round(now - self._last_leader_contact, 4)
                    ),
                },
                "snapshot": {
                    "index": self.snapshot_index,
                    "term": self.snapshot_term,
                    "threshold": self.config.snapshot_threshold,
                    "trailing_logs": self.config.trailing_logs,
                    "compactions": self.compactions,
                    "compaction_wall_ms": round(self.compaction_wall_ms, 3),
                    "persist_wall_ms": round(self.snapshot_persist_ms, 3),
                    "last_bytes": self.snapshot_last_bytes,
                    "disk_bytes": self.snapshot_disk_bytes,
                    "installs_received": self.snapshots_installed,
                    "installs_sent": self.snapshots_sent,
                    "chunks_sent": self.snapshot_chunks_sent,
                    "chunks_received": self.snapshot_chunks_received,
                },
            }

    # -- persistence --------------------------------------------------------

    def _paths(self) -> Tuple[str, str]:
        d = self.config.data_dir
        return os.path.join(d, "raft-meta.json"), os.path.join(d, "raft-log.jsonl")

    def _persist_meta(self) -> None:
        if not self.config.data_dir:
            return
        meta_path, _ = self._paths()
        # The peer table rides the meta file: _config entries are compacted
        # out of the log, and the snapshot holds only FSM state, so without
        # this a restart from snapshot would come up with peers == {self}.
        _atomic_write(meta_path, json.dumps(
            {"term": self.current_term, "voted_for": self.voted_for,
             "peers": dict(self.config.peers)}
        ))

    @staticmethod
    def _journal_frame(body: str) -> str:
        """Checksummed journal line: crc32 of the JSON body, fixed-width
        hex, one space, body. The crc covers torn writes AND bit flips;
        the body alone stays the wire-byte measure so leader/follower/
        reloaded byte books agree."""
        return f"{zlib.crc32(body.encode()):08x} {body}"

    @staticmethod
    def _journal_parse(raw: str) -> Optional[str]:
        """Validate one journal line; returns the JSON body, or None when
        the line is torn/corrupt. Legacy lines (pre-checksum journals
        start straight at ``{``) pass through — json-parse downstream is
        their only integrity check."""
        if raw.startswith("{"):
            return raw
        if len(raw) < 10 or raw[8] != " ":
            return None
        prefix, body = raw[:8], raw[9:]
        try:
            want = int(prefix, 16)
        except ValueError:
            return None
        if zlib.crc32(body.encode()) != want:
            return None
        return body

    def _persist_entry_line(self, body: str) -> None:
        """Append one pre-serialized journal body (apply() builds it once
        so the byte measurement and the journal share one dumps); the
        crc32 frame is added here."""
        if not self.config.data_dir:
            return
        _, log_path = self._paths()
        with open(log_path, "a") as f:
            f.write(self._journal_frame(body) + "\n")

    def _truncate_persisted_log(self) -> None:
        if not self.config.data_dir:
            return
        _, log_path = self._paths()
        _atomic_write(log_path, "".join(
            self._journal_frame(
                json.dumps({"index": i, **entry.to_wire()})
            ) + "\n"
            for i, entry in enumerate(self.log, start=self.log_offset + 1)
        ))

    def _snap_path(self, index: int) -> str:
        return os.path.join(self.config.data_dir, f"raft-snap-{index:016d}.json")

    def _write_snapshot_file(self, index: int, term: int, data: bytes) -> None:
        """Write a snapshot to disk, retaining the newest
        ``snapshot_retain`` files (raft.FileSnapshotStore, server.go:453)."""
        self.snapshot_last_bytes = len(data)
        if not self.config.data_dir:
            return
        t0 = time.monotonic()
        path = self._snap_path(index)
        _atomic_write(path, json.dumps({
            "index": index,
            "term": term,
            "data": base64.b64encode(data).decode("ascii"),
        }))
        self._prune_snapshots()
        self.snapshot_persist_ms += (time.monotonic() - t0) * 1000.0
        try:
            self.snapshot_disk_bytes = os.path.getsize(path)
        except OSError:
            pass

    def _prune_snapshots(self) -> None:
        snaps = sorted(glob.glob(
            os.path.join(self.config.data_dir, "raft-snap-*.json")
        ))
        retain = max(1, self.config.snapshot_retain)
        for old in snaps[:-retain]:
            try:
                os.remove(old)
            except OSError:
                pass

    def _load_persistent(self) -> None:
        if not self.config.data_dir:
            return
        os.makedirs(self.config.data_dir, exist_ok=True)
        meta_path, log_path = self._paths()
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            self.current_term = meta.get("term", 0)
            self.voted_for = meta.get("voted_for")
            persisted_peers = meta.get("peers") or {}
            persisted_peers.pop(self.config.node_id, None)
            self.config.peers.update(persisted_peers)
        except (OSError, ValueError):
            pass
        # Newest valid snapshot first (fall back through retained copies),
        # restored into the FSM before the log tail replays over it. Restore
        # failures of any kind (corrupt file, truncated pickle, …) fall
        # through to the older retained copy — that is what retain=2 is for.
        snaps = sorted(glob.glob(
            os.path.join(self.config.data_dir, "raft-snap-*.json")
        ), reverse=True)
        for path in snaps:
            try:
                with open(path) as f:
                    snap = json.load(f)
                data = base64.b64decode(snap["data"])
                t_restore0 = time.monotonic()
                self.fsm.restore_bytes(data)
                self.recovery["snapshot_restore_ms"] = round(
                    (time.monotonic() - t_restore0) * 1000.0, 3
                )
                self.recovery["snapshot_index"] = snap["index"]
                self.recovery["snapshot_bytes"] = len(data)
            except Exception:
                # Restore failures of ANY kind fall through to the older
                # retained copy (that is what retain=2 is for) — but a
                # skipped snapshot is forensic gold after a bad restart,
                # so it counts, not just logs (nomadlint EXC001).
                telemetry.incr_counter(("raft", "snapshot_restore_failed"))
                self.logger.warning("raft: skipping unreadable snapshot %s", path)
                continue
            self.snapshot_index = snap["index"]
            self.snapshot_term = snap["term"]
            self._snap_data = data
            self.commit_index = self.last_applied = self.snapshot_index
            # Any trailing tail persisted before the restart is discarded by
            # the contiguity rule below; the log restarts at the snapshot.
            self.log_offset = self.snapshot_index
            self.log_offset_term = self.snapshot_term
            break
        # Replay the log tail only if it joins the snapshot contiguously:
        # log[k] must hold entry log_offset+k+1. A gap (e.g. the newest
        # snapshot was unreadable and we fell back to an older one whose
        # successor entries were already compacted away) would mis-index
        # every entry, so the tail is discarded and re-fetched from the
        # leader instead.
        torn = False
        try:
            with open(log_path) as f:
                for line in f:
                    raw = line.rstrip("\n")
                    body = self._journal_parse(raw) if raw else None
                    if body is None:
                        # Torn/corrupt line: a crash mid-append (or a bit
                        # flip) must not brick the node. Everything before
                        # this line replayed cleanly; everything from it
                        # on is untrustworthy and is truncated below.
                        torn = True
                        break
                    try:
                        d = json.loads(body)
                    except ValueError:
                        torn = True
                        break
                    if d["index"] <= self.log_offset:
                        continue
                    if d["index"] != self.log_offset + len(self.log) + 1:
                        self.logger.warning(
                            "raft: discarding log from non-contiguous "
                            "index %d (expected %d)",
                            d["index"], self.log_offset + len(self.log) + 1,
                        )
                        break
                    entry = _Entry.from_wire(d)
                    # The journal body's own length IS the byte measure
                    # (the convention apply() stamps) — no re-dump on
                    # the cold-start path the recovery timeline clocks.
                    entry.wire_bytes = len(body)
                    self.log.append(entry)
        except OSError:
            pass
        if torn:
            telemetry.incr_counter(("raft", "journal", "truncated_tail"))
            self.recovery["journal_truncated_tail"] += 1
            self.logger.warning(
                "raft: journal tail torn/corrupt; truncated to last whole "
                "checksummed entry (index %d)",
                self.log_offset + len(self.log),
            )
            # Rewrite the clean prefix so the NEXT append lands on a valid
            # journal instead of extending a corrupt tail.
            self._truncate_persisted_log()
        # Close out the recovery bookkeeping for this load: the tail past
        # last_applied is what leadership (or the next leader's commit
        # advance) will REPLAY into the FSM; an empty tail means replay
        # is already done (wall 0), and a warm start (no durable state)
        # leaves the whole record inert.
        self.recovery["log_entries_loaded"] = len(self.log)
        self.recovery["replay_target"] = self.log_offset + len(self.log)
        self.recovery["cold_start"] = bool(
            self.recovery["snapshot_index"] or self.log
        )
        if self.recovery["replay_target"] <= self.last_applied:
            self.recovery["replay_wall_ms"] = 0.0

    # -- helpers ------------------------------------------------------------

    def _random_deadline(self) -> float:
        # nomadlint: allow(DET001) -- election-timeout jitter is liveness
        # randomization (split-vote avoidance, raft §5.2), not a placement
        # decision: replay determinism never depends on which replica wins
        # an election, and seeding it per-node would correlate restarts.
        return time.monotonic() + random.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _last_log(self) -> Tuple[int, int]:
        if not self.log:
            return self.log_offset, self.log_offset_term
        return self.log_offset + len(self.log), self.log[-1].term

    def _entry_at(self, index: int) -> _Entry:
        return self.log[index - self.log_offset - 1]

    def _term_at(self, index: int) -> int:
        if index == self.log_offset:
            return self.log_offset_term
        return self._entry_at(index).term

    def _other_peers(self) -> Dict[str, str]:
        return {
            pid: addr
            for pid, addr in self.config.peers.items()
            if pid != self.config.node_id
        }

    def _become_follower(self, term: int, leader_id: Optional[str]) -> None:
        was_leader = self.role == LEADER
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_meta()
        self.role = FOLLOWER
        if leader_id is not None:
            self.leader_id = leader_id
        if was_leader and self.on_leadership_change:
            threading.Thread(
                target=self.on_leadership_change, args=(False,), daemon=True
            ).start()
        # Fail outstanding leader futures
        for future in self._apply_futures.values():
            if not future.done():
                future.set_exception(NotLeaderError(self.leader_addr))
        self._apply_futures.clear()
        # Open write-path records belong to the deposed leadership: the
        # entries may still commit under the new leader, but this node
        # can no longer attribute their submit→applied path honestly.
        self._wp_open.clear()

    # -- election (paper §5.2) ----------------------------------------------

    def _election_loop(self) -> None:
        while not self._shutdown.is_set():
            time.sleep(0.01)
            with self._lock:
                if self.role == LEADER:
                    continue
                if self.removed:
                    # Not a member: don't disrupt the cluster with elections.
                    self._election_deadline = self._random_deadline()
                    continue
                if len(self.config.peers) < self.config.bootstrap_expect:
                    # Not yet bootstrapped: wait for peers to join.
                    self._election_deadline = self._random_deadline()
                    continue
                if time.monotonic() < self._election_deadline:
                    continue
                # Start an election
                self.role = CANDIDATE
                self.current_term += 1
                self.voted_for = self.config.node_id
                self._persist_meta()
                term = self.current_term
                last_idx, last_term = self._last_log()
                self._election_deadline = self._random_deadline()
            self._run_election(term, last_idx, last_term)

    def _run_election(self, term: int, last_idx: int, last_term: int) -> None:
        votes = 1
        needed = len(self.config.peers) // 2 + 1
        votes_lock = threading.Lock()
        done = threading.Event()

        def request(pid: str, addr: str) -> None:
            nonlocal votes
            # Injected vote loss: the request never leaves this candidate
            # (one edge, one direction — target "<self>-><peer>").
            fault = faults.fire(
                "raft.vote", target=f"{self.config.node_id}->{pid}"
            )
            if fault is not None and fault.mode in ("drop", "partition"):
                return
            try:
                resp = self.pool.call(addr, "Raft.RequestVote", {
                    "term": term,
                    "candidate_id": self.config.node_id,
                    "last_log_index": last_idx,
                    "last_log_term": last_term,
                }, timeout=1.0)
            except (RPCError, RemoteError):
                return
            with self._lock:
                if resp["term"] > self.current_term:
                    self._become_follower(resp["term"], None)
                    done.set()
                    return
            if resp.get("vote_granted"):
                with votes_lock:
                    votes += 1
                    if votes >= needed:
                        done.set()

        threads = [
            threading.Thread(target=request, args=(pid, addr), daemon=True)
            for pid, addr in self._other_peers().items()
        ]
        for t in threads:
            t.start()
        if needed == 1:
            done.set()
        done.wait(timeout=self.config.election_timeout_max)

        with self._lock:
            if self.role != CANDIDATE or self.current_term != term:
                return
            with votes_lock:
                won = votes >= needed
            if not won:
                return
            # Become leader (paper §5.3)
            self.role = LEADER
            self.leader_id = self.config.node_id
            last_idx, _ = self._last_log()
            for pid in self._other_peers():
                self.next_index[pid] = last_idx + 1
                self.match_index[pid] = 0
            self.logger.info(
                "raft: node %s won election for term %d",
                self.config.node_id, term,
            )
            if self.recovery["time_to_leader_ms"] is None:
                self.recovery["time_to_leader_ms"] = round(
                    (time.monotonic() - self._recovery_t0) * 1000.0, 3
                )
        # Commit a no-op immediately: a leader may only count replicas for
        # current-term entries (paper §5.4.2), so this is what commits any
        # prior-term tail — including a freshly replayed log.
        self.apply("_noop", {})
        if self.on_leadership_change:
            threading.Thread(
                target=self.on_leadership_change, args=(True,), daemon=True
            ).start()
        self._replicate_now.set()

    def _handle_request_vote(self, args: dict) -> dict:
        with self._lock:
            # Votes from non-members are ignored WITHOUT adopting their
            # term: a server removed while partitioned (it never saw its
            # removal commit) would otherwise depose live leaders with
            # ever-higher terms forever (hashicorp/raft guards the same
            # way; the cluster layer re-joins such a server via gossip).
            if args["candidate_id"] not in self.config.peers:
                return {"term": self.current_term, "vote_granted": False}
            term = args["term"]
            if term > self.current_term:
                self._become_follower(term, None)
            granted = False
            if term == self.current_term and self.voted_for in (
                None, args["candidate_id"]
            ):
                last_idx, last_term = self._last_log()
                up_to_date = (args["last_log_term"], args["last_log_index"]) >= (
                    last_term, last_idx
                )
                if up_to_date:
                    granted = True
                    self.voted_for = args["candidate_id"]
                    self._persist_meta()
                    self._election_deadline = self._random_deadline()
            return {"term": self.current_term, "vote_granted": granted}

    # -- replication (paper §5.3) --------------------------------------------

    def _leader_loop(self) -> None:
        while not self._shutdown.is_set():
            fired = self._replicate_now.wait(self.config.heartbeat_interval)
            self._replicate_now.clear()
            with self._lock:
                if self.role != LEADER:
                    continue
            self._broadcast_append()
            del fired

    def _broadcast_append(self) -> None:
        peers = self._other_peers()
        if not peers:
            with self._lock:
                self._advance_commit_locked()
            return
        threads = [
            threading.Thread(
                target=self._replicate_to, args=(pid, addr), daemon=True
            )
            for pid, addr in peers.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1.0)

    def _replicate_to(self, pid: str, addr: str) -> None:
        with self._lock:
            if self.role != LEADER or pid in self._replicating_peers:
                return
            self._replicating_peers.add(pid)
        try:
            self._replicate_to_locked_out(pid, addr)
        finally:
            with self._lock:
                self._replicating_peers.discard(pid)

    def _replicate_to_locked_out(self, pid: str, addr: str) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            next_idx = self.next_index.get(pid, 1)
            if next_idx <= self.log_offset:
                # The entries this follower needs were compacted away (it is
                # behind even the trailing tail): ship the snapshot instead
                # (paper §7 InstallSnapshot).
                snap = (self.snapshot_index, self.snapshot_term, self._snap_data)
            else:
                snap = None
                prev_idx = next_idx - 1
                prev_term = self._term_at(prev_idx) if prev_idx > 0 else 0
                entries = [
                    e.to_wire()
                    for e in self.log[next_idx - self.log_offset - 1:]
                ]
            commit = self.commit_index
        # Injected append loss (covers the InstallSnapshot arm too: both
        # are the leader's replication stream to this peer). A drop here is
        # ordinary message loss — the next heartbeat retries, exactly the
        # redundancy Raft's correctness argument assumes.
        fault = faults.fire(
            "raft.append", target=f"{self.config.node_id}->{pid}"
        )
        if fault is not None and fault.mode in ("drop", "partition"):
            return
        if snap is not None:
            self._send_snapshot(pid, addr, term, *snap)
            return
        try:
            resp = self.pool.call(addr, "Raft.AppendEntries", {
                "term": term,
                "leader_id": self.config.node_id,
                "prev_log_index": prev_idx,
                "prev_log_term": prev_term,
                "entries": entries,
                "leader_commit": commit,
            }, timeout=1.0)
        except (RPCError, RemoteError):
            return
        with self._lock:
            if resp["term"] > self.current_term:
                self._become_follower(resp["term"], None)
                return
            if self.role != LEADER or self.current_term != term:
                return
            if resp.get("success"):
                old_match = self.match_index.get(pid, 0)
                self.match_index[pid] = prev_idx + len(entries)
                self.next_index[pid] = self.match_index[pid] + 1
                now = time.monotonic()
                self._peer_ack_at[pid] = now
                # First-ack anchors for the write-path partition: the
                # freshly covered indexes' replicate stage ends here.
                for i in range(old_match + 1, self.match_index[pid] + 1):
                    rec = self._wp_open.get(i)
                    if rec is not None:
                        rec["anchors"].setdefault("first_ack", now)
                self._advance_commit_locked()
            else:
                # Back off and retry (fast backtrack via follower hint)
                hint = resp.get("conflict_index")
                self.next_index[pid] = max(
                    1, hint if hint else self.next_index.get(pid, 2) - 1
                )
                self._replicate_now.set()

    def _send_snapshot(self, pid: str, addr: str, term: int,
                       snap_index: int, snap_term: int,
                       data: Optional[bytes]) -> None:
        """Stream one snapshot in ``snapshot_chunk_bytes`` pieces (paper
        §7's offset/done framing). Each chunk is a bounded RPC, so a
        multi-MB snapshot interleaves with live traffic and keeps
        resetting the follower's election timer; leadership is re-checked
        between chunks so a deposed leader stops streaming immediately.
        match/next advance only after the final chunk's ack — a transfer
        aborted midway retries whole on the next replication pass."""
        if data is None:
            return
        chunk = max(1, int(self.config.snapshot_chunk_bytes))
        total = len(data)
        offset = 0
        while True:
            with self._lock:
                if self.role != LEADER or self.current_term != term:
                    return
            piece = data[offset:offset + chunk]
            done = offset + len(piece) >= total
            try:
                resp = self.pool.call(addr, "Raft.InstallSnapshot", {
                    "term": term,
                    "leader_id": self.config.node_id,
                    "last_included_index": snap_index,
                    "last_included_term": snap_term,
                    "offset": offset,
                    "done": done,
                    "data": base64.b64encode(piece).decode("ascii"),
                }, timeout=5.0)
            except (RPCError, RemoteError):
                return
            with self._lock:
                if resp["term"] > self.current_term:
                    self._become_follower(resp["term"], None)
                    return
                if self.role != LEADER or self.current_term != term:
                    return
                self.snapshot_chunks_sent += 1
            if not resp.get("success", True):
                # The follower discarded the reassembly (identity/offset
                # mismatch — e.g. it restarted mid-transfer): abort; the
                # next pass restarts from offset 0.
                return
            if done:
                break
            offset += len(piece)
        with self._lock:
            if self.role != LEADER or self.current_term != term:
                return
            self.match_index[pid] = max(self.match_index.get(pid, 0), snap_index)
            self.next_index[pid] = snap_index + 1
            self._peer_ack_at[pid] = time.monotonic()
            self.snapshots_sent += 1
        self._replicate_now.set()

    def _handle_install_snapshot(self, args: dict) -> dict:
        # Decode outside the lock: the payload can be MBs and is a pure
        # function of the request. (FSM restore + file writes stay under the
        # lock: they must be ordered against concurrent AppendEntries.)
        decoded = base64.b64decode(args["data"])
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.role != FOLLOWER:
                self._become_follower(term, args["leader_id"])
            self.leader_id = args["leader_id"]
            self._election_deadline = self._random_deadline()
            self._last_leader_contact = time.monotonic()

            snap_index = args["last_included_index"]
            snap_term = args["last_included_term"]
            # Chunk reassembly (legacy single-shot senders omit offset/
            # done: one whole-payload chunk). Identity- and offset-checked:
            # any mismatch — a competing transfer, a dropped chunk, our own
            # restart mid-transfer — discards the buffer and fails the RPC
            # so the leader restarts from offset 0. Live AppendEntries
            # interleave freely between chunks; the suffix-retention rule
            # below reconciles whatever appended during the transfer.
            offset = int(args.get("offset", 0))
            done = bool(args.get("done", True))
            key = (snap_index, snap_term)
            if offset == 0:
                self._snap_chunks = bytearray()
                self._snap_chunks_key = key
            elif (self._snap_chunks is None
                    or self._snap_chunks_key != key
                    or len(self._snap_chunks) != offset):
                self._snap_chunks = None
                self._snap_chunks_key = None
                return {"term": self.current_term, "success": False}
            self._snap_chunks.extend(decoded)
            self.snapshot_chunks_received += 1
            if not done:
                return {"term": self.current_term, "success": True}
            data = bytes(self._snap_chunks)
            self._snap_chunks = None
            self._snap_chunks_key = None
            if snap_index <= self.commit_index:
                # Stale snapshot: we already have (and applied) everything
                # it contains.
                return {"term": self.current_term, "success": True}
            self.fsm.restore_bytes(data)
            # Paper §7: retain any log suffix that extends past the snapshot
            # and agrees with it; otherwise discard the whole log.
            last_idx, _ = self._last_log()
            if (last_idx > snap_index
                    and snap_index >= self.log_offset
                    and self._term_at(snap_index) == snap_term):
                del self.log[: snap_index - self.log_offset]
            else:
                self.log = []
            self.snapshot_index = snap_index
            self.snapshot_term = snap_term
            self.log_offset = snap_index
            self.log_offset_term = snap_term
            self._snap_data = data
            self.commit_index = max(self.commit_index, snap_index)
            self.last_applied = max(self.last_applied, snap_index)
            self._write_snapshot_file(snap_index, snap_term, data)
            self._truncate_persisted_log()
            self.snapshots_installed += 1
            self.logger.info(
                "raft: node %s installed snapshot at index %d",
                self.config.node_id, snap_index,
            )
            return {"term": self.current_term, "success": True}

    def _advance_commit_locked(self) -> None:
        """Advance commit index over majority-matched entries of the current
        term (paper §5.4.2), then apply."""
        last_idx, _ = self._last_log()
        old_commit = self.commit_index
        for n in range(last_idx, self.commit_index, -1):
            if self._term_at(n) != self.current_term:
                break
            votes = 1 + sum(
                1 for pid in self._other_peers() if self.match_index.get(pid, 0) >= n
            )
            if votes >= len(self.config.peers) // 2 + 1:
                self.commit_index = n
                break
        if self.commit_index > old_commit:
            self.commit_advances += 1
            now = time.monotonic()
            for i in range(old_commit + 1, self.commit_index + 1):
                rec = self._wp_open.get(i)
                if rec is not None:
                    rec["anchors"].setdefault("committed", now)
        self._apply_committed_locked()

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            index = self.last_applied + 1
            entry = self._entry_at(index)
            rec = self._wp_open.get(index)
            if rec is not None:
                rec["anchors"]["fsm_start"] = time.monotonic()
            replaying = (index <= self.recovery["replay_target"]
                         and self.recovery["replay_wall_ms"] is None)
            if replaying and self._replay_started is None:
                self._replay_started = time.monotonic()
            try:
                if entry.msg_type == "_config":
                    self._apply_config_locked(entry.payload)
                elif entry.msg_type != "_noop":
                    self.fsm.apply(
                        index, entry.msg_type,
                        decode_payload(entry.msg_type, entry.payload),
                    )
                error = None
            except Exception as e:  # deterministic FSM error
                # Counted because the error is SWALLOWED for entries
                # nobody holds a future for (replicated followers): a
                # silently diverging FSM would otherwise leave zero
                # evidence (nomadlint EXC001).
                telemetry.incr_counter(("raft", "fsm_apply_error"))
                error = e
            self.last_applied = index
            if replaying:
                # Restart-replay accounting: entries re-applied from the
                # persisted tail, per msg_type, closed out when the tail
                # is exhausted (the recovery report's replay rate).
                self.recovery["entries_replayed"] += 1
                by_type = self.recovery["replayed_by_type"]
                by_type[entry.msg_type] = by_type.get(entry.msg_type, 0) + 1
                if index >= self.recovery["replay_target"]:
                    self.recovery["replay_wall_ms"] = round(
                        (time.monotonic() - self._replay_started) * 1000.0,
                        3,
                    )
            future = self._apply_futures.pop(index, None)
            if rec is not None:
                rec["anchors"]["fsm_end"] = time.monotonic()
            if future is not None and not future.done():
                if error is None:
                    future.set_result(index)
                else:
                    future.set_exception(error)
            if rec is not None:
                rec["anchors"]["resolved"] = time.monotonic()
                self._wp_open.pop(index, None)
                self._wp_done.append(rec)
                self._wp_seq += 1
        if (self.last_applied - self.snapshot_index
                >= self.config.snapshot_threshold and not self._compacting):
            self._compacting = True
            threading.Thread(
                target=self._compact_async, daemon=True,
                name=f"raft-compact-{self.config.node_id}",
            ).start()

    def _compact_async(self) -> None:
        """Snapshot the FSM and drop the log prefix (paper §7). The
        expensive parts — FSM serialization and the snapshot file write —
        run off the node lock so replication and elections aren't stalled
        (the reference snapshots in a background goroutine the same way).
        Only a cheap copy-on-write handle is taken under the lock."""
        t_compact0 = time.monotonic()
        try:
            with self._lock:
                idx = self.last_applied
                snap_term = self._term_at(idx)
                cow = getattr(self.fsm, "snapshot_cow", None)
                serialize = getattr(self.fsm, "serialize_cow", None)
                if cow is not None and serialize is not None:
                    handle = cow()
                    data = None
                else:
                    # FSMs without a COW snapshot serialize under the lock,
                    # stalling heartbeats/elections for the duration —
                    # acceptable only for small test FSMs. Production FSMs
                    # must provide snapshot_cow()/serialize_cow() (the
                    # server FSM does: server/fsm.py:104-117) so only a
                    # cheap handle is taken here.
                    data = self.fsm.snapshot_bytes()
            if data is None:
                data = serialize(handle)
            # Durability order: the snapshot file must hit disk before the
            # log prefix it replaces is truncated.
            self._write_snapshot_file(idx, snap_term, data)
            with self._lock:
                if idx <= self.snapshot_index:
                    return  # an InstallSnapshot overtook us
                # Keep a trailing tail of entries past the snapshot so
                # followers behind by < trailing_logs replicate normally.
                keep_from = max(
                    self.log_offset, idx - max(0, self.config.trailing_logs)
                )
                if keep_from > self.log_offset:
                    self.log_offset_term = self._term_at(keep_from)
                    del self.log[: keep_from - self.log_offset]
                    self.entries_truncated += keep_from - self.log_offset
                    self.log_offset = keep_from
                self.snapshot_index = idx
                self.snapshot_term = snap_term
                self._snap_data = data
                self._truncate_persisted_log()
                self.compactions += 1
                self.compaction_wall_ms += (
                    time.monotonic() - t_compact0
                ) * 1000.0
            self.logger.info(
                "raft: node %s compacted log through index %d "
                "(%d bytes snapshot)", self.config.node_id, idx, len(data),
            )
        finally:
            self._compacting = False

    def _handle_append_entries(self, args: dict) -> dict:
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            # Valid leader for this term
            if term > self.current_term or self.role != FOLLOWER:
                self._become_follower(term, args["leader_id"])
            self.leader_id = args["leader_id"]
            self._election_deadline = self._random_deadline()
            self._last_leader_contact = time.monotonic()
            if self.removed:
                # A leader talking to us means we are a member again
                # (re-added by a committed _config entry on its side).
                self.removed = False

            prev_idx = args["prev_log_index"]
            prev_term = args["prev_log_term"]
            entries = args["entries"]
            if prev_idx < self.snapshot_index:
                # Everything at or below our snapshot index is committed and
                # matches the leader by definition; skip the overlap.
                skip = self.snapshot_index - prev_idx
                entries = entries[skip:]
                prev_idx = self.snapshot_index
                prev_term = self.snapshot_term
            last_idx, _ = self._last_log()
            if prev_idx > self.snapshot_index:
                if last_idx < prev_idx:
                    return {"term": self.current_term, "success": False,
                            "conflict_index": last_idx + 1}
                if self._term_at(prev_idx) != prev_term:
                    # Find the first index of the conflicting term
                    conflict_term = self._term_at(prev_idx)
                    first = prev_idx
                    while (first > self.log_offset + 1
                           and self._term_at(first - 1) == conflict_term):
                        first -= 1
                    return {"term": self.current_term, "success": False,
                            "conflict_index": first}

            # Append any new entries, truncating conflicts
            changed = False
            for i, wire in enumerate(entries):
                idx = prev_idx + 1 + i
                entry = _Entry.from_wire(wire)
                pos = idx - self.log_offset - 1
                append = False
                if len(self.log) > pos:
                    if self.log[pos].term != entry.term:
                        del self.log[pos:]
                        append = True
                else:
                    append = True
                if append:
                    # One dumps per ACTUALLY appended entry, measured in
                    # the journal-line convention (index key included)
                    # so leader/follower/reloaded byte books agree for
                    # identical entries.
                    entry.wire_bytes = len(
                        json.dumps({"index": idx, **entry.to_wire()})
                    )
                    self.log.append(entry)
                    self.entries_appended += 1
                    self.bytes_appended += entry.wire_bytes
                    changed = True
            if changed:
                self._truncate_persisted_log()

            if args["leader_commit"] > self.commit_index:
                last_idx, _ = self._last_log()
                self.commit_index = min(args["leader_commit"], last_idx)
                self._apply_committed_locked()
            return {"term": self.current_term, "success": True}
