"""End-to-end latency attribution: per-allocation lifecycle timelines.

The north-star artifacts measure plan/eval latency from event timestamps
and stop there — nobody could say where the rest of a user-visible
placement goes. This module answers that question WITHOUT adding a single
hot-path instrument: it stitches what the observability stack already
records — per-eval trace spans (``nomad_tpu/trace.py``; the span context
rides Plan/Eval envelopes) and the raft-index-stamped typed event stream
(``nomad_tpu/events.py``) — into one **timeline** per evaluation/allocation
batch, then decomposes submit→placed / submit→running latency into
per-stage queue-wait vs service-time contributions (the waterfall Borg's
cell-scale evaluation and Sparrow's headline metric call for, PAPERS.md).

The stitcher is strictly read-only on decisions: it consumes retained
spans and events after the fact, so enabling it cannot perturb placement
(the SIMLOAD event digest is the enforcement: r08 artifacts carry this
section with digests identical to the pre-attribution r07 runs).

Stage taxonomy (a PARTITION of submit→placed, so stage sums reconcile
with measured end-to-end latency by construction — ``unattributed``
holds the thread-handoff/dispatch gaps the spans don't cover):

==================  =====  ====================================================
``broker_wait``     queue  eval ready/blocked-queue wait (restarts on
                           redelivery — each extra pass is a visible retry
                           segment, not lost time)
``raft_catchup``    svc    worker FSM catch-up before snapshotting
``schedule_solve``  svc    the scheduler pass minus nested plan submits
                           (snapshot + staging + device solve + readback)
``submit_overhead`` svc    plan submit RPC minus queue/verify/commit
``plan_queue_wait`` queue  plan-queue parked time
``plan_verify``     svc    fused/scalar plan verification
``raft_commit``     svc    raft apply → durable commit
``unattributed``    —      submit→placed minus everything above
``client_ack``      svc    PlanApplied → client running ack (the
                           submit→running extension; event-stamped)
==================  =====  ====================================================

A bounce through the optimistic pipeline (conflict → RefreshIndex →
re-plan) shows up as ``attempts > 1`` plus per-attempt segments; the
conflict count rides ``bounces``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional, Tuple

from nomad_tpu import structs

# Stage partition of submit->placed, in pipeline order. client_ack extends
# the partition to submit->running.
STAGES = (
    "broker_wait",
    "raft_catchup",
    "schedule_solve",
    "submit_overhead",
    "plan_queue_wait",
    "plan_verify",
    "raft_commit",
    "unattributed",
)

# Express-lane stages (server/express.py): a separate taxonomy — the
# express path skips broker/worker/plan-queue entirely, so its timeline
# is the in-line pick + lease (submit→placed) with the async raft commit
# OUTSIDE submit→placed (it happens after the caller was answered).
# Surfaced in the waterfall only when express timelines are present.
EXPRESS_STAGES = (
    "express_pick",
    "express_lease",
)

# Async-commit stage: informative (how long until the placement became
# durable), deliberately NOT part of the submit→placed partition.
EXPRESS_ASYNC_STAGES = ("express_commit",)

STAGE_KINDS = {
    "broker_wait": "queue",
    "raft_catchup": "service",
    "schedule_solve": "service",
    "submit_overhead": "service",
    "plan_queue_wait": "queue",
    "plan_verify": "service",
    "raft_commit": "service",
    "unattributed": "gap",
    "client_ack": "service",
    "express_pick": "service",
    "express_lease": "service",
    "express_commit": "async",
}

# Span name -> stage for the directly-mapped spans. schedule_solve and
# submit_overhead are derived (parent minus nested children).
_SPAN_STAGE = {
    "broker.wait": "broker_wait",
    "worker.wait_for_index": "raft_catchup",
    "plan.queue_wait": "plan_queue_wait",
    "plan.evaluate": "plan_verify",
    "plan.apply": "raft_commit",
    "express.pick": "express_pick",
    "express.lease": "express_lease",
    "express.commit": "express_commit",
}


def _dur_ms(span: Dict[str, Any]) -> float:
    if span.get("end") is None:
        return 0.0
    return (span["end"] - span["start"]) * 1000.0


class Timeline:
    """One evaluation's lifecycle: submit → placed (→ running), with the
    per-stage decomposition and per-attempt segments. An eval is the
    timeline key because that is the granularity plans, columnar alloc
    blocks, and the trace all share; per-alloc lookups resolve through
    ``Allocation.eval_id``."""

    __slots__ = (
        "eval_id", "job_id", "eval_type", "triggered_by",
        "submitted_at", "placed_at", "running_at",
        "attempts", "bounces", "stage_ms", "solver_ms", "segments",
        "spans_seen",
    )

    def __init__(self, eval_id: str):
        self.eval_id = eval_id
        self.job_id = ""
        self.eval_type = ""
        self.triggered_by = ""
        self.submitted_at: Optional[float] = None
        self.placed_at: Optional[float] = None
        self.running_at: Optional[float] = None
        self.attempts = 0            # submit_plan cycles observed
        self.bounces = 0             # refresh/conflict cycles among them
        self.stage_ms: Dict[str, float] = {}
        self.solver_ms: Dict[str, float] = {}
        # (stage, attempt, start_ms_rel, duration_ms) detail rows,
        # ordered by start — the per-eval waterfall.
        self.segments: List[Dict[str, Any]] = []
        self.spans_seen = 0

    # -- derived -------------------------------------------------------------

    @property
    def submit_to_placed_ms(self) -> Optional[float]:
        if self.submitted_at is None or self.placed_at is None:
            return None
        return (self.placed_at - self.submitted_at) * 1000.0

    @property
    def submit_to_running_ms(self) -> Optional[float]:
        if self.submitted_at is None or self.running_at is None:
            return None
        return (self.running_at - self.submitted_at) * 1000.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "eval_id": self.eval_id,
            "job_id": self.job_id,
            "eval_type": self.eval_type,
            "triggered_by": self.triggered_by,
            "submitted_at": self.submitted_at,
            "placed_at": self.placed_at,
            "running_at": self.running_at,
            "submit_to_placed_ms": _round(self.submit_to_placed_ms),
            "submit_to_running_ms": _round(self.submit_to_running_ms),
            "attempts": self.attempts,
            "bounces": self.bounces,
            "stage_ms": {k: round(v, 3) for k, v in self.stage_ms.items()},
            "solver_ms": {k: round(v, 3) for k, v in self.solver_ms.items()},
            "segments": list(self.segments),
            "spans_seen": self.spans_seen,
        }


def _round(v: Optional[float], nd: int = 3) -> Optional[float]:
    return None if v is None else round(v, nd)


# ---------------------------------------------------------------------------
# Stitching
# ---------------------------------------------------------------------------


def scan_events(events: Iterable) -> Dict[str, Dict[str, Any]]:
    """One pass over the event stream -> per-eval lifecycle anchors:
    ``submitted`` (first EvalUpdated(pending)), ``placed`` (first
    PlanApplied), ``running`` (first AllocClientUpdated(running) whose
    payload names the eval), plus job metadata and the per-key raft-index
    sequence the ordering tests pin. Accepts Event objects or dicts."""
    out: Dict[str, Dict[str, Any]] = {}

    def _rec(key: str) -> Dict[str, Any]:
        rec = out.get(key)
        if rec is None:
            rec = out[key] = {
                "submitted": None, "placed": None, "running": None,
                "job_id": "", "triggered_by": "",
            }
        return rec

    for e in events:
        if isinstance(e, dict):
            topic, etype, key = e["topic"], e["type"], e["key"]
            payload, etime = e.get("payload") or {}, e["time"]
        else:
            topic, etype, key = e.topic, e.type, e.key
            payload, etime = e.payload, e.time
        if topic == "Eval" and etype == "EvalUpdated":
            rec = _rec(key)
            if (payload.get("status") == structs.EVAL_STATUS_PENDING
                    and rec["submitted"] is None):
                rec["submitted"] = etime
                rec["job_id"] = payload.get("job_id", "")
                rec["triggered_by"] = payload.get("triggered_by", "")
        elif topic == "Plan" and etype == "PlanApplied":
            rec = _rec(key)
            if rec["placed"] is None:
                rec["placed"] = etime
        elif topic == "Express" and etype == "ExpressPlaced":
            # Express evals never publish a pending EvalUpdated (they
            # commit COMPLETE, asynchronously); the placement event
            # carries the in-line latency, so the anchors derive from it:
            # placed = event time, submitted = placed - placed_ms.
            rec = _rec(key)
            if rec["submitted"] is None:
                ms = float(payload.get("placed_ms", 0.0))
                rec["placed"] = etime
                rec["submitted"] = etime - ms / 1000.0
                rec["job_id"] = payload.get("job_id", "")
                rec["triggered_by"] = "express"
        elif topic == "Alloc" and etype == "AllocClientUpdated":
            ev_id = payload.get("eval_id", "")
            if (ev_id
                    and payload.get("client_status")
                    == structs.ALLOC_CLIENT_STATUS_RUNNING):
                rec = _rec(ev_id)
                if rec["running"] is None:
                    rec["running"] = etime
    return out


def stitch_eval(eval_id: str, spans: Optional[List[Dict[str, Any]]],
                anchors: Optional[Dict[str, Any]] = None) -> Timeline:
    """Build one Timeline from a trace's span dicts (tracer.get_trace
    shape) plus the event-derived anchors. Works degraded: with no spans
    the end-to-end numbers still come from the anchors (tracing disabled
    is not an error — the waterfall is just all ``unattributed``)."""
    tl = Timeline(eval_id)
    anchors = anchors or {}
    tl.submitted_at = anchors.get("submitted")
    tl.placed_at = anchors.get("placed")
    tl.running_at = anchors.get("running")
    tl.job_id = anchors.get("job_id", "")
    tl.triggered_by = anchors.get("triggered_by", "")

    spans = [s for s in (spans or []) if s.get("end") is not None]
    spans.sort(key=lambda s: (s["start"], s["name"]))
    tl.spans_seen = len(spans)
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    root = by_name.get("eval", [None])[0]
    if root is not None:
        ann = root.get("annotations") or {}
        tl.job_id = tl.job_id or ann.get("job_id", "")
        tl.eval_type = ann.get("type", "")
        tl.triggered_by = tl.triggered_by or ann.get("triggered_by", "")
        if tl.submitted_at is None:
            tl.submitted_at = root["start"]

    submits = by_name.get("worker.submit_plan", [])
    tl.attempts = max(1, len(submits)) if spans else 0
    for s in by_name.get("plan.evaluate", ()):
        ann = s.get("annotations") or {}
        if ann.get("refresh_index"):
            tl.bounces += 1

    stage_ms: Dict[str, float] = {}

    def _add(stage: str, span: Dict[str, Any], attempt: int) -> None:
        d = _dur_ms(span)
        stage_ms[stage] = stage_ms.get(stage, 0.0) + d
        if tl.submitted_at is not None:
            tl.segments.append({
                "stage": stage,
                "kind": STAGE_KINDS[stage],
                "attempt": attempt,
                "start_ms": round((span["start"] - tl.submitted_at) * 1000.0, 3),
                "duration_ms": round(d, 3),
            })

    # Attempt index: the i-th occurrence of a span name is attempt i+1
    # (redeliveries restart broker.wait; bounces restart the plan spans).
    for name, stage in _SPAN_STAGE.items():
        for i, s in enumerate(by_name.get(name, ())):
            _add(stage, s, i + 1)

    # Derived stages: parent minus nested children, clamped at zero (an
    # open child or clock jitter must not go negative).
    invoke_ms = sum(_dur_ms(s) for s in by_name.get(
        "worker.invoke_scheduler", ()))
    submit_ms = sum(_dur_ms(s) for s in submits)
    plan_child_ms = sum(
        stage_ms.get(k, 0.0)
        for k in ("plan_queue_wait", "plan_verify", "raft_commit")
    )
    if invoke_ms:
        solve = max(0.0, invoke_ms - submit_ms)
        stage_ms["schedule_solve"] = solve
        for i, s in enumerate(by_name.get("worker.invoke_scheduler", ())):
            if tl.submitted_at is not None:
                tl.segments.append({
                    "stage": "schedule_solve", "kind": "service",
                    "attempt": i + 1,
                    "start_ms": round(
                        (s["start"] - tl.submitted_at) * 1000.0, 3),
                    "duration_ms": round(_dur_ms(s), 3),
                })
    if submit_ms:
        stage_ms["submit_overhead"] = max(0.0, submit_ms - plan_child_ms)

    # Solver detail (nested inside schedule_solve, not a partition stage).
    for name, group in by_name.items():
        if name.startswith("solver."):
            tl.solver_ms[name[len("solver."):]] = sum(
                _dur_ms(s) for s in group
            )

    # e2e comes from the event anchors only: a no-op eval (no PlanApplied)
    # keeps it absent rather than inventing one from the root span.
    e2e = tl.submit_to_placed_ms
    if e2e is not None:
        if tl.triggered_by == "express":
            # Express submit→placed is the in-line path: only the
            # express stages partition it. The async-commit machinery's
            # spans (express_commit and the plan stages nested under it)
            # run AFTER the caller was answered and must not charge it.
            attributed = sum(stage_ms.get(s, 0.0) for s in EXPRESS_STAGES)
        else:
            attributed = sum(
                v for k, v in stage_ms.items()
                if STAGE_KINDS.get(k) != "async"
            )
        stage_ms["unattributed"] = max(0.0, e2e - attributed)
    if (tl.placed_at is not None and tl.running_at is not None
            and tl.running_at >= tl.placed_at):
        stage_ms["client_ack"] = (tl.running_at - tl.placed_at) * 1000.0

    tl.stage_ms = stage_ms
    tl.segments.sort(key=lambda seg: seg["start_ms"])
    return tl


def stitch(events: Iterable, tracer=None) -> Dict[str, Timeline]:
    """Stitch a timeline for every eval the event stream saw submitted.
    ``tracer`` defaults to the process tracer; pass None-able — evals
    whose traces were evicted (or recorded with tracing off) still get
    event-anchored timelines."""
    if tracer is None:
        from nomad_tpu import trace

        tracer = trace.get_tracer()
    anchors = scan_events(events)
    out: Dict[str, Timeline] = {}
    for eval_id, rec in anchors.items():
        if rec["submitted"] is None:
            continue
        spans = tracer.get_trace(eval_id) if tracer is not None else None
        out[eval_id] = stitch_eval(eval_id, spans, rec)
    return out


def stitch_from_server(server, eval_id: str) -> Optional[Timeline]:
    """Live-server lookup for the HTTP tier: anchors from the server's
    retained event ring, spans from the process tracer. None when neither
    the ring nor the tracer knows the eval."""
    from nomad_tpu import trace

    broker = getattr(getattr(server, "fsm", None), "events", None)
    anchors = scan_events(broker.all_events()) if broker is not None else {}
    rec = anchors.get(eval_id)
    spans = trace.get_tracer().get_trace(eval_id)
    if rec is None and spans is None:
        return None
    return stitch_eval(eval_id, spans, rec)


# ---------------------------------------------------------------------------
# Critical-path attribution: the latency waterfall
# ---------------------------------------------------------------------------


def _percentile(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    n = len(sorted_vals)
    return sorted_vals[max(0, min(n - 1, math.ceil(p * n) - 1))]


def _quantile_block(vals: List[float]) -> Dict[str, Any]:
    s = sorted(vals)
    return {
        "n": len(s),
        "p50_ms": round(_percentile(s, 0.50), 2),
        "p95_ms": round(_percentile(s, 0.95), 2),
        "p99_ms": round(_percentile(s, 0.99), 2),
        "max_ms": round(s[-1], 2) if s else 0.0,
    }


def attribution(timelines: Iterable[Timeline]) -> Dict[str, Any]:
    """The scenario-window reduction: submit→placed / submit→running
    percentiles plus a per-stage waterfall — each stage's total and mean
    contribution, its share of aggregate end-to-end time, and its share
    inside the p95 tail (the critical-path view: which stage buys the
    tail). ``reconciliation`` proves the partition property: attributed
    stage sums (incl. the explicit unattributed gap) equal measured
    end-to-end within rounding."""
    tls = [t for t in timelines if t.submit_to_placed_ms is not None]
    placed = [t.submit_to_placed_ms for t in tls]
    running = [t.submit_to_running_ms for t in tls
               if t.submit_to_running_ms is not None]

    out: Dict[str, Any] = {
        "timelines": len(tls),
        "submit_to_placed_ms": _quantile_block(placed),
        "submit_to_running_ms": _quantile_block(running),
        "attempts": {
            "max": max((t.attempts for t in tls), default=0),
            "bounced_timelines": sum(1 for t in tls if t.bounces),
            "bounces": sum(t.bounces for t in tls),
        },
    }
    if not tls:
        out["waterfall"] = []
        out["reconciliation"] = {"end_to_end_ms": 0.0, "stage_sum_ms": 0.0,
                                 "attributed_fraction": 0.0}
        return out

    total_e2e = sum(placed)
    p95 = _percentile(sorted(placed), 0.95)
    tail = [t for t in tls if t.submit_to_placed_ms >= p95] or tls
    tail_e2e = sum(t.submit_to_placed_ms for t in tail)

    waterfall = []
    stage_sum_all = 0.0
    stages = list(STAGES)
    if any(t.stage_ms.get(s) for t in tls for s in EXPRESS_STAGES):
        # Express timelines present: their stages join the waterfall
        # (before the unattributed gap, which stays last).
        stages = stages[:-1] + list(EXPRESS_STAGES) + stages[-1:]
    for stage in stages:
        per_tl = [t.stage_ms.get(stage, 0.0) for t in tls]
        total = sum(per_tl)
        stage_sum_all += total
        tail_total = sum(t.stage_ms.get(stage, 0.0) for t in tail)
        waterfall.append({
            "stage": stage,
            "kind": STAGE_KINDS[stage],
            "total_ms": round(total, 2),
            "mean_ms": round(total / len(tls), 3),
            "p95_ms": round(_percentile(sorted(per_tl), 0.95), 2),
            "share": round(total / total_e2e, 4) if total_e2e else 0.0,
            "share_of_p95_tail": (
                round(tail_total / tail_e2e, 4) if tail_e2e else 0.0
            ),
        })
    out["waterfall"] = waterfall
    out["reconciliation"] = {
        "end_to_end_ms": round(total_e2e, 2),
        "stage_sum_ms": round(stage_sum_all, 2),
        # Partition property: 1.0 up to clamping/rounding. The <10%
        # acceptance bound guards the stitcher's clock consistency, not a
        # tunable.
        "attributed_fraction": (
            round(stage_sum_all / total_e2e, 4) if total_e2e else 0.0
        ),
    }
    return out


def worst_k(timelines: Iterable[Timeline], k: int = 8) -> List[Dict[str, Any]]:
    """The K slowest submit→placed timelines, slowest first — what the
    debug bundle and tier-1 failure forensics attach."""
    ranked = sorted(
        (t for t in timelines if t.submit_to_placed_ms is not None),
        key=lambda t: t.submit_to_placed_ms, reverse=True,
    )
    return [t.to_dict() for t in ranked[:k]]
