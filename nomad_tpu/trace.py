"""Eval-lifecycle tracing: Dapper-style spans over the broker → scheduler →
solver → plan-apply pipeline.

The reference instruments every hot path with go-metrics timers
(nomad/worker.go:147, nomad/plan_apply.go:149, nomad/fsm.go:148,
nomad/rpc.go:68) but aggregates them — no single evaluation's latency can
be decomposed after the fact. This module adds the per-evaluation view:
lightweight spans with parent links and key/value annotations, recorded
into a bounded, lock-protected ring of traces keyed by evaluation id.

Span taxonomy (producers in parentheses):

- ``eval``                      root; broker enqueue → ack/failed (eval_broker)
- ``broker.wait``               ready-queue wait, enqueue/nack → dequeue (eval_broker)
- ``worker.wait_for_index``     FSM catch-up before snapshot (worker)
- ``worker.invoke_scheduler``   the scheduler pass (worker)
- ``solver.staging``            host tensorization: masks + usage (tpu/solver)
- ``solver.transfer``           per-eval device uploads + dispatch (tpu/solver)
- ``solver.execute``            device execution wait (ops/binpack, ops/coalesce)
- ``solver.readback``           D2H readback + host expansion (ops/binpack)
- ``worker.submit_plan``        plan submit → response (worker)
- ``plan.queue_wait``           plan-queue wait, enqueue → applier dequeue
- ``plan.evaluate``             plan verification against the snapshot
- ``plan.apply``                raft apply → commit (plan_apply)
- ``fsm.apply``                 one FSM log-entry apply, annotated msg_type

The span context (``{"trace_id", "span_id"}``) crosses the RPC boundary in
the request envelope: ``Plan.span_ctx`` rides Plan.Submit, and
Eval.Dequeue responses carry the root context so a follower's worker
parents its spans on the leader's broker span (``Tracer.adopt_root``).

Exposition lives in the HTTP tier: ``/v1/evaluation/<id>/trace``,
``/v1/agent/traces``, and Chrome trace-event export (``chrome_trace``)
that loads directly into Perfetto (https://ui.perfetto.dev).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

# Monotonic wall clock: epoch-anchored perf_counter, so spans from every
# thread order consistently (time.time() can step backwards under NTP,
# which would break the nesting invariants the trace consumers rely on).
# nomadlint: allow(DET002) -- one-shot wall anchor for the monotonic
# span clock; sampled exactly once at import, never in span math.
_EPOCH = time.time() - time.perf_counter()


def now() -> float:
    return _EPOCH + time.perf_counter()


# Span ids need process-uniqueness, not entropy: os.urandom is a syscall
# (~30us under load — more than the rest of a span's lifecycle combined),
# so ids derive from one urandom seed and a counter pushed through a
# 64-bit odd-multiplier bijection (unique per process, random-looking).
_SPAN_SEED = int.from_bytes(os.urandom(8), "little")
_span_counter = itertools.count(1)


def _new_span_id() -> str:
    mixed = (next(_span_counter) * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    return format(_SPAN_SEED ^ mixed, "016x")


class Span:
    """One timed operation within a trace. Not thread-safe per instance:
    a span is started, annotated, and finished by one component."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "start", "end",
        "annotations", "thread", "_tracer",
    )

    def __init__(self, tracer: "Tracer", trace_id: str, name: str,
                 parent_id: str = "", start: Optional[float] = None,
                 annotations: Optional[Dict[str, Any]] = None):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.name = name
        self.start = now() if start is None else start
        self.end: Optional[float] = None
        self.annotations: Dict[str, Any] = dict(annotations or {})
        self.thread = threading.current_thread().name

    def annotate(self, key: str, value: Any) -> "Span":
        self.annotations[key] = value
        return self

    def finish(self, end: Optional[float] = None) -> None:
        if self.end is not None:
            return  # idempotent: racing finishers keep the first stamp
        self.end = now() if end is None else end
        self._tracer._record_finished(self)

    def ctx(self) -> Dict[str, str]:
        """The wire-portable span context (rides RPC request envelopes)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_ms": (
                round((self.end - self.start) * 1000.0, 4)
                if self.end is not None else None
            ),
            "thread": self.thread,
            # Copy: serialization happens outside any lock, and an open
            # span's producer may annotate concurrently — handing out the
            # live dict would race json.dumps with a dict resize.
            "annotations": dict(self.annotations),
        }


class _NullSpan:
    """Inert span: returned when tracing is disabled so call sites never
    branch. Shared singleton; every method is a no-op."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = ""
    name = ""
    start = 0.0
    end: Optional[float] = None
    annotations: Dict[str, Any] = {}

    def annotate(self, key: str, value: Any) -> "_NullSpan":
        return self

    def finish(self, end: Optional[float] = None) -> None:
        pass

    def ctx(self) -> Dict[str, str]:
        return {}

    def to_dict(self) -> Dict[str, Any]:
        return {}


NULL_SPAN = _NullSpan()


class _Trace:
    __slots__ = ("trace_id", "spans", "open", "root_ctx", "dropped",
                 "updated", "done")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: List[Span] = []          # finished spans
        self.open: Dict[str, Span] = {}      # span_id -> unfinished span
        self.root_ctx: Dict[str, str] = {}   # the root span's wire context
        self.dropped = 0
        self.updated = now()
        self.done = False


class Tracer:
    """Bounded ring of traces. Oldest-inserted trace evicted past
    ``max_traces``; per-trace span count capped at ``max_spans`` (excess
    finishes are counted, not stored). All methods are thread-safe."""

    def __init__(self, max_traces: int = 256, max_spans: int = 512,
                 enabled: bool = True):
        self.max_traces = max(1, max_traces)
        self.max_spans = max(1, max_spans)
        self.enabled = enabled
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, _Trace]" = (
            collections.OrderedDict()
        )
        # Process-wide loss accounting (mutated under the lock): per-trace
        # ``dropped`` says one eval's trace is partial, but without an
        # aggregate, silent trace loss under 10k-node load is invisible
        # until someone opens the one trace that happens to be truncated.
        self.spans_dropped = 0
        self.traces_evicted = 0

    # -- producing ---------------------------------------------------------

    def start_span(self, trace_id: str, name: str, parent: Any = None,
                   start: Optional[float] = None,
                   annotations: Optional[Dict[str, Any]] = None,
                   root: bool = False):
        """Open a span. ``parent`` is a Span, a wire context dict, or a
        span_id string. ``root=True`` additionally registers the span's
        context as the trace root (what ``root_ctx`` returns)."""
        if not self.enabled or not trace_id:
            return NULL_SPAN
        parent_id = ""
        if isinstance(parent, Span):
            parent_id = parent.span_id
        elif isinstance(parent, dict):
            parent_id = parent.get("span_id", "")
        elif isinstance(parent, str):
            parent_id = parent
        span = Span(self, trace_id, name, parent_id, start, annotations)
        with self._lock:
            tr = self._trace_locked(trace_id)
            tr.open[span.span_id] = span
            tr.updated = now()
            if root:
                tr.root_ctx = span.ctx()
        return span

    def _record_finished(self, span: Span) -> None:
        with self._lock:
            tr = self._traces.get(span.trace_id)
            if tr is None:
                # Trace evicted while the span was open: re-admit it so a
                # slow eval's tail spans aren't silently lost.
                tr = self._trace_locked(span.trace_id)
            tr.open.pop(span.span_id, None)
            if len(tr.spans) >= self.max_spans:
                tr.dropped += 1
                self.spans_dropped += 1
            else:
                tr.spans.append(span)
            tr.updated = now()

    def record_batch(self, parent, stages, prefix: str = "") -> None:
        """Bulk-record already-measured ``(name, start, end)`` triples as
        finished children of ``parent`` under ONE lock hold — the solver
        emits its four stage cuts per eval, and per-span locking was a
        measurable slice of the tracing overhead budget."""
        if (not self.enabled or not stages or parent is None
                or isinstance(parent, _NullSpan)):
            return
        spans = []
        for name, t0, t1 in stages:
            s = Span(self, parent.trace_id, prefix + name,
                     parent.span_id, t0)
            s.end = t1
            spans.append(s)
        with self._lock:
            tr = self._trace_locked(parent.trace_id)
            for s in spans:
                if len(tr.spans) >= self.max_spans:
                    tr.dropped += 1
                    self.spans_dropped += 1
                else:
                    tr.spans.append(s)
            tr.updated = now()

    def adopt_root(self, trace_id: str, ctx: Dict[str, str]) -> None:
        """Register a REMOTE root context (received over RPC) so local
        spans of this trace can parent on it via root_ctx()."""
        if not self.enabled or not trace_id or not ctx:
            return
        with self._lock:
            tr = self._trace_locked(trace_id)
            if not tr.root_ctx:
                tr.root_ctx = dict(ctx)

    def root_ctx(self, trace_id: str) -> Dict[str, str]:
        with self._lock:
            tr = self._traces.get(trace_id)
            return dict(tr.root_ctx) if tr is not None else {}

    def mark_done(self, trace_id: str) -> None:
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is not None:
                tr.done = True
                tr.updated = now()

    def _trace_locked(self, trace_id: str) -> _Trace:
        tr = self._traces.get(trace_id)
        if tr is None:
            tr = _Trace(trace_id)
            self._traces[trace_id] = tr
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
                self.traces_evicted += 1
        return tr

    def stats(self) -> Dict[str, Any]:
        """Aggregate tracer health for /v1/agent/metrics: retained count
        plus the process-wide loss counters — a 10k-node run silently
        evicting traces (or truncating span rings) shows up here, not
        only inside whichever single trace got clipped."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "retained": len(self._traces),
                "max_traces": self.max_traces,
                "max_spans": self.max_spans,
                "spans_dropped": self.spans_dropped,
                "traces_evicted": self.traces_evicted,
            }

    # -- querying ----------------------------------------------------------

    def get_trace(self, trace_id: str) -> Optional[List[Dict[str, Any]]]:
        """All spans of one trace (finished + still-open), sorted by start
        time. None when the trace is unknown (or was evicted)."""
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                return None
            spans = list(tr.spans) + list(tr.open.values())
        out = [s.to_dict() for s in spans]
        out.sort(key=lambda d: (d["start"], d["name"]))
        return out

    def traces(self) -> List[Dict[str, Any]]:
        """Summaries of retained traces, most recently updated first."""
        with self._lock:
            items = list(self._traces.values())
        out = []
        for tr in items:
            spans = list(tr.spans)
            root = next((s for s in spans if not s.parent_id), None)
            out.append({
                "trace_id": tr.trace_id,
                "spans": len(spans),
                "open_spans": len(tr.open),
                "dropped_spans": tr.dropped,
                "done": tr.done,
                "updated": tr.updated,
                "root": root.name if root is not None else "",
                "duration_ms": (
                    round((root.end - root.start) * 1000.0, 4)
                    if root is not None and root.end is not None else None
                ),
            })
        out.sort(key=lambda d: d["updated"], reverse=True)
        return out

    def chrome_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Chrome trace-event JSON for one trace — drops straight into
        Perfetto / chrome://tracing. Complete ('X') events in microseconds;
        thread-name metadata events map our thread names to tids."""
        spans = self.get_trace(trace_id)
        if spans is None:
            return None
        tids: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []
        for s in spans:
            tid = tids.setdefault(s["thread"], len(tids) + 1)
            end = s["end"] if s["end"] is not None else now()
            events.append({
                "name": s["name"],
                "cat": "eval",
                "ph": "X",
                "ts": round(s["start"] * 1e6, 1),
                "dur": round((end - s["start"]) * 1e6, 1),
                "pid": 1,
                "tid": tid,
                "args": {
                    **s["annotations"],
                    "span_id": s["span_id"],
                    "parent_id": s["parent_id"],
                },
            })
        for name, tid in tids.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": name},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Global tracer + thread-local context
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global: Optional[Tracer] = None


def get_tracer() -> Tracer:
    global _global
    with _global_lock:
        if _global is None:
            _global = Tracer()
        return _global


def set_tracer(tracer: Tracer) -> Tracer:
    global _global
    with _global_lock:
        _global = tracer
    return tracer


def configure(max_traces: int = 256, max_spans: int = 512,
              enabled: bool = True) -> Tracer:
    """Agent telemetry wiring: (re)build the process tracer from the
    ``telemetry { }`` config block knobs."""
    return set_tracer(Tracer(max_traces, max_spans, enabled))


_tls = threading.local()


def current_span():
    """The active span on this thread (set by use_span), or None."""
    return getattr(_tls, "span", None)


@contextmanager
def use_span(span):
    """Install ``span`` as this thread's active span: downstream
    components (solver stages, FSM applies) parent on it without any
    signature plumbing. NULL_SPAN installs as None."""
    prev = getattr(_tls, "span", None)
    _tls.span = span if not isinstance(span, _NullSpan) else None
    try:
        yield span
    finally:
        _tls.span = prev


# ---------------------------------------------------------------------------
# Stage timing — the ONE stage-cut path shared by the production solver and
# bench.py's device-time breakdown (no second parallel timer).
# ---------------------------------------------------------------------------


class _StageCtx:
    """Slotted stage context: measurably cheaper than a generator-based
    contextmanager on the per-solve hot path."""

    __slots__ = ("timer", "name", "t0")

    def __init__(self, timer: "StageTimer", name: str):
        self.timer = timer
        self.name = name

    def __enter__(self):
        self.t0 = now()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.timer.stages.append((self.name, self.t0, now()))
        return False


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_CTX = _NullCtx()


class StageTimer:
    """Named, ordered stage cuts (staging / transfer / execute / readback —
    the same cuts bench.py's breakdown publishes). Stages recorded on any
    thread; emitted afterwards as child spans + telemetry samples."""

    __slots__ = ("stages",)

    def __init__(self):
        self.stages: List[tuple] = []  # (name, start, end)

    def stage(self, name: str) -> _StageCtx:
        return _StageCtx(self, name)

    def durations_ms(self) -> Dict[str, float]:
        """Summed per-stage wall in milliseconds."""
        out: Dict[str, float] = {}
        for name, t0, t1 in self.stages:
            out[name] = out.get(name, 0.0) + (t1 - t0) * 1000.0
        return out

    def emit_spans(self, parent, prefix: str = "solver.") -> None:
        """Retroactively record each stage as a child span of ``parent``
        (a live Span), preserving the measured start/end stamps — one
        bulk insert, one lock hold."""
        if parent is None or isinstance(parent, _NullSpan):
            return
        tracer = getattr(parent, "_tracer", None) or get_tracer()
        tracer.record_batch(parent, self.stages, prefix)

    def emit_telemetry(self, key_prefix=("solver",)) -> None:
        from nomad_tpu import telemetry

        for name, ms in self.durations_ms().items():
            telemetry.add_sample(tuple(key_prefix) + (name,), ms)


class _NullStageTimer(StageTimer):
    """Inert stage timer handed out when no timer is installed: ``stage``
    costs one shared-singleton enter/exit on the solve hot path."""

    __slots__ = ()

    def stage(self, name: str):
        return _NULL_CTX

    def emit_spans(self, parent, prefix: str = "solver.") -> None:
        pass

    def emit_telemetry(self, key_prefix=("solver",)) -> None:
        pass


NULL_STAGES = _NullStageTimer()


def active_stages() -> StageTimer:
    """The stage timer installed on this thread (by the solver entry
    point), or the inert singleton."""
    return getattr(_tls, "stages", None) or NULL_STAGES


@contextmanager
def use_stages(st: StageTimer):
    prev = getattr(_tls, "stages", None)
    _tls.stages = None if isinstance(st, _NullStageTimer) else st
    try:
        yield st
    finally:
        _tls.stages = prev


def stage(name: str):
    """Record ``name`` on this thread's active stage timer (no-op when
    none is installed) — used by the device-path fetch closures to cut
    execute/readback without plumbing a timer through their signatures."""
    return active_stages().stage(name)
