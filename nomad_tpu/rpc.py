"""Network RPC: framed JSON over TCP with connection pooling.

The transport tier of the reference is msgpack-RPC over yamux with a pooled
client (/root/reference/nomad/rpc.go:21-137, nomad/pool.go). Capabilities
carried over: a single listener serving concurrent requests, client-side
connection reuse, request/response correlation, and clean propagation of
remote errors. Framing is length-prefixed JSON (the codec is internal to
this framework; pickle is avoided — peers are semi-trusted).

Wire format: 4-byte big-endian length + JSON object.
Request:  {"seq": n, "method": "Service.Method", "args": {...}}
Response: {"seq": n, "error": null | str, "result": ...}
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

_LEN = struct.Struct(">I")
MAX_FRAME = 64 << 20


class RPCError(Exception):
    pass


class RemoteError(RPCError):
    """An error raised by the remote handler."""


def _send_frame(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > MAX_FRAME:
        raise RPCError(f"frame too large: {length}")
    return json.loads(_recv_exact(sock, length))


class RPCServer:
    """Serves registered handlers on a TCP listener (rpc.go:21-72 listen/
    handleConn, minus the protocol-byte demux — raft runs on its own RPC
    methods instead of a separate stream)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 logger: Optional[logging.Logger] = None):
        self.logger = logger or logging.getLogger("nomad_tpu.rpc")
        self._handlers: Dict[str, Callable[[dict], Any]] = {}
        self._listener = socket.create_server((host, port))
        self.addr = "{}:{}".format(*self._listener.getsockname())
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"rpc-{self.addr}"
        )

    def register(self, method: str, handler: Callable[[dict], Any]) -> None:
        self._handlers[method] = handler

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            self._listener.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._shutdown.is_set():
                req = _recv_frame(conn)
                resp = self._dispatch(req)
                _send_frame(conn, resp)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            conn.close()

    def _dispatch(self, req: dict) -> dict:
        seq = req.get("seq")
        method = req.get("method", "")
        handler = self._handlers.get(method)
        if handler is None:
            return {"seq": seq, "error": f"unknown method {method!r}",
                    "result": None}
        try:
            return {"seq": seq, "error": None, "result": handler(req.get("args", {}))}
        except Exception as e:
            self.logger.debug("rpc: handler %s failed: %s", method, e)
            return {"seq": seq, "error": f"{type(e).__name__}: {e}",
                    "result": None}


class ConnPool:
    """Pooled RPC client connections (reference: nomad/pool.go:138-371).
    One pooled connection per address; requests on a connection serialize
    (sufficient at control-plane rates; the reference multiplexes instead)."""

    def __init__(self, timeout: float = 10.0):
        self.timeout = timeout
        self._lock = threading.Lock()
        self._conns: Dict[str, Tuple[socket.socket, threading.Lock]] = {}
        self._seq = 0

    def call(self, addr: str, method: str, args: dict,
             timeout: Optional[float] = None) -> Any:
        """RPC to addr; raises RemoteError for handler errors, RPCError for
        transport failures (after invalidating the pooled conn)."""
        sock, conn_lock = self._acquire(addr)
        with self._lock:
            self._seq += 1
            seq = self._seq
        try:
            with conn_lock:
                sock.settimeout(timeout or self.timeout)
                _send_frame(sock, {"seq": seq, "method": method, "args": args})
                resp = _recv_frame(sock)
        except (ConnectionError, OSError, ValueError) as e:
            self._invalidate(addr)
            raise RPCError(f"rpc to {addr} failed: {e}") from e
        if resp.get("error"):
            raise RemoteError(resp["error"])
        return resp.get("result")

    def _acquire(self, addr: str) -> Tuple[socket.socket, threading.Lock]:
        with self._lock:
            entry = self._conns.get(addr)
            if entry is not None:
                return entry
        host, port = addr.rsplit(":", 1)
        try:
            sock = socket.create_connection((host, int(port)), timeout=self.timeout)
        except OSError as e:
            raise RPCError(f"failed to connect to {addr}: {e}") from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        entry = (sock, threading.Lock())
        with self._lock:
            existing = self._conns.get(addr)
            if existing is not None:
                sock.close()
                return existing
            self._conns[addr] = entry
        return entry

    def _invalidate(self, addr: str) -> None:
        with self._lock:
            entry = self._conns.pop(addr, None)
        if entry is not None:
            try:
                entry[0].close()
            except OSError:
                pass

    def shutdown(self) -> None:
        with self._lock:
            for sock, _ in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()
