"""Network RPC: framed JSON over TCP with stream-multiplexed pooling.

The transport tier of the reference is msgpack-RPC over yamux with a pooled
client (/root/reference/nomad/rpc.go:21-137, nomad/pool.go). Capabilities
carried over: a single listener serving concurrent requests, client-side
connection reuse, request/response correlation, and clean propagation of
remote errors. Framing is length-prefixed JSON (the codec is internal to
this framework; pickle is avoided — peers are semi-trusted).

Multiplexing (yamux-lite): the seq field IS the stream id. One pooled
connection per address carries any number of in-flight requests — the
server dispatches each request on its own thread and writes responses
out of order under a per-connection write lock; the client parks each
caller on its seq and a per-connection reader demuxes responses. A
blocking long-poll (Eval.Dequeue, blocking queries) therefore shares the
connection with control traffic instead of requiring a second pool, which
is the scaling answer the reference gets from yamux streams
(nomad/rpc.go:120-137).

Wire format: 4-byte big-endian length + JSON object.
Request:  {"seq": n, "method": "Service.Method", "args": {...}}
Response: {"seq": n, "error": null | str, "result": ...}
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional

from nomad_tpu import faults, telemetry

_LEN = struct.Struct(">I")

# Sentinel a dispatcher returns to swallow the response frame entirely —
# the injected-fault path for "request executed, response lost" (the
# caller then times out with RPCTimeoutError: possibly-executed, NOT
# auto-retried). Organic code never returns it.
SWALLOW_RESPONSE = object()
MAX_FRAME = 64 << 20
# Kernel-level send timeout (SO_SNDTIMEO): bounds sendall on a peer that
# stopped reading WITHOUT touching recv (the demux reader blocks forever by
# design). A send that trips this invalidates the connection.
SEND_TIMEOUT = 30.0
# Per-connection cap on in-flight server-side requests: reads from a
# flooding peer pause (TCP backpressure) instead of spawning unbounded
# threads.
MAX_INFLIGHT_PER_CONN = 64


def _set_send_timeout(sock: socket.socket, seconds: float) -> None:
    sec = int(seconds)
    usec = int((seconds - sec) * 1_000_000)
    sock.setsockopt(
        socket.SOL_SOCKET, socket.SO_SNDTIMEO, struct.pack("ll", sec, usec)
    )


def _hard_close(sock: socket.socket) -> None:
    """shutdown(SHUT_RDWR) then close: plain close() does not interrupt a
    recv blocked in another thread, and the peer would never see FIN."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class RPCError(Exception):
    pass


class RemoteError(RPCError):
    """An error raised by the remote handler."""


class RPCUndeliveredError(RPCError):
    """Transport failed BEFORE the request reached the peer (connect
    failure, or sendall raised so the length-prefixed frame is incomplete
    and the peer's codec drops the connection without dispatching). Safe
    to retry even for non-idempotent RPCs — the handler never ran."""


class RPCTimeoutError(RPCError):
    """The per-call deadline expired with the request possibly executed
    remotely (response lost or late). NOT safe to blindly retry
    non-idempotent RPCs."""


def _send_frame(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > MAX_FRAME:
        raise RPCError(f"frame too large: {length}")
    return json.loads(_recv_exact(sock, length))


def serve_frames(
    conn: socket.socket,
    dispatch: Callable[[Any], dict],
    shutdown: threading.Event,
    logger: logging.Logger,
    write_lock: Optional[threading.Lock] = None,
    thread_name: str = "rpc-stream",
) -> None:
    """Per-connection serve loop shared by RPCServer and the SCADA-analog
    uplink provider: each inbound frame runs on its own thread; responses
    interleave on the shared connection under a write lock, correlated by
    seq — so a parked long-poll never head-of-line blocks control traffic.
    In-flight requests per connection are capped: acquiring the semaphore
    before reading the next frame applies TCP backpressure to a flooding
    peer instead of spawning unbounded threads.

    Runs until the connection drops or ``shutdown`` is set; transport
    errors propagate to the caller (which owns socket cleanup). A handler
    result that fails to serialize is answered with an error frame so the
    peer fails fast instead of timing out."""
    if write_lock is None:
        write_lock = threading.Lock()
    inflight = threading.Semaphore(MAX_INFLIGHT_PER_CONN)

    def handle(req: Any) -> None:
        try:
            resp = dispatch(req)
            if resp is SWALLOW_RESPONSE:
                return
            try:
                with write_lock:
                    _send_frame(conn, resp)
            except (ConnectionError, OSError):
                pass
            except Exception as e:
                logger.warning(
                    "rpc: response for %s not serializable: %s",
                    req.get("method") if isinstance(req, dict) else req, e,
                )
                err = {"seq": req.get("seq") if isinstance(req, dict) else None,
                       "error": f"response serialization failed: {e}",
                       "result": None}
                try:
                    with write_lock:
                        _send_frame(conn, err)
                except Exception:
                    _hard_close(conn)
        finally:
            inflight.release()

    while not shutdown.is_set():
        inflight.acquire()
        try:
            req = _recv_frame(conn)
        except BaseException:
            inflight.release()
            raise
        threading.Thread(
            target=handle, args=(req,), daemon=True, name=thread_name,
        ).start()


class RPCServer:
    """Serves registered handlers on a TCP listener (rpc.go:21-72 listen/
    handleConn, minus the protocol-byte demux — raft runs on its own RPC
    methods instead of a separate stream)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 logger: Optional[logging.Logger] = None,
                 ssl_context=None):
        self.logger = logger or logging.getLogger("nomad_tpu.rpc")
        self._handlers: Dict[str, Callable[[dict], Any]] = {}
        # Optional TLS arm (reference nomad/rpc.go:104-110 rpcTLS): the
        # context wraps each accepted conn; the mux above is unchanged.
        self._ssl_context = ssl_context
        self._listener = socket.create_server((host, port))
        self.addr = "{}:{}".format(*self._listener.getsockname())
        self._shutdown = threading.Event()
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"rpc-{self.addr}"
        )

    def register(self, method: str, handler: Callable[[dict], Any]) -> None:
        self._handlers[method] = handler

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        # shutdown(SHUT_RDWR) BEFORE close: a bare close() does not wake
        # the thread blocked in accept() — the open file description
        # (and with it the LISTEN port binding) survives until that
        # syscall returns, so a server restarting on the SAME port gets
        # EADDRINUSE from its own ghost (the restart-under-load
        # scenario's kill/rebind found this).
        _hard_close(self._listener)
        # Close accepted connections too: parked long-poll streams on
        # peers must fail fast, not sleep out their timeouts.
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            _hard_close(conn)
        # The accept thread must actually exit before the caller may
        # rebind the port.
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _set_send_timeout(conn, SEND_TIMEOUT)
            if self._ssl_context is not None:
                # Bound the handshake: a half-open probe must not pin
                # this thread forever.
                conn.settimeout(SEND_TIMEOUT)
                conn = self._ssl_context.wrap_socket(conn, server_side=True)
                conn.settimeout(None)
        except (ConnectionError, OSError, ValueError) as e:
            self.logger.debug("rpc: TLS handshake failed: %s", e)
            _hard_close(conn)
            return
        with self._conns_lock:
            self._conns.add(conn)
        try:
            serve_frames(conn, self._dispatch, self._shutdown, self.logger)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    def _dispatch(self, req: dict) -> dict:
        # Request counters/timers (reference: nomad/rpc.go:68 rpc.request
        # + per-method MeasureSince at the endpoint handlers).
        seq = req.get("seq")
        method = req.get("method", "")
        fault = faults.fire("rpc.recv", target=method)
        if fault is not None:
            if fault.mode == "drop":
                # Execute, then lose the response: the caller's deadline
                # expires with the request POSSIBLY EXECUTED — the
                # RPCTimeoutError half of the retry-safety distinction.
                handler = self._handlers.get(method)
                if handler is not None:
                    try:
                        handler(req.get("args", {}))
                    except Exception:
                        pass
                return SWALLOW_RESPONSE
            if fault.mode == "partition":
                # The request silently never arrives (handler NOT run):
                # like every other site's partition, loss — never a fast
                # explicit error. The caller still times out, and from
                # its side that is indistinguishable from a lost
                # response, exactly as with a real partition.
                return SWALLOW_RESPONSE
            if fault.mode == "error":
                return {"seq": seq, "error": "injected fault: rpc.recv",
                        "result": None}
        handler = self._handlers.get(method)
        telemetry.incr_counter(("rpc", "request"))
        if handler is None:
            telemetry.incr_counter(("rpc", "unknown_method"))
            return {"seq": seq, "error": f"unknown method {method!r}",
                    "result": None}
        start = time.perf_counter()
        try:
            out = {"seq": seq, "error": None,
                   "result": handler(req.get("args", {}))}
        except Exception as e:
            self.logger.debug("rpc: handler %s failed: %s", method, e)
            telemetry.incr_counter(("rpc", "request_error"))
            out = {"seq": seq, "error": f"{type(e).__name__}: {e}",
                   "result": None}
        telemetry.measure_since(("rpc", method), start)
        return out


class _Waiter:
    __slots__ = ("event", "resp")

    def __init__(self):
        self.event = threading.Event()
        self.resp: Optional[dict] = None


class _MuxConn:
    """One multiplexed client connection: a reader thread demuxes
    responses to parked callers by seq (the yamux-stream analog)."""

    def __init__(self, sock: socket.socket, addr: str):
        self.sock = sock
        self.addr = addr
        self.write_lock = threading.Lock()
        self.lock = threading.Lock()
        self.pending: Dict[int, _Waiter] = {}
        self.dead: Optional[Exception] = None
        threading.Thread(
            target=self._read_loop, daemon=True, name=f"rpc-mux-{addr}"
        ).start()

    def register(self, seq: int) -> _Waiter:
        waiter = _Waiter()
        with self.lock:
            if self.dead is not None:
                # Nothing was sent yet: undelivered, retryable.
                raise RPCUndeliveredError(
                    f"connection to {self.addr} is down: {self.dead}"
                )
            self.pending[seq] = waiter
        return waiter

    def forget(self, seq: int) -> None:
        with self.lock:
            self.pending.pop(seq, None)

    def _read_loop(self) -> None:
        try:
            while True:
                resp = _recv_frame(self.sock)
                with self.lock:
                    waiter = self.pending.pop(resp.get("seq"), None)
                if waiter is not None:
                    waiter.resp = resp
                    waiter.event.set()
                # Unknown seq: a response arriving after its caller timed
                # out — dropped; the stream stays healthy.
        except Exception as e:
            with self.lock:
                self.dead = e
                pending = list(self.pending.values())
                self.pending.clear()
            for waiter in pending:
                waiter.event.set()  # resp stays None -> transport error
            try:
                self.sock.close()
            except OSError:
                pass


class ConnPool:
    """Pooled, stream-multiplexed RPC client connections (reference:
    nomad/pool.go:138-371 + yamux). One connection per address carries all
    concurrent requests — long-polls and control traffic interleave."""

    def __init__(self, timeout: float = 10.0, ssl_context=None):
        self.timeout = timeout
        # Optional TLS: wraps each pooled conn at dial; with
        # check_hostname the context verifies the host part of the addr.
        self._ssl_context = ssl_context
        self._lock = threading.Lock()
        self._conns: Dict[str, _MuxConn] = {}
        self._seq = 0

    def call(self, addr: str, method: str, args: dict,
             timeout: Optional[float] = None) -> Any:
        """RPC to addr; raises RemoteError for handler errors, RPCError for
        transport failures (after invalidating the pooled conn). A per-call
        timeout does NOT kill the shared connection — the late response is
        simply dropped by the demuxer."""
        fault = faults.fire("rpc.send", target=f"{addr} {method}")
        if fault is not None:
            if fault.mode in ("drop", "partition"):
                # The frame never goes out: provably undelivered, so the
                # injected failure is retry-safe exactly like a connect
                # failure (the distinction callers' retry policies key on).
                raise RPCUndeliveredError(
                    f"injected fault: rpc.send to {addr} dropped"
                )
            if fault.mode == "error":
                raise RPCError(f"injected fault: rpc.send to {addr}")
        mux = self._acquire(addr)
        with self._lock:
            self._seq += 1
            seq = self._seq
        waiter = mux.register(seq)
        try:
            with mux.write_lock:
                _send_frame(mux.sock, {"seq": seq, "method": method,
                                       "args": args})
        except (ConnectionError, OSError, ValueError) as e:
            mux.forget(seq)
            self._invalidate(addr, mux)
            # sendall raised -> the frame is incomplete -> the peer never
            # dispatched it: undelivered, retryable.
            raise RPCUndeliveredError(f"rpc to {addr} failed: {e}") from e
        if not waiter.event.wait(timeout or self.timeout):
            mux.forget(seq)
            raise RPCTimeoutError(f"rpc to {addr} timed out: {method}")
        resp = waiter.resp
        if resp is None:  # reader died: transport failure
            self._invalidate(addr, mux)
            raise RPCError(f"rpc to {addr} failed: {mux.dead}")
        if resp.get("error"):
            raise RemoteError(resp["error"])
        return resp.get("result")

    def call_retry(self, addr: str, method: str, args: dict,
                   timeout: Optional[float] = None, retries: int = 2,
                   backoff=None):
        """``call`` with the transport tier's one safe auto-retry: only
        RPCUndeliveredError (the handler provably never ran, rpc.py:78-83)
        is replayed, under jittered backoff (or a caller-supplied
        ``backoff`` — a severed-conn single replay wants no sleep at all).
        RPCTimeoutError and lost responses surface immediately — the
        request may have executed, and redelivery belongs to the caller's
        idempotency machinery (broker nacks, raft-upsert semantics)."""
        from nomad_tpu.backoff import retry_undelivered

        return retry_undelivered(
            lambda: self.call(addr, method, args, timeout=timeout),
            retries=retries, backoff=backoff,
        )

    def _acquire(self, addr: str) -> _MuxConn:
        with self._lock:
            mux = self._conns.get(addr)
            if mux is not None and mux.dead is None:
                return mux
        host, port = addr.rsplit(":", 1)
        try:
            sock = socket.create_connection((host, int(port)), timeout=self.timeout)
            if self._ssl_context is not None:
                sock = self._ssl_context.wrap_socket(
                    sock, server_hostname=host
                )
        except (OSError, ValueError) as e:
            # A failed TLS handshake never dispatched anything either.
            raise RPCUndeliveredError(
                f"failed to connect to {addr}: {e}"
            ) from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Kernel send timeout bounds sendall on a peer that stopped
        # reading (the write_lock holder must never block forever);
        # per-call deadlines are enforced by the waiter, and the demux
        # reader blocks on recv by design.
        sock.settimeout(None)
        _set_send_timeout(sock, SEND_TIMEOUT)
        mux = _MuxConn(sock, addr)
        with self._lock:
            existing = self._conns.get(addr)
            if existing is not None and existing.dead is None:
                # Lost the connect race: hard-close so the loser's already-
                # running reader thread unblocks and exits.
                _hard_close(sock)
                return existing
            self._conns[addr] = mux
        return mux

    def _invalidate(self, addr: str, mux: Optional[_MuxConn] = None) -> None:
        with self._lock:
            current = self._conns.get(addr)
            if mux is None or current is mux:
                self._conns.pop(addr, None)
                mux = current
        if mux is not None:
            _hard_close(mux.sock)

    def shutdown(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for mux in conns:
            _hard_close(mux.sock)
