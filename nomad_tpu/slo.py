"""SLO layer: declarative latency objectives, burn rates, live monitoring.

ROADMAP item 5 names the target — p95 submit→placed < 250ms — but until
now nothing in the agent *watched* it: the artifacts measured plan
latency per run and no live surface said "are we inside the objective
right now, and how fast is the error budget burning?". This module adds
that surface:

- **Objectives** are declared in agent config (``telemetry { slo {
  submit_to_placed_p95_ms = 250 } }``) or ``ServerConfig.slo_objectives``;
  the spelling ``<metric>_p<NN>_ms = <threshold>`` is parsed into
  (metric, percentile objective, threshold).
- **Samples** come from the server's own event stream, not from new
  hot-path instruments: an :class:`SLOMonitor` thread tails the FSM's
  event broker (``EvalUpdated(pending)`` → ``PlanApplied`` →
  ``AllocClientUpdated(running)``) and computes submit→placed /
  submit→running per eval — read-only on decisions by construction, the
  same posture as the lifecycle stitcher.
- **Error budgets** ride :class:`telemetry.BurnRateWindow`: each sample
  is good iff it lands under the threshold; the objective percentile is
  the budget (p95 → 5% of samples may be bad per window).
- **Exposition**: ``/v1/agent/slo`` serves :meth:`SLOMonitor.snapshot`;
  the monitor also publishes ``slo.<name>.burn_rate`` /
  ``slo.<name>.budget_remaining`` gauges and a ``slo.<name>.breach``
  counter through the ordinary telemetry sink, so the Prometheus scrape
  carries them with zero extra wiring.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional

from nomad_tpu import structs, telemetry

# The metrics an objective may bind to. submit_to_placed is Sparrow's
# headline cut to durable placement; submit_to_running extends through the
# client ack (PAPERS.md); express_placed is the express lane's in-line
# submit→placed latency (server/express.py — sampled from ExpressPlaced
# events' placed_ms payload, the lane's own clock: PlanApplied lands
# asynchronously and would measure the commit, not the placement).
METRICS = ("submit_to_placed", "submit_to_running", "express_placed")

# Default objectives when none are configured: the ROADMAP item-5 target
# plus a looser end-to-end bound through the client ack.
DEFAULT_OBJECTIVES: Dict[str, float] = {
    "submit_to_placed_p95_ms": 250.0,
    "submit_to_running_p95_ms": 1000.0,
}

# The express lane's target (ROADMAP item 4: p50 submit→placed < 1ms for
# express-eligible tasks at steady-10k). Merged over the defaults when a
# server runs with the lane enabled and no explicit objective set; NOT
# part of DEFAULT_OBJECTIVES — a lane-off server must keep its exact
# pre-express objective surface.
EXPRESS_OBJECTIVES: Dict[str, float] = {
    "express_placed_p50_ms": 1.0,
}

# Scenario-scoped objectives: SIMLOAD families whose CONTRACT is not the
# default cell SLO. The gate (tools/bench_watch.py) and the scenario
# runner's in-artifact slo_check both consult this table by scenario
# name, so a banked artifact and its CI verdict can never disagree about
# which promise was being judged.
#
# - churn-fragmentation (and its tier-1 smoke): the scenario's claim is
#   the capacity/stranding trajectory, and its probe wave INTENTIONALLY
#   races a ~9000-alloc deregistration stop storm — the p95 tail is the
#   storm, not placement health. The scenario-scoped bound (1s) catches
#   a real regression (the r13 bank's p95 is ~455ms) without pretending
#   the run ever promised the 250ms steady-state SLO.
# - restart-under-load (and its smoke): evals caught mid-flight by the
#   leader kill wait out the downtime (~1-3s: re-election + snapshot
#   restore + log replay) and THEN place — survival and recovery speed
#   are the contract (the recovery gate judges those), so the placed
#   bound absorbs the declared downtime.
# - read-storm (and its smoke): the leader's HTTP front end serves an
#   impolite read fleet BY DESIGN while the steady-10k write load
#   places — the GIL contention between serving and planning is the
#   number the artifact banks (plan p50 under read pressure), and the
#   read lanes themselves are judged by bench_watch's read gate. The
#   1s placed bound catches a real write-path regression without
#   pretending the run ever promised the uncontended 250ms SLO.
SCENARIO_OBJECTIVES: Dict[str, Dict[str, float]] = {
    "churn-fragmentation": {**DEFAULT_OBJECTIVES,
                            "submit_to_placed_p95_ms": 1000.0},
    "churn-frag-200": {**DEFAULT_OBJECTIVES,
                       "submit_to_placed_p95_ms": 1000.0},
    "restart-under-load": {**DEFAULT_OBJECTIVES,
                           "submit_to_placed_p95_ms": 15000.0},
    "restart-800": {**DEFAULT_OBJECTIVES,
                    "submit_to_placed_p95_ms": 15000.0},
    # The read-storm families run a REPLICATED 3-member cell since the
    # follower read plane (r19): every plan is one raft entry fsynced
    # and replicated on the 100ms heartbeat cadence, under election
    # timeouts widened to 2.5-5s for digest determinism — placement
    # p95 is replication-dominated (~3s observed), not scheduler-bound.
    # The bound catches a pile-up regression on top of that floor; the
    # read-lane gate separately holds the leader's plan p50 to the
    # leader-only contrast arm.
    "read-storm": {**DEFAULT_OBJECTIVES,
                   "submit_to_placed_p95_ms": 5000.0},
    "read-storm-800": {**DEFAULT_OBJECTIVES,
                       "submit_to_placed_p95_ms": 5000.0},
    # Chaos families (nomad_tpu/simcluster/chaos.py; the specs declare
    # the SAME bounds and register() re-merges them — declared here too
    # so a process that never imports the chaos compiler, like the
    # bench_watch slo-gate scan, judges the banked artifacts against
    # the declared bounds, and test_chaos.py pins the two in sync):
    # - rack-failure drains a 256-job full-node fill through ONE
    #   scheduler worker (determinism) — the fill's serial queue
    #   backlog IS the p95, and the chaos gate separately judges the
    #   expiry->re-placement quantiles the family actually promises.
    # - partition-flap drops the leader's append stream half of every
    #   flap period BY DESIGN — commit stalls during the storm are the
    #   scenario's point; the bound catches a real scheduling
    #   regression on top of the declared partition stalls.
    # - follower-crash-rejoin runs a 2-worker raft cell while a
    #   chunked snapshot streams to the rejoining follower; plans
    #   queued behind the kill/restart window wait it out.
    "rack-failure": {**DEFAULT_OBJECTIVES,
                     "submit_to_placed_p95_ms": 15000.0},
    "partition-flap": {**DEFAULT_OBJECTIVES,
                       "submit_to_placed_p95_ms": 5000.0},
    "follower-crash-rejoin": {**DEFAULT_OBJECTIVES,
                              "submit_to_placed_p95_ms": 5000.0},
}

# Read-lane objectives (ROADMAP item 2's follower read plane): not
# latency-percentile objectives — contract checks on the consistency
# lanes a read-carrying artifact banks in its ``reads.lanes`` section.
# Judged offline by evaluate_read_lanes (the bench_watch read-lane
# gate), never by the live SLOMonitor: the lanes' promises (bound
# honored, share served by followers, zero linearizable violations) are
# per-run invariants, not rolling budgets.
READ_LANE_OBJECTIVES: Dict[str, float] = {
    # Followers must absorb at least this share of lane-entered reads
    # when the plane is on and the cell has followers to serve.
    "follower_serve_share_min": 0.80,
    # Served stale ages must sit inside the client bound: observed
    # stale-age p95 / bound must stay <= this ratio (1.0 = the bound
    # itself — the refusal path keeps anything past it off the books).
    "stale_age_p95_bound_ratio_max": 1.0,
    # Linearizable-lane responses observed with applied < read index.
    "linear_violations_max": 0.0,
    # Read responses missing the freshness stamp (every stale answer
    # must carry last-applied index + age — the acceptance contract).
    "stamp_missing_max": 0.0,
}


_NAME_RE = re.compile(r"^(?P<metric>[a-z_]+)_p(?P<pct>\d{1,2})_ms$")


@dataclass(frozen=True)
class Objective:
    """One parsed objective: ``percentile`` of ``metric`` samples must
    land at or under ``threshold_ms`` over the rolling window."""

    name: str
    metric: str
    percentile: float
    threshold_ms: float
    window_s: float = 3600.0

    @classmethod
    def parse(cls, name: str, threshold_ms: float,
              window_s: float = 3600.0) -> "Objective":
        m = _NAME_RE.match(name)
        if m is None:
            raise ValueError(
                f"SLO objective {name!r} must look like "
                "<metric>_p<NN>_ms (e.g. submit_to_placed_p95_ms)"
            )
        metric = m.group("metric")
        if metric not in METRICS:
            raise ValueError(
                f"SLO metric {metric!r} unknown (have: {METRICS})"
            )
        pct = int(m.group("pct"))
        if not 1 <= pct <= 99:
            raise ValueError(f"SLO percentile must be in [1, 99], got {pct}")
        threshold = float(threshold_ms)
        if threshold <= 0:
            raise ValueError(f"SLO threshold must be positive, got {threshold}")
        return cls(name=name, metric=metric, percentile=pct / 100.0,
                   threshold_ms=threshold, window_s=window_s)


def parse_objectives(spec: Optional[Dict[str, float]],
                     window_s: float = 3600.0) -> List[Objective]:
    """Config block -> objective list; None/empty means the defaults."""
    items = spec if spec else DEFAULT_OBJECTIVES
    return [Objective.parse(name, ms, window_s)
            for name, ms in sorted(items.items())]


class _Tracker:
    """One objective's rolling accounting: burn-rate window + a bounded
    reservoir so the snapshot reports the observed percentile next to
    the target."""

    __slots__ = ("objective", "window", "sample")

    def __init__(self, objective: Objective):
        self.objective = objective
        self.window = telemetry.BurnRateWindow(
            window_s=objective.window_s, objective=objective.percentile,
        )
        self.sample = telemetry.AggregateSample()

    def record(self, value_ms: float) -> bool:
        good = value_ms <= self.objective.threshold_ms
        self.window.record(good)
        self.sample.ingest(value_ms)
        return good

    def reset(self) -> None:
        """Fresh window + reservoir (the monitor's warmup boundary)."""
        o = self.objective
        self.window = telemetry.BurnRateWindow(
            window_s=o.window_s, objective=o.percentile,
        )
        self.sample = telemetry.AggregateSample()

    def snapshot(self) -> Dict[str, Any]:
        o = self.objective
        stats = self.window.stats()
        quantiles = self.sample.quantiles()
        return {
            "name": o.name,
            "metric": o.metric,
            "percentile": o.percentile,
            "threshold_ms": o.threshold_ms,
            "observed": {
                "count": self.sample.count,
                "max_ms": round(self.sample.max, 2),
                **{k: round(v, 2) for k, v in quantiles.items()},
            },
            # Inside the objective iff the bad fraction stays within the
            # budget the percentile grants.
            "met": stats["burn_rate"] <= 1.0,
            **stats,
        }


class SLOMonitor(threading.Thread):
    """Tails one server's event broker and keeps the SLO books.

    Deliberately a CONSUMER of the bounded event ring rather than a
    hot-path hook: the control plane publishes exactly what it published
    before (SIMLOAD event digests pin this), and a wedged monitor can
    never block an apply. The cost of that posture is honesty about
    loss: if the monitor ever falls further behind than the ring, the
    gap is counted (``truncated_gaps``), not silently absorbed."""

    # Bounded pending/placed maps: an eval that never places (or whose
    # running ack never arrives) must not leak forever.
    MAX_TRACKED = 8192

    def __init__(self, broker, objectives: Optional[Dict[str, float]] = None,
                 window_s: float = 3600.0, poll_interval: float = 0.25):
        super().__init__(daemon=True, name="slo-monitor")
        self.broker = broker
        self.trackers = [_Tracker(o)
                         for o in parse_objectives(objectives, window_s)]
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # Serializes whole drain-and-record passes (poll) against the
        # warmup-boundary wipe (reset): without it a concurrent poll
        # could fetch warmup events BEFORE the wipe and record them
        # AFTER, leaking exactly the sample reset() exists to exclude.
        self._poll_lock = threading.Lock()
        self._cursor = 0
        # eval id -> EvalUpdated(pending) wall stamp / PlanApplied stamp.
        self._pending: "Dict[str, float]" = {}
        self._placed: "Dict[str, float]" = {}
        # Insertion-ordered dedup table (value unused): evals whose
        # running transition is already counted. A dict, not a set, so
        # overflow evicts oldest-first like the other tables — wiping it
        # would let every later alloc ack of an already-counted eval
        # re-record an inflated submit_to_running sample.
        self._running_seen: "Dict[str, bool]" = {}
        self.samples = {m: telemetry.AggregateSample() for m in METRICS}
        self.truncated_gaps = 0
        # Warmup boundary accounting (reset()): how many times the books
        # were wiped and how many samples each wipe discarded — honesty
        # about what the live monitor is NOT counting.
        self.resets = 0
        self.reset_excluded = 0

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            self.poll()
        self.poll()  # final drain so short-lived servers still account

    def poll(self) -> None:
        with self._poll_lock:
            latest, events, truncated = self.broker.events_after(
                self._cursor)
            if truncated and self._cursor:
                self.truncated_gaps += 1
                telemetry.incr_counter(("slo", "monitor", "truncated_gap"))
            self._cursor = latest
            if events:
                self.observe(events)

    # -- accounting ----------------------------------------------------------

    def observe(self, events: Iterable) -> None:
        """Feed a batch of events (Event objects) through the lifecycle
        accounting. Separated from the thread loop so tests drive it
        synchronously with synthetic streams."""
        with self._lock:
            for e in events:
                if e.topic == "Eval" and e.type == "EvalUpdated":
                    if (e.payload.get("status")
                            == structs.EVAL_STATUS_PENDING
                            and e.key not in self._pending
                            and e.key not in self._placed):
                        self._pending[e.key] = e.time
                        self._evict_locked(self._pending)
                elif e.topic == "Plan" and e.type == "PlanApplied":
                    t0 = self._pending.pop(e.key, None)
                    if t0 is not None and e.key not in self._placed:
                        self._placed[e.key] = t0
                        self._evict_locked(self._placed)
                        self._record_locked(
                            "submit_to_placed", (e.time - t0) * 1000.0
                        )
                elif e.topic == "Express" and e.type == "ExpressPlaced":
                    # The express lane's in-line placement latency rides
                    # the event payload (the async PlanApplied would
                    # measure the commit, not the sub-ms placement).
                    ms = e.payload.get("placed_ms")
                    if ms is not None:
                        self._record_locked("express_placed", float(ms))
                elif e.topic == "Alloc" and e.type == "AllocClientUpdated":
                    ev_id = e.payload.get("eval_id", "")
                    if (ev_id
                            and e.payload.get("client_status")
                            == structs.ALLOC_CLIENT_STATUS_RUNNING
                            and ev_id not in self._running_seen):
                        t0 = self._placed.get(ev_id)
                        if t0 is not None:
                            self._running_seen[ev_id] = True
                            self._evict_locked(self._running_seen)
                            self._record_locked(
                                "submit_to_running", (e.time - t0) * 1000.0
                            )
            self._publish_gauges_locked()

    def reset(self) -> None:
        """Drop every sample and error-budget window accumulated so far
        (counted — ``resets``/``reset_excluded`` surface in snapshot()).
        The scenario runner calls this at the warmup boundary so the
        live monitor judges the measured window's steady state: without
        it, warmup's cold-compile evaluations burn the error budget and
        ``/v1/agent/slo`` reports a breach the steady state never had
        (the PR 8 documented caveat). Drains the event ring first so a
        warmup eval whose events are still unpolled can't leak across
        the boundary; serialized with poll() so an in-flight drain can
        never record pre-boundary events after the wipe."""
        with self._poll_lock:
            # Drain under the poll lock ONLY (the broker lock must not
            # nest inside the monitor lock — poll()'s observe() orders
            # them broker-then-monitor), then wipe under the monitor
            # lock.
            latest, _events, _trunc = self.broker.events_after(
                self._cursor)
            self._cursor = latest
            self._reset_books_locked()

    def _reset_books_locked(self) -> None:
        with self._lock:
            excluded = sum(agg.count for agg in self.samples.values())
            self.resets += 1
            self.reset_excluded += excluded
            for tr in self.trackers:
                tr.reset()
            self.samples = {m: telemetry.AggregateSample()
                            for m in METRICS}
            self._pending.clear()
            self._placed.clear()
            self._running_seen.clear()
            self._publish_gauges_locked()

    def _evict_locked(self, table: Dict[str, Any]) -> None:
        # Oldest-inserted eviction (dict preserves insertion order): an
        # abandoned eval costs one slot, never unbounded growth.
        while len(table) > self.MAX_TRACKED:
            table.pop(next(iter(table)))

    def _record_locked(self, metric: str, value_ms: float) -> None:
        self.samples[metric].ingest(value_ms)
        telemetry.add_sample(("slo", metric), value_ms)
        for tr in self.trackers:
            if tr.objective.metric == metric:
                if not tr.record(value_ms):
                    telemetry.incr_counter(
                        ("slo", tr.objective.name, "breach")
                    )

    def _publish_gauges_locked(self) -> None:
        for tr in self.trackers:
            stats = tr.window.stats()
            telemetry.set_gauge(
                ("slo", tr.objective.name, "burn_rate"),
                stats["burn_rate"],
            )
            telemetry.set_gauge(
                ("slo", tr.objective.name, "budget_remaining"),
                stats["budget_remaining_fraction"],
            )

    # -- exposition ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``/v1/agent/slo`` body: every objective's target vs
        observed percentiles, budget state, burn rate; plus the raw
        per-metric sample aggregates."""
        with self._lock:
            objectives = [tr.snapshot() for tr in self.trackers]
            samples = {
                m: {
                    "count": agg.count,
                    "mean_ms": round(agg.mean, 2),
                    "max_ms": round(agg.max, 2),
                    **{k: round(v, 2) for k, v in agg.quantiles().items()},
                }
                for m, agg in self.samples.items()
            }
            return {
                "objectives": objectives,
                "samples": samples,
                "pending_evals": len(self._pending),
                "truncated_gaps": self.truncated_gaps,
                "resets": self.resets,
                "reset_excluded": self.reset_excluded,
            }

    def burn_rate(self, metric: str = "submit_to_placed") -> float:
        """Worst (max) error-budget burn rate over the objectives bound
        to ``metric`` — the admission front door's shed signal
        (server/admission.py): >1.0 means the budget runs out before the
        window does. 0.0 with no matching objective."""
        with self._lock:
            return max(
                (tr.window.stats()["burn_rate"] for tr in self.trackers
                 if tr.objective.metric == metric),
                default=0.0,
            )

    def summary(self) -> Dict[str, Any]:
        """Compact agent-info line: objective name -> met/burn_rate."""
        with self._lock:
            return {
                tr.objective.name: {
                    "met": tr.window.stats()["burn_rate"] <= 1.0,
                    "burn_rate": tr.window.stats()["burn_rate"],
                    "count": tr.sample.count,
                }
                for tr in self.trackers
            }


def evaluate_artifact(attribution: Dict[str, Any],
                      objectives: Optional[Dict[str, float]] = None,
                      ) -> List[Dict[str, Any]]:
    """Offline check of a SIMLOAD ``latency_attribution`` section against
    objectives (the bench_watch / CI gate path): for each objective,
    compare the artifact's observed percentile of the metric against the
    threshold. Artifact percentiles come at fixed cuts (p50/p95/p99) —
    an objective at another percentile is checked against the next
    STRICTER recorded cut (conservative, never lenient)."""
    out: List[Dict[str, Any]] = []
    cuts = (0.50, 0.95, 0.99)
    for o in parse_objectives(objectives):
        block = attribution.get(o.metric + "_ms") or {}
        stricter = [c for c in cuts if c >= o.percentile]
        cut = min(stricter) if stricter else max(cuts)
        observed = block.get(f"p{int(cut * 100)}_ms")
        n = block.get("n", 0)
        met = None if (observed is None or not n) else observed <= o.threshold_ms
        out.append({
            "objective": o.name,
            "threshold_ms": o.threshold_ms,
            "checked_percentile": cut,
            "observed_ms": observed,
            "n": n,
            "met": met,
        })
    return out


def evaluate_read_lanes(artifact: Dict[str, Any],
                        objectives: Optional[Dict[str, float]] = None,
                        ) -> List[Dict[str, Any]]:
    """Offline check of a SIMLOAD artifact's ``reads.lanes`` section
    against the read-lane objectives (the bench_watch read-lane gate
    path). Empty when the artifact never ran the read plane (no lanes
    section, or ``enabled: false`` — the leader-only contrast arm):
    the lane contract can only be judged where lanes were served."""
    lanes = ((artifact.get("reads") or {}).get("lanes")) or {}
    if not lanes.get("enabled"):
        return []
    obj = dict(READ_LANE_OBJECTIVES)
    obj.update(objectives or {})
    rows: List[Dict[str, Any]] = []

    def row(name: str, threshold: float, observed, met) -> None:
        rows.append({"objective": name, "threshold": threshold,
                     "observed": observed, "met": met})

    share = lanes.get("follower_serve_share")
    # A single-member cell has no followers to serve; the share
    # objective only binds where the cell could route around the leader.
    members = int(lanes.get("members", 1) or 1)
    row("follower_serve_share",
        obj["follower_serve_share_min"], share,
        None if (share is None or members <= 1)
        else share >= obj["follower_serve_share_min"])

    bound = lanes.get("stale_bound_ms")
    age_p95 = (lanes.get("stale_age_ms") or {}).get("p95")
    ratio = (None if (bound is None or age_p95 is None or not bound)
             else age_p95 / float(bound))
    row("stale_age_p95_bound_ratio",
        obj["stale_age_p95_bound_ratio_max"],
        None if ratio is None else round(ratio, 4),
        None if ratio is None
        else ratio <= obj["stale_age_p95_bound_ratio_max"])

    for name, key in (("linear_violations", "linear_violations"),
                      ("stamp_missing", "stamp_missing")):
        observed = lanes.get(key)
        row(name, obj[name + "_max"], observed,
            None if observed is None
            else observed <= obj[name + "_max"])
    return rows
