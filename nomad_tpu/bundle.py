"""Operator debug bundle: the one-shot flight recorder.

Upstream analog: ``nomad operator debug`` (Nomad 1.0), which captures an
archive of API state, metrics, and pprof profiles from a live cluster so
an operator can attach ONE artifact to a bug report instead of a
transcript of curl commands. This module builds the single-JSON version:
everything the observability stack retains at the moment of capture —

- ``metrics``     InmemSink interval dump + process-lifetime cumulative
                  counters/sample-summaries (with reservoir quantiles)
- ``traces``      tracer summaries, plus full span trees for the most
                  recently updated traces
- ``events``      last-K events from the agent's cluster event stream
                  (nomad_tpu.events) — or, with no agent, from every
                  broker live in the process
- ``config``      the effective agent config, secrets redacted
- ``faults``      the armed fault plan + per-rule fire counts
- ``breaker``     device circuit-breaker state (scheduler.DEVICE_BREAKER)
- ``mirror``      device-mirror cache stats (hits/misses, delta_rolls vs
                  full_rebuilds, rows_restaged) — whether the solver's
                  staging is riding the delta path or rebuilding
- ``plan_pipeline``  optimistic plan-pipeline totals (batches/plans,
                  committed vs conflicts, fused vs scalar verifies) —
                  whether the apply path is batching and how contended
                  the optimistic concurrency is
- ``slo``         the live SLO snapshot (nomad_tpu.slo): objectives vs
                  observed percentiles, error budgets, burn rates
- ``admission``   the admission front door (nomad_tpu/server/admission):
                  decision counters, per-client rate lanes, recent typed
                  rejections, SLO-shed coupling
- ``capacity``    the capacity observatory (nomad_tpu/capacity.py):
                  utilization, bin-pack density, per-lane usage,
                  fragmentation histograms, stranded-capacity % — the
                  utilization picture a postmortem needs
- ``reads``       the read-path observatory (nomad_tpu/read_observe.py):
                  per-endpoint serving attribution (lane split, blocking
                  hold/serve partition, SSE session books), watch-registry
                  wake economy, and the freshness/staleness distribution —
                  what the follower read path was doing at capture time
- ``profile``     the continuous sampling profiler
                  (nomad_tpu/profile_observe.py): collapsed-stack
                  aggregates and per-thread-role wall shares — where the
                  process was spending its time at capture
- ``runtime``     the runtime economy ledgers (same module): the
                  lock-contention table when telemetry{lock_watchdog}
                  is on, and the byte-economy ledger — mirror buffers by
                  bucket x dtype with the projected 1M-node footprint,
                  bounded rings, state store, RSS
- ``solver``      the device-solve efficiency panel (tpu/solver.py):
                  padding waste, bucket occupancy, compile attribution,
                  device-time-per-placement
- ``timelines``   the worst-K slowest submit→placed lifecycle timelines
                  (nomad_tpu.lifecycle) stitched from the retained spans
                  and event ring — where the tail's time went
- ``threads``     Python stacks of every live thread (sys._current_frames
                  — the goroutine-dump analog)

Served by ``/v1/agent/debug/bundle`` (debug-gated, like the rest of the
introspection surface) and fetched by ``tools/debug_bundle.py``;
``tools/tier1.py`` writes a process-local bundle next to the junitxml
when a suite run goes red.

Redaction rule: any config key whose name contains ``token``, ``secret``,
or ``password`` (case-insensitive) is replaced with ``<redacted>`` when
set. Paths (cert/key files) are locations, not credentials, and stay.
"""

from __future__ import annotations

import json
import sys
import time
import traceback
from typing import Any, Dict, List, Optional

BUNDLE_FORMAT = "nomad-tpu-debug-bundle/v1"

# Sections every bundle carries (tests assert this schema; a consumer can
# rely on the keys existing even when a subsystem was not running — the
# value is then None or an {"error": ...} stub, never absent).
BUNDLE_SECTIONS = (
    "format", "captured_at", "metrics", "traces", "events", "config",
    "faults", "breaker", "mirror", "plan_pipeline", "slo", "admission",
    "express", "capacity", "raft", "reads", "profile", "runtime",
    "solver", "timelines", "nomadlint", "threads",
)

# Every `python -m tools.nomadlint` run writes its full report here; the
# bundle embeds it so a red tier-1 run records what the static gate saw
# without re-running the analysis in-process.
NOMADLINT_REPORT_PATH = "/tmp/nomadlint_report.json"

_SECRET_MARKERS = ("token", "secret", "password")

# Full span trees for at most this many most-recent traces: summaries are
# cheap, span trees are the expensive part of the tracer dump.
MAX_FULL_TRACES = 8


def redact_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Redact credential-bearing values; coerce everything else to
    JSON-able primitives (non-primitive objects stringify)."""
    out: Dict[str, Any] = {}
    for key, value in config.items():
        lowered = key.lower()
        if any(m in lowered for m in _SECRET_MARKERS) and value:
            out[key] = "<redacted>"
        elif value is None or isinstance(value, (bool, int, float, str)):
            out[key] = value
        elif isinstance(value, dict):
            out[key] = redact_config(value)
        elif isinstance(value, (list, tuple)):
            out[key] = [v if isinstance(v, (bool, int, float, str))
                        else str(v) for v in value]
        else:
            out[key] = str(value)
    return out


def thread_stacks(depth: int = 12) -> Dict[str, List[str]]:
    """Formatted stacks of every live thread, keyed by thread name — the
    first thing needed when an agent wedges. Duplicate names (an
    in-process multi-server cluster runs several ``worker-0``s) get an
    ``#ident`` suffix instead of silently shadowing each other."""
    import threading

    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, str(ident))
        key = name if name not in out else f"{name}#{ident}"
        out[key] = traceback.format_stack(frame)[-depth:]
    return out


def _metrics_section() -> Optional[Dict[str, Any]]:
    from nomad_tpu import telemetry

    sink = telemetry.get_global().sink
    if not isinstance(sink, telemetry.InmemSink):
        sink = next(
            (s for s in getattr(sink, "sinks", [])
             if isinstance(s, telemetry.InmemSink)),
            None,
        )
    if sink is None:
        return None
    counters, samples = sink.cumulative()
    return {
        "intervals": sink.data(),
        "cumulative": {"counters": counters, "samples": samples},
    }


def _traces_section() -> Dict[str, Any]:
    from nomad_tpu import trace

    tracer = trace.get_tracer()
    summaries = tracer.traces()
    return {
        "summaries": summaries,
        "spans": {
            s["trace_id"]: tracer.get_trace(s["trace_id"])
            for s in summaries[:MAX_FULL_TRACES]
        },
    }


def _events_section(agent, last_events: int) -> List[Dict[str, Any]]:
    from nomad_tpu import events as events_mod

    brokers = []
    server = getattr(agent, "server", None) if agent is not None else None
    if server is not None and getattr(server, "fsm", None) is not None:
        brokers = [server.fsm.events]
    else:
        # Process-local capture: whatever brokers are alive right now.
        with events_mod._brokers_lock:
            brokers = list(events_mod._BROKERS)
    out: List[Dict[str, Any]] = []
    for broker in brokers:
        out.extend(e.to_dict() for e in broker.all_events())
    out.sort(key=lambda e: (e["time"], e["index"]))
    return out[-last_events:] if last_events else out


def _breaker_section() -> Dict[str, Any]:
    try:
        from nomad_tpu.scheduler import DEVICE_BREAKER

        return DEVICE_BREAKER.stats()
    except Exception as e:  # pragma: no cover - import-time breakage only
        return {"error": str(e)}


def _mirror_section() -> Dict[str, Any]:
    from nomad_tpu.tpu.mirror import GLOBAL_MIRROR_CACHE

    return GLOBAL_MIRROR_CACHE.stats()


def _plan_pipeline_section() -> Dict[str, Any]:
    from nomad_tpu.server.plan_pipeline import PIPELINE_TOTALS

    return PIPELINE_TOTALS.stats()


def _slo_section(agent) -> Optional[Dict[str, Any]]:
    """Live SLO snapshot from the agent's server (None without one, or
    with the monitor disabled)."""
    server = getattr(agent, "server", None) if agent is not None else None
    monitor = getattr(server, "slo_monitor", None)
    return monitor.snapshot() if monitor is not None else None


def _admission_section(agent) -> Optional[Dict[str, Any]]:
    """Admission front-door snapshot (nomad_tpu/server/admission.py):
    decision counters, rate-lane table, recent typed rejections — where
    a 'clients are getting 429s' report starts. None without a server."""
    server = getattr(agent, "server", None) if agent is not None else None
    admission = getattr(server, "admission", None)
    return admission.snapshot() if admission is not None else None


def _express_section(agent) -> Optional[Dict[str, Any]]:
    """Express-lane snapshot (nomad_tpu/server/express.py): placement/
    commit/bounce books, the reservation ledger, place-latency
    quantiles, recent committer outcomes. None without a server."""
    server = getattr(agent, "server", None) if agent is not None else None
    express = getattr(server, "express_lane", None)
    return express.snapshot() if express is not None else None


def _capacity_section(agent) -> Optional[Dict[str, Any]]:
    """Capacity observatory snapshot (nomad_tpu/capacity.py): a
    postmortem bundle must carry the utilization picture — whether the
    cell was full, fragmented, or stranding capacity when things went
    sideways. None without a server or with the observatory disabled."""
    server = getattr(agent, "server", None) if agent is not None else None
    acct = getattr(server, "capacity_accountant", None)
    if acct is None or not acct.config.enabled:
        return None
    acct.refresh()
    return acct.snapshot()


def _raft_section(agent) -> Optional[Dict[str, Any]]:
    """Raft & recovery observatory snapshot (nomad_tpu/raft_observe.py):
    a postmortem bundle must carry the replicated write path's books —
    per-entry stage costs, follower lag, log/snapshot economy, and
    whether (and how fast) this process recovered from a cold restart.
    None without a server or with the observatory disabled."""
    server = getattr(agent, "server", None) if agent is not None else None
    obs = getattr(server, "raft_observatory", None)
    if obs is None or not obs.config.enabled:
        return None
    obs.refresh()
    return obs.snapshot()


def _reads_section(agent) -> Optional[Dict[str, Any]]:
    """Read-path observatory snapshot (nomad_tpu/read_observe.py): the
    serving books a read-pressure postmortem needs — which routes were
    hot, how long blocking queries held vs served, whether SSE tails
    were lagging or truncating, and how stale the answers were. None
    without a server or with the observatory disabled."""
    server = getattr(agent, "server", None) if agent is not None else None
    obs = getattr(server, "read_observatory", None)
    if obs is None or not obs.config.enabled:
        return None
    obs.refresh()
    return obs.snapshot()


def _runtime_observatory(agent):
    server = getattr(agent, "server", None) if agent is not None else None
    obs = getattr(server, "runtime_observatory", None)
    if obs is None or not obs.config.enabled:
        return None
    return obs


def _profile_section(agent) -> Optional[Dict[str, Any]]:
    """Sampling-profiler view (nomad_tpu/profile_observe.py): the
    collapsed-stack aggregates and per-role wall shares at capture time
    — a bundle attached to a "the agent was slow" report carries its own
    profile. None without a server or with the observatory disabled."""
    obs = _runtime_observatory(agent)
    return obs.profile_view() if obs is not None else None


def _runtime_section(agent) -> Optional[Dict[str, Any]]:
    """Runtime economy ledgers (nomad_tpu/profile_observe.py): lock
    contention + the byte-economy ledger, refreshed at capture so the
    footprint numbers describe the process NOW."""
    obs = _runtime_observatory(agent)
    if obs is None:
        return None
    obs.refresh()
    return obs.runtime_view()


def _solver_section() -> Dict[str, Any]:
    """Device-solve efficiency panel (tpu/solver.py SOLVER_PANEL):
    padding economy, bucket occupancy, compile attribution — next to the
    mirror's delta-roll wall costs already in the ``mirror`` section."""
    from nomad_tpu.tpu.solver import SOLVER_PANEL

    return SOLVER_PANEL.snapshot()


# Worst-K slowest timelines embedded per bundle: summaries of the tail,
# not the whole run — a red tier-1 bundle must stay one readable JSON.
TIMELINE_WORST_K = 8


def _timelines_section(agent, last_events: int) -> List[Dict[str, Any]]:
    """Worst-K slowest submit→placed lifecycle timelines stitched from
    the same events the ``events`` section carries plus the retained
    traces (nomad_tpu.lifecycle) — the flight recorder answers 'where
    did the slow placements spend their time' directly."""
    from nomad_tpu import lifecycle

    events = _events_section(agent, last_events)
    timelines = lifecycle.stitch(events)
    return lifecycle.worst_k(timelines.values(), k=TIMELINE_WORST_K)


def _nomadlint_section() -> Optional[Dict[str, Any]]:
    """Most recent nomadlint report, if a gate run left one. None (not an
    error) when no lint run happened on this host — the section is about
    provenance, and an absent report is a fact worth recording as such."""
    import os

    try:
        with open(NOMADLINT_REPORT_PATH) as f:
            report = json.load(f)
        mtime = os.path.getmtime(NOMADLINT_REPORT_PATH)
    except (OSError, ValueError):
        return None
    # mtime + the report's own repo/generated_at stamps let a reader
    # detect a stale or foreign report (the path is host-global).
    return {"path": NOMADLINT_REPORT_PATH, "mtime": mtime, "report": report}


def collect(agent=None, last_events: int = 512) -> Dict[str, Any]:
    """Build the bundle. ``agent`` is a live nomad_tpu.agent.Agent for the
    full capture; None collects the process-local subset (metrics/faults/
    breaker/threads + any live event brokers) — the tier-1 red-run path."""
    from nomad_tpu import faults

    bundle: Dict[str, Any] = {
        "format": BUNDLE_FORMAT,
        # nomadlint: allow(DET002) -- user-facing capture timestamp on
        # an operator artifact; never used in interval arithmetic.
        "captured_at": time.time(),
        "metrics": None,
        "traces": None,
        "events": [],
        "config": None,
        "faults": None,
        "breaker": None,
        "mirror": None,
        "plan_pipeline": None,
        "slo": None,
        "admission": None,
        "express": None,
        "capacity": None,
        "raft": None,
        "reads": None,
        "profile": None,
        "runtime": None,
        "solver": None,
        "timelines": [],
        "nomadlint": None,
        "threads": None,
    }
    for section, build in (
        ("metrics", _metrics_section),
        ("traces", _traces_section),
        ("events", lambda: _events_section(agent, last_events)),
        ("faults", lambda: faults.get_registry().snapshot()),
        ("breaker", _breaker_section),
        ("mirror", _mirror_section),
        ("plan_pipeline", _plan_pipeline_section),
        ("slo", lambda: _slo_section(agent)),
        ("admission", lambda: _admission_section(agent)),
        ("express", lambda: _express_section(agent)),
        ("capacity", lambda: _capacity_section(agent)),
        ("raft", lambda: _raft_section(agent)),
        ("reads", lambda: _reads_section(agent)),
        ("profile", lambda: _profile_section(agent)),
        ("runtime", lambda: _runtime_section(agent)),
        ("solver", _solver_section),
        ("timelines", lambda: _timelines_section(agent, last_events)),
        ("nomadlint", _nomadlint_section),
        ("threads", thread_stacks),
    ):
        # One wedged subsystem must not cost the whole flight recording.
        try:
            bundle[section] = build()
        except Exception as e:
            bundle[section] = {"error": str(e)}
    if agent is not None:
        try:
            bundle["config"] = redact_config(vars(agent.config))
        except Exception as e:
            bundle["config"] = {"error": str(e)}
    return bundle
