"""Agent configuration files: HCL/JSON parsing + merge semantics.

Reference: /root/reference/command/agent/config.go (624 LoC) — the agent
reads any number of config files/directories given with ``-config``; later
files override earlier ones field-by-field, maps merge key-by-key, and CLI
flags override files. Blocks: ports, addresses, advertise, client, server,
telemetry, atlas.

The HCL dialect is the same one job specs use, so this reuses
``nomad_tpu.jobspec.hcl``; ``.json`` files parse with the stdlib.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.jobspec.hcl import Body, parse as parse_hcl


@dataclass
class Ports:
    """config.go Ports block."""

    http: int = 4646
    rpc: int = 4647
    serf: int = 4648


@dataclass
class Addresses:
    """Bind overrides per subsystem (config.go Addresses block)."""

    http: str = ""
    rpc: str = ""
    serf: str = ""


@dataclass
class AdvertiseAddrs:
    """Addresses advertised to peers (config.go AdvertiseAddrs block)."""

    rpc: str = ""
    serf: str = ""


@dataclass
class ClientBlock:
    """config.go ClientConfig block."""

    enabled: bool = False
    state_dir: str = ""
    alloc_dir: str = ""
    servers: List[str] = field(default_factory=list)
    node_class: str = ""
    node_id: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    options: Dict[str, str] = field(default_factory=dict)
    network_interface: str = ""
    network_speed: int = 0


@dataclass
class ServerBlock:
    """config.go ServerConfig block, extended with the optimistic
    scheduling knob (``scheduler_workers`` is the first-class spelling of
    worker concurrency; ``num_schedulers`` the legacy alias; 0 = server
    default) and the admission/backpressure knobs
    (nomad_tpu/server/admission.py): ``eval_pending_cap`` bounds the
    broker's pending evals, ``plan_queue_cap`` the plan queue,
    ``max_blocking_watchers`` the blocking-query watcher registrations —
    all 0 = unbounded — and the ``admission { }`` sub-block configures
    per-client token-bucket rate lanes + SLO-coupled shedding::

        server {
          eval_pending_cap = 4096
          plan_queue_cap = 512
          max_blocking_watchers = 50000
          admission {
            client_rate = 10
            client_burst = 50
            shed_start_burn = 2.0
          }
        }
    """

    enabled: bool = False
    bootstrap_expect: int = 0
    data_dir: str = ""
    protocol_version: int = 0
    num_schedulers: int = 0
    scheduler_workers: int = 0
    eval_pending_cap: int = 0
    plan_queue_cap: int = 0
    max_blocking_watchers: int = 0
    admission: Optional[Dict[str, object]] = None
    # Express placement lane (nomad_tpu/server/express.py): the
    # ``express { }`` sub-block enables leader-local sub-millisecond
    # placement for express-flagged batch jobs under leased capacity
    # reservations. None = lane off (the default posture).
    express: Optional[Dict[str, object]] = None
    # Capacity observatory (nomad_tpu/capacity.py): the ``capacity { }``
    # sub-block tunes the read-only accountant behind
    # /v1/agent/capacity (poll/event cadence, reference shapes for the
    # stranded-capacity yardstick). None = defaults (enabled).
    capacity: Optional[Dict[str, object]] = None
    # Raft & recovery observatory (nomad_tpu/raft_observe.py): the
    # ``raft_observe { }`` sub-block tunes the read-only observer behind
    # /v1/agent/raft (poll/event cadence). None = defaults (enabled).
    raft_observe: Optional[Dict[str, object]] = None
    # Read-path observatory (nomad_tpu/read_observe.py): the
    # ``reads { }`` sub-block tunes the read-only observer behind
    # /v1/agent/reads (poll/event cadence). None = defaults (enabled).
    reads: Optional[Dict[str, object]] = None
    # Consistency-lane read plane (nomad_tpu/server/read_path.py): the
    # ``read_path { }`` sub-block tunes the SERVING-path lane machinery
    # (stale-lane default bound, linearizable read-index/apply-wait
    # timeouts). None = defaults (enabled).
    read_path: Optional[Dict[str, object]] = None
    # Runtime self-observatory (nomad_tpu/profile_observe.py): the
    # ``profile { }`` sub-block tunes the read-only observer behind
    # /v1/agent/profile and /v1/agent/runtime (sampling cadence/jitter/
    # seed, byte-ledger and event cadence). None = defaults (enabled).
    profile: Optional[Dict[str, object]] = None
    # Solver device mesh (nomad_tpu/parallel/mesh.py): the
    # ``solver_mesh { }`` sub-block shards the node axis of every device
    # solve over a JAX mesh — ``node_shards`` devices per eval row,
    # ``eval_parallel`` rows. None = single-device solves (the default;
    # decision-invariant — sharding only moves where the flops run).
    solver_mesh: Optional[Dict[str, object]] = None
    enabled_schedulers: List[str] = field(default_factory=list)
    start_join: List[str] = field(default_factory=list)


@dataclass
class Telemetry:
    """config.go Telemetry block, extended with eval-trace knobs
    (nomad_tpu.trace): ``trace_buffer_size`` bounds the completed-trace
    ring (0 = the default of 256), ``disable_tracing`` turns span
    recording off entirely, and ``event_buffer_size`` bounds the cluster
    event stream ring (nomad_tpu.events; 0 = the default of 2048).
    ``histogram_buckets`` overrides the fixed Prometheus histogram bucket
    bounds in ms (empty = telemetry.DEFAULT_HISTOGRAM_BUCKETS_MS); the
    ``slo { }`` sub-block declares latency objectives
    (``submit_to_placed_p95_ms = 250`` style, nomad_tpu.slo). Absent vs
    explicitly empty matters for ``slo``: no block (None) means the
    default objective set, an empty ``slo { }`` disables the monitor.
    ``lock_watchdog`` installs the telemetry.LockWatchdog at agent
    construction (BEFORE any server lock is built): runtime lock-order
    assertion plus per-site contention/hold timing, surfaced through
    /v1/agent/runtime and the ``nomad_lock_*`` metric family. Default
    off — wrapping costs a try-acquire per tracked acquisition."""

    statsite_address: str = ""
    statsd_address: str = ""
    disable_hostname: bool = False
    trace_buffer_size: int = 0
    disable_tracing: bool = False
    event_buffer_size: int = 0
    histogram_buckets: List[float] = field(default_factory=list)
    slo: Optional[Dict[str, float]] = None
    lock_watchdog: bool = False


@dataclass
class Atlas:
    """config.go AtlasConfig block. When ``endpoint`` is set the agent
    dials it and exposes the HTTP API over the tunnel
    (nomad_tpu.scada.UplinkProvider, ref command/agent/scada.go); without
    an explicit endpoint the uplink stays off — the reference's default
    points at a defunct third-party SaaS."""

    infrastructure: str = ""
    token: str = ""
    join: bool = False
    endpoint: str = ""


@dataclass
class FaultsBlock:
    """Deterministic fault-injection plan (nomad_tpu.faults) — a tpu-native
    extension with no reference analog. ``sites`` maps a site name
    (faults.SITES) to one rule mapping or a list of them::

        faults {
          seed = 42
          sites {
            "rpc.send" = { mode = "drop"  probability = 0.2 }
            "solver.execute" = { mode = "error"  count = 5 }
          }
        }

    Faults configured here arm at agent start; the debug-gated
    ``/v1/agent/faults`` endpoint reconfigures them live."""

    seed: int = 0
    sites: Dict[str, object] = field(default_factory=dict)


@dataclass
class TLSBlock:
    """TLS for the server RPC tier and the uplink tunnel (reference:
    nomad/tlsutil feeding the rpcTLS listener arm, nomad/rpc.go:104-110).
    ``uplink`` additionally wraps the dialed atlas tunnel."""

    enabled: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    verify_incoming: bool = True
    verify_hostname: bool = False
    uplink: bool = False


@dataclass
class FileConfig:
    """Full agent config-file surface (config.go Config struct)."""

    region: str = ""
    datacenter: str = ""
    name: str = ""
    data_dir: str = ""
    log_level: str = ""
    bind_addr: str = ""
    enable_debug: bool = False
    ports: Ports = field(default_factory=Ports)
    addresses: Addresses = field(default_factory=Addresses)
    advertise: AdvertiseAddrs = field(default_factory=AdvertiseAddrs)
    client: ClientBlock = field(default_factory=ClientBlock)
    server: ServerBlock = field(default_factory=ServerBlock)
    telemetry: Telemetry = field(default_factory=Telemetry)
    atlas: Atlas = field(default_factory=Atlas)
    tls: TLSBlock = field(default_factory=TLSBlock)
    faults: FaultsBlock = field(default_factory=FaultsBlock)
    leave_on_interrupt: bool = False
    leave_on_terminate: bool = False
    enable_syslog: bool = False
    syslog_facility: str = "LOCAL0"
    disable_update_check: bool = False
    scheduler_backend: str = ""  # tpu-native extension: 'tpu' | 'host'

    # -- merge ------------------------------------------------------------

    def merge(self, other: "FileConfig") -> "FileConfig":
        """Field-by-field override by ``other`` (config.go Merge): scalars
        override when set, maps/lists merge/extend, nested blocks recurse."""
        out = FileConfig()
        for name in (
            "region", "datacenter", "name", "data_dir", "log_level",
            "bind_addr", "syslog_facility", "scheduler_backend",
        ):
            setattr(out, name, getattr(other, name) or getattr(self, name))
        for name in (
            "enable_debug", "leave_on_interrupt", "leave_on_terminate",
            "enable_syslog", "disable_update_check",
        ):
            setattr(out, name, getattr(other, name) or getattr(self, name))

        out.ports = Ports(
            http=other.ports.http if other.ports.http != 4646 else self.ports.http,
            rpc=other.ports.rpc if other.ports.rpc != 4647 else self.ports.rpc,
            serf=other.ports.serf if other.ports.serf != 4648 else self.ports.serf,
        )
        out.addresses = Addresses(
            http=other.addresses.http or self.addresses.http,
            rpc=other.addresses.rpc or self.addresses.rpc,
            serf=other.addresses.serf or self.addresses.serf,
        )
        out.advertise = AdvertiseAddrs(
            rpc=other.advertise.rpc or self.advertise.rpc,
            serf=other.advertise.serf or self.advertise.serf,
        )
        out.client = ClientBlock(
            enabled=other.client.enabled or self.client.enabled,
            state_dir=other.client.state_dir or self.client.state_dir,
            alloc_dir=other.client.alloc_dir or self.client.alloc_dir,
            servers=self.client.servers + [
                s for s in other.client.servers if s not in self.client.servers
            ],
            node_class=other.client.node_class or self.client.node_class,
            node_id=other.client.node_id or self.client.node_id,
            meta={**self.client.meta, **other.client.meta},
            options={**self.client.options, **other.client.options},
            network_interface=(
                other.client.network_interface or self.client.network_interface
            ),
            network_speed=other.client.network_speed or self.client.network_speed,
        )
        out.server = ServerBlock(
            enabled=other.server.enabled or self.server.enabled,
            bootstrap_expect=(
                other.server.bootstrap_expect or self.server.bootstrap_expect
            ),
            data_dir=other.server.data_dir or self.server.data_dir,
            protocol_version=(
                other.server.protocol_version or self.server.protocol_version
            ),
            num_schedulers=other.server.num_schedulers or self.server.num_schedulers,
            scheduler_workers=(
                other.server.scheduler_workers or self.server.scheduler_workers
            ),
            eval_pending_cap=(
                other.server.eval_pending_cap or self.server.eval_pending_cap
            ),
            plan_queue_cap=(
                other.server.plan_queue_cap or self.server.plan_queue_cap
            ),
            max_blocking_watchers=(
                other.server.max_blocking_watchers
                or self.server.max_blocking_watchers
            ),
            # Admission knobs merge key-by-key like client.meta: a later
            # file overrides one knob without dropping the rest; None
            # means "no block here" and defers to the other layer.
            admission=(
                self.server.admission if other.server.admission is None
                else other.server.admission if self.server.admission is None
                else {**self.server.admission, **other.server.admission}
            ),
            # Express knobs merge key-by-key like admission: a later file
            # overrides one knob without dropping the rest.
            express=(
                self.server.express if other.server.express is None
                else other.server.express if self.server.express is None
                else {**self.server.express, **other.server.express}
            ),
            # Capacity knobs merge key-by-key like express/admission.
            capacity=(
                self.server.capacity if other.server.capacity is None
                else other.server.capacity if self.server.capacity is None
                else {**self.server.capacity, **other.server.capacity}
            ),
            # Raft-observatory knobs merge key-by-key like capacity.
            raft_observe=(
                self.server.raft_observe
                if other.server.raft_observe is None
                else other.server.raft_observe
                if self.server.raft_observe is None
                else {**self.server.raft_observe,
                      **other.server.raft_observe}
            ),
            # Read-observatory knobs merge key-by-key like capacity.
            reads=(
                self.server.reads
                if other.server.reads is None
                else other.server.reads
                if self.server.reads is None
                else {**self.server.reads, **other.server.reads}
            ),
            # Read-plane knobs merge key-by-key like the blocks above.
            read_path=(
                self.server.read_path
                if other.server.read_path is None
                else other.server.read_path
                if self.server.read_path is None
                else {**self.server.read_path, **other.server.read_path}
            ),
            # Runtime-observatory knobs merge key-by-key like capacity.
            profile=(
                self.server.profile
                if other.server.profile is None
                else other.server.profile
                if self.server.profile is None
                else {**self.server.profile, **other.server.profile}
            ),
            # Solver-mesh knobs merge key-by-key like the blocks above.
            solver_mesh=(
                self.server.solver_mesh if other.server.solver_mesh is None
                else other.server.solver_mesh
                if self.server.solver_mesh is None
                else {**self.server.solver_mesh, **other.server.solver_mesh}
            ),
            enabled_schedulers=(
                other.server.enabled_schedulers or self.server.enabled_schedulers
            ),
            start_join=self.server.start_join + [
                a for a in other.server.start_join
                if a not in self.server.start_join
            ],
        )
        out.telemetry = Telemetry(
            statsite_address=(
                other.telemetry.statsite_address or self.telemetry.statsite_address
            ),
            statsd_address=(
                other.telemetry.statsd_address or self.telemetry.statsd_address
            ),
            disable_hostname=(
                other.telemetry.disable_hostname or self.telemetry.disable_hostname
            ),
            trace_buffer_size=(
                other.telemetry.trace_buffer_size
                or self.telemetry.trace_buffer_size
            ),
            disable_tracing=(
                other.telemetry.disable_tracing
                or self.telemetry.disable_tracing
            ),
            event_buffer_size=(
                other.telemetry.event_buffer_size
                or self.telemetry.event_buffer_size
            ),
            histogram_buckets=(
                list(other.telemetry.histogram_buckets)
                or list(self.telemetry.histogram_buckets)
            ),
            # Objectives merge key-by-key like client.meta: a later file
            # overrides one objective's threshold without dropping the
            # rest of the set. None = no block (defaults apply); an
            # explicit empty block anywhere in the chain disables — so a
            # later `slo {}` must override, not vanish into the merge.
            slo=(
                self.telemetry.slo if other.telemetry.slo is None
                else other.telemetry.slo if (not other.telemetry.slo
                                             or self.telemetry.slo is None)
                else {**self.telemetry.slo, **other.telemetry.slo}
            ),
            lock_watchdog=(
                other.telemetry.lock_watchdog
                or self.telemetry.lock_watchdog
            ),
        )
        out.atlas = Atlas(
            infrastructure=other.atlas.infrastructure or self.atlas.infrastructure,
            token=other.atlas.token or self.atlas.token,
            join=other.atlas.join or self.atlas.join,
            endpoint=other.atlas.endpoint or self.atlas.endpoint,
        )
        out.tls = TLSBlock(
            enabled=other.tls.enabled or self.tls.enabled,
            ca_file=other.tls.ca_file or self.tls.ca_file,
            cert_file=other.tls.cert_file or self.tls.cert_file,
            key_file=other.tls.key_file or self.tls.key_file,
            # verify_incoming defaults True; an explicit False in either
            # layer wins (relaxation must be expressible).
            verify_incoming=(self.tls.verify_incoming
                             and other.tls.verify_incoming),
            verify_hostname=(other.tls.verify_hostname
                             or self.tls.verify_hostname),
            uplink=other.tls.uplink or self.tls.uplink,
        )
        out.faults = FaultsBlock(
            seed=other.faults.seed or self.faults.seed,
            # Site rules merge key-by-key like client.meta: a later file
            # overrides a site's whole rule (list), never splices into it.
            sites={**self.faults.sites, **other.faults.sites},
        )
        return out


def default_config() -> FileConfig:
    """config.go DefaultConfig."""
    cfg = FileConfig()
    cfg.region = "global"
    cfg.datacenter = "dc1"
    cfg.log_level = "INFO"
    cfg.bind_addr = "127.0.0.1"
    return cfg


def dev_config() -> FileConfig:
    """config.go DevConfig: server + client in one process, permissive
    driver options."""
    cfg = default_config()
    cfg.name = "dev-node"
    cfg.server.enabled = True
    cfg.server.bootstrap_expect = 1
    cfg.client.enabled = True
    cfg.client.options = {
        "driver.raw_exec.enable": "1",
        "driver.mock_driver.enable": "1",
    }
    return cfg


# ---------------------------------------------------------------------------
# Parsing
# ---------------------------------------------------------------------------


def _from_mapping(data: dict) -> FileConfig:
    cfg = FileConfig()
    scalars = {
        "region", "datacenter", "name", "data_dir", "log_level", "bind_addr",
        "enable_debug", "leave_on_interrupt", "leave_on_terminate",
        "enable_syslog", "syslog_facility", "disable_update_check",
        "scheduler_backend",
    }
    for key, value in data.items():
        if key in scalars:
            setattr(cfg, key, value)
        elif key == "ports":
            for k, v in value.items():
                setattr(cfg.ports, k, int(v))
        elif key == "addresses":
            for k, v in value.items():
                setattr(cfg.addresses, k, v)
        elif key == "advertise":
            for k, v in value.items():
                setattr(cfg.advertise, k, v)
        elif key == "client":
            for k, v in value.items():
                if k in ("meta", "options"):
                    getattr(cfg.client, k).update(
                        {str(mk): str(mv) for mk, mv in v.items()}
                    )
                elif k == "servers":
                    cfg.client.servers = list(v)
                elif k == "network_speed":
                    cfg.client.network_speed = int(v)
                else:
                    setattr(cfg.client, k, v)
        elif key == "server":
            for k, v in value.items():
                if k in ("enabled_schedulers", "start_join"):
                    setattr(cfg.server, k, list(v))
                elif k in ("scheduler_workers", "num_schedulers"):
                    # Validated knob (both spellings): worker concurrency
                    # is a capacity commitment — reject nonsense at parse
                    # time instead of spawning a surprise at
                    # leader-establish.
                    n = int(v)
                    if not 0 <= n <= 128:
                        raise ValueError(
                            f"server.{k} must be in [0, 128], got {n}"
                        )
                    setattr(cfg.server, k, n)
                elif k in ("eval_pending_cap", "plan_queue_cap",
                           "max_blocking_watchers"):
                    # Queue/watcher bounds: parse-time validated like
                    # scheduler_workers — a typo'd cap must fail config
                    # load, not silently unbound a production queue.
                    n = int(v)
                    if not 0 <= n <= 10_000_000:
                        raise ValueError(
                            f"server.{k} must be in [0, 10000000], got {n}"
                        )
                    setattr(cfg.server, k, n)
                elif k == "admission":
                    if not isinstance(v, dict):
                        raise ValueError("server.admission must be a mapping")
                    # Parse-time validation: unknown keys / bad ranges
                    # fail here (AdmissionConfig.parse), not agent start.
                    from nomad_tpu.server.admission import AdmissionConfig

                    AdmissionConfig.parse(dict(v))
                    cfg.server.admission = dict(v)
                elif k == "express":
                    if not isinstance(v, dict):
                        raise ValueError("server.express must be a mapping")
                    # Same posture: a typo'd express knob fails config
                    # load (ExpressConfig.parse), not agent start.
                    from nomad_tpu.server.express import ExpressConfig

                    ExpressConfig.parse(dict(v))
                    cfg.server.express = dict(v)
                elif k == "capacity":
                    if not isinstance(v, dict):
                        raise ValueError("server.capacity must be a mapping")
                    # Same posture: a typo'd capacity knob fails config
                    # load (CapacityConfig.parse), not agent start.
                    from nomad_tpu.capacity import CapacityConfig

                    CapacityConfig.parse(dict(v))
                    cfg.server.capacity = dict(v)
                elif k == "raft_observe":
                    if not isinstance(v, dict):
                        raise ValueError(
                            "server.raft_observe must be a mapping")
                    # Same posture: a typo'd observatory knob fails
                    # config load (RaftObserveConfig.parse), not start.
                    from nomad_tpu.raft_observe import RaftObserveConfig

                    RaftObserveConfig.parse(dict(v))
                    cfg.server.raft_observe = dict(v)
                elif k == "reads":
                    if not isinstance(v, dict):
                        raise ValueError(
                            "server.reads must be a mapping")
                    # Same posture: a typo'd observatory knob fails
                    # config load (ReadObserveConfig.parse), not start.
                    from nomad_tpu.read_observe import ReadObserveConfig

                    ReadObserveConfig.parse(dict(v))
                    cfg.server.reads = dict(v)
                elif k == "read_path":
                    if not isinstance(v, dict):
                        raise ValueError(
                            "server.read_path must be a mapping")
                    # Same posture: a typo'd lane knob fails config
                    # load (ReadPathConfig.parse), not first request.
                    from nomad_tpu.server.read_path import ReadPathConfig

                    ReadPathConfig.parse(dict(v))
                    cfg.server.read_path = dict(v)
                elif k == "profile":
                    if not isinstance(v, dict):
                        raise ValueError(
                            "server.profile must be a mapping")
                    # Same posture: a typo'd observatory knob fails
                    # config load (ProfileObserveConfig.parse), not
                    # start.
                    from nomad_tpu.profile_observe import (
                        ProfileObserveConfig,
                    )

                    ProfileObserveConfig.parse(dict(v))
                    cfg.server.profile = dict(v)
                elif k == "solver_mesh":
                    if not isinstance(v, dict):
                        raise ValueError(
                            "server.solver_mesh must be a mapping")
                    # Same posture: a typo'd mesh knob fails config load
                    # (SolverMeshConfig.parse), not leader-establish.
                    from nomad_tpu.parallel.mesh import SolverMeshConfig

                    SolverMeshConfig.parse(dict(v))
                    cfg.server.solver_mesh = dict(v)
                elif k in ("bootstrap_expect", "protocol_version"):
                    setattr(cfg.server, k, int(v))
                else:
                    setattr(cfg.server, k, v)
        elif key == "telemetry":
            for k, v in value.items():
                if k in ("trace_buffer_size", "event_buffer_size"):
                    v = int(v)
                elif k == "histogram_buckets":
                    if (not isinstance(v, (list, tuple))
                            or not all(isinstance(b, (int, float))
                                       and not isinstance(b, bool)
                                       and b > 0 for b in v)):
                        raise ValueError(
                            "telemetry.histogram_buckets must be a list "
                            "of positive numbers (bucket bounds in ms)"
                        )
                    v = sorted(float(b) for b in v)
                elif k == "slo":
                    if not isinstance(v, dict):
                        raise ValueError("telemetry.slo must be a mapping")
                    # Parse-time validation: a typo'd objective name must
                    # fail config load, not agent start.
                    from nomad_tpu.slo import Objective

                    v = {name: float(ms) for name, ms in v.items()}
                    for name, ms in v.items():
                        Objective.parse(name, ms)
                elif k == "lock_watchdog":
                    # Parse-time validated: the knob is process-global
                    # (it patches threading.Lock), so a stringly-typed
                    # truthy surprise must fail config load.
                    if not isinstance(v, bool):
                        raise ValueError(
                            "telemetry.lock_watchdog must be a boolean")
                setattr(cfg.telemetry, k, v)
        elif key == "atlas":
            for k, v in value.items():
                setattr(cfg.atlas, k, v)
        elif key == "tls":
            for k, v in value.items():
                if not hasattr(cfg.tls, k):
                    raise ValueError(f"unknown tls config key {k!r}")
                setattr(cfg.tls, k, v)
        elif key == "faults":
            for k, v in value.items():
                if k == "seed":
                    cfg.faults.seed = int(v)
                elif k == "sites":
                    if not isinstance(v, dict):
                        raise ValueError("faults.sites must be a mapping")
                    cfg.faults.sites.update(v)
                else:
                    raise ValueError(f"unknown faults config key {k!r}")
        else:
            raise ValueError(f"unknown agent config key {key!r}")
    return cfg


def _body_to_mapping(body: Body) -> dict:
    """Collapse the generic HCL AST into the JSON-equivalent mapping:
    repeated blocks merge, block labels are invalid for agent config."""
    out: dict = dict(body.assigns())
    from nomad_tpu.jobspec.hcl import Block

    for item in body.items:
        if isinstance(item, Block):
            if item.labels:
                raise ValueError(
                    f"agent config block {item.type!r} takes no labels"
                )
            sub = _body_to_mapping(item.body)
            if item.type in out and isinstance(out[item.type], dict):
                out[item.type].update(sub)
            else:
                out[item.type] = sub
    return out


def parse_config(text: str, name: str = "<config>") -> FileConfig:
    """Parse one config file's text: JSON if it looks like JSON, else HCL."""
    stripped = text.lstrip()
    if stripped.startswith("{"):
        return _from_mapping(json.loads(text))
    return _from_mapping(_body_to_mapping(parse_hcl(text)))


def load_config_file(path: str) -> FileConfig:
    with open(path, "r") as fh:
        return parse_config(fh.read(), name=path)


def load_config_path(path: str) -> FileConfig:
    """File or directory (directories load *.hcl / *.json sorted by name,
    like config.go LoadConfigDir)."""
    if os.path.isdir(path):
        cfg = FileConfig()
        entries = sorted(
            e for e in os.listdir(path)
            if e.endswith(".hcl") or e.endswith(".json")
        )
        for entry in entries:
            cfg = cfg.merge(load_config_file(os.path.join(path, entry)))
        return cfg
    return load_config_file(path)
