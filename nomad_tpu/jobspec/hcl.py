"""Minimal HCL (v1) parser for job specifications.

Covers the dialect the reference jobspec uses (/root/reference/jobspec/
test-fixtures/*.hcl): blocks with string labels, assignments of strings,
numbers, booleans, and lists, nested blocks, and ``#``, ``//``, ``/* */``
comments. Produces a Body of Assign/Block items preserving repetition and
order (the jobspec merges repeated ``meta`` blocks like the reference).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple, Union


class HCLParseError(Exception):
    def __init__(self, message: str, line: int):
        super().__init__(f"line {line}: {message}")
        self.line = line


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t\r]+)
  | (?P<newline>\n)
  | (?P<comment>\#[^\n]*|//[^\n]*)
  | (?P<block_comment>/\*.*?\*/)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<heredoc><<-?(?P<hd_tag>\w+)\n.*?\n\s*(?P=hd_tag))
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
  | (?P<punct>[{}\[\]=,])
    """,
    re.VERBOSE | re.DOTALL,
)

_ESCAPES = {"n": "\n", "t": "\t", '"': '"', "\\": "\\", "r": "\r"}


@dataclass
class _Token:
    kind: str
    value: Any
    line: int


def _tokenize(text: str) -> List[_Token]:
    tokens: List[_Token] = []
    pos = 0
    line = 1
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise HCLParseError(f"unexpected character {text[pos]!r}", line)
        kind = m.lastgroup
        raw = m.group(0)
        if kind == "newline":
            line += 1
        elif kind in ("ws", "comment"):
            pass
        elif kind == "block_comment":
            line += raw.count("\n")
        elif kind == "string":
            value = _unescape(raw[1:-1], line)
            tokens.append(_Token("string", value, line))
        elif kind == "heredoc":
            body = raw.split("\n", 1)[1]
            body = body.rsplit("\n", 1)[0]
            tokens.append(_Token("string", body, line))
            line += raw.count("\n")
        elif kind == "number":
            num = float(raw) if "." in raw else int(raw)
            tokens.append(_Token("number", num, line))
        elif kind == "ident":
            if raw == "true":
                tokens.append(_Token("bool", True, line))
            elif raw == "false":
                tokens.append(_Token("bool", False, line))
            else:
                tokens.append(_Token("ident", raw, line))
        elif kind == "punct":
            tokens.append(_Token(raw, raw, line))
        pos = m.end()
    return tokens


def _unescape(s: str, line: int) -> str:
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\":
            i += 1
            if i >= len(s):
                raise HCLParseError("dangling escape", line)
            out.append(_ESCAPES.get(s[i], s[i]))
        else:
            out.append(c)
        i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass
class Assign:
    key: str
    value: Any


@dataclass
class Block:
    type: str
    labels: List[str]
    body: "Body"


@dataclass
class Body:
    items: List[Union[Assign, Block]] = field(default_factory=list)

    def get(self, key: str, default: Any = None) -> Any:
        for item in self.items:
            if isinstance(item, Assign) and item.key == key:
                default = item.value
        return default

    def has(self, key: str) -> bool:
        return any(
            isinstance(item, Assign) and item.key == key for item in self.items
        )

    def assigns(self) -> dict:
        out = {}
        for item in self.items:
            if isinstance(item, Assign):
                out[item.key] = item.value
        return out

    def blocks(self, block_type: str) -> List[Block]:
        return [
            item
            for item in self.items
            if isinstance(item, Block) and item.type == block_type
        ]

    def merged_map(self, block_type: str) -> dict:
        """Merge repeated blocks' assignments (the reference iterates meta
        blocks and merges, parse.go:130-142)."""
        out: dict = {}
        for block in self.blocks(block_type):
            out.update(block.body.assigns())
        return out


class _Parser:
    def __init__(self, tokens: List[_Token]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[_Token]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> _Token:
        tok = self.peek()
        if tok is None:
            last_line = self.tokens[-1].line if self.tokens else 1
            raise HCLParseError("unexpected end of input", last_line)
        self.pos += 1
        return tok

    def expect(self, kind: str) -> _Token:
        tok = self.next()
        if tok.kind != kind:
            raise HCLParseError(f"expected {kind}, got {tok.kind}", tok.line)
        return tok

    def parse_body(self, until: Optional[str]) -> Body:
        body = Body()
        while True:
            tok = self.peek()
            if tok is None:
                if until is None:
                    return body
                raise HCLParseError(f"expected {until!r}", self.tokens[-1].line)
            if until is not None and tok.kind == until:
                self.next()
                return body
            body.items.append(self.parse_item())

    def parse_item(self) -> Union[Assign, Block]:
        key_tok = self.next()
        if key_tok.kind not in ("ident", "string"):
            raise HCLParseError(
                f"expected identifier, got {key_tok.kind}", key_tok.line
            )
        key = key_tok.value

        tok = self.peek()
        if tok is None:
            raise HCLParseError("unexpected end after key", key_tok.line)

        if tok.kind == "=":
            self.next()
            # `key = {` object assignment is treated as a block
            if (nxt := self.peek()) is not None and nxt.kind == "{":
                self.next()
                return Block(key, [], self.parse_body("}"))
            return Assign(key, self.parse_value())

        # Block: optional string labels then {
        labels: List[str] = []
        while tok is not None and tok.kind == "string":
            labels.append(self.next().value)
            tok = self.peek()
        if tok is None or tok.kind != "{":
            raise HCLParseError(
                f"expected '{{' after block header {key!r}",
                tok.line if tok else key_tok.line,
            )
        self.next()
        return Block(key, labels, self.parse_body("}"))

    def parse_value(self) -> Any:
        tok = self.next()
        if tok.kind in ("string", "number", "bool"):
            return tok.value
        if tok.kind == "[":
            values = []
            while True:
                nxt = self.peek()
                if nxt is None:
                    raise HCLParseError("unterminated list", tok.line)
                if nxt.kind == "]":
                    self.next()
                    return values
                values.append(self.parse_value())
                nxt = self.peek()
                if nxt is not None and nxt.kind == ",":
                    self.next()
        raise HCLParseError(f"unexpected value token {tok.kind}", tok.line)


def parse(text: str) -> Body:
    return _Parser(_tokenize(text)).parse_body(until=None)
