"""Jobspec: HCL -> Job.

Reference: /root/reference/jobspec/parse.go. Semantics preserved:
- exactly one ``job "<id>"`` block; id + name default to the label
- defaults: priority 50, region "global", type "service" (parse.go:98-101)
- repeated ``meta`` blocks merge; values stringified (weak decode)
- standalone ``task`` blocks become single-task groups with count 1
  (parse.go:144-160)
- constraint sugar: ``version``/``regexp``/``distinct_hosts`` keys set the
  operand (parse.go:296-347); default operand "="
- durations like "60s"/"10m" in update/restart blocks
- dynamic port labels validated against ^[a-zA-Z0-9_]+$ with
  case-insensitive collision detection (parse.go:19-20, 499-514)
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from nomad_tpu import structs
from nomad_tpu.jobspec.hcl import Block, Body, HCLParseError, parse as hcl_parse
from nomad_tpu.structs import (
    Constraint,
    Job,
    NetworkResource,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    UpdateStrategy,
    new_restart_policy,
)

RE_DYNAMIC_PORTS = re.compile(r"^[a-zA-Z0-9_]+$")


class JobspecError(Exception):
    pass


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
    "s": 1.0, "m": 60.0, "h": 3600.0,
}


def parse_duration(value: Any) -> float:
    """Go-style duration to seconds: "60s", "10m", "1h30m". Bare numbers are
    nanoseconds, like Go's time.Duration integer semantics."""
    if isinstance(value, (int, float)):
        return float(value) * 1e-9
    s = str(value).strip()
    if not s:
        return 0.0
    pos = 0
    total = 0.0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise JobspecError(f"invalid duration {value!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise JobspecError(f"invalid duration {value!r}")
    return total


def _stringify(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _stringify_map(m: Dict[str, Any]) -> Dict[str, str]:
    """Weak decode: HCL numbers/bools in meta/env become strings."""
    return {k: _stringify(v) for k, v in m.items()}


def _config_map(m: Dict[str, Any]) -> Dict[str, Any]:
    """Task config keeps list values (reference Config is
    map[string]interface{}); scalars are stringified."""
    return {
        k: [_stringify(i) for i in v] if isinstance(v, list) else _stringify(v)
        for k, v in m.items()
    }


def parse(text: str) -> Job:
    """Parse a jobspec string into a Job (reference: parse.go:22-58)."""
    try:
        root = hcl_parse(text)
    except HCLParseError as e:
        raise JobspecError(f"error parsing: {e}") from e

    jobs = root.blocks("job")
    if not jobs:
        raise JobspecError("'job' stanza not found")
    if len(jobs) > 1:
        raise JobspecError("only one 'job' block allowed")
    return _parse_job(jobs[0])


def parse_file(path: str) -> Job:
    """reference: parse.go:60-74"""
    with open(path) as f:
        return parse(f.read())


def _parse_job(block: Block) -> Job:
    """reference: parse.go:76-170"""
    if not block.labels:
        raise JobspecError("job block requires a name label")
    body = block.body

    job = Job(
        id=body.get("id", block.labels[0]),
        name=body.get("name", block.labels[0]),
        region=str(body.get("region", "global")),
        type=str(body.get("type", "service")),
        priority=int(body.get("priority", 50)),
        all_at_once=bool(body.get("all_at_once", False)),
        # Express-lane opt-in (nomad_tpu/server/express.py; tpu-native
        # extension, no reference analog): `express = true` on a batch
        # job requests leader-local sub-millisecond placement.
        express=bool(body.get("express", False)),
        datacenters=[str(d) for d in body.get("datacenters", [])],
    )

    job.constraints = _parse_constraints(body)
    updates = body.blocks("update")
    if updates:
        if len(updates) > 1:
            raise JobspecError("only one 'update' block allowed per job")
        u = updates[0].body
        job.update = UpdateStrategy(
            stagger=parse_duration(u.get("stagger", 0)),
            max_parallel=int(u.get("max_parallel", 0)),
        )
    job.meta = _stringify_map(body.merged_map("meta"))

    # Standalone tasks become single-task groups (parse.go:144-160)
    for task in _parse_tasks(body):
        job.task_groups.append(
            TaskGroup(
                name=task.name,
                count=1,
                tasks=[task],
                restart_policy=new_restart_policy(job.type),
            )
        )

    seen = set()
    for group_block in body.blocks("group"):
        if not group_block.labels:
            raise JobspecError("group block requires a name label")
        name = group_block.labels[0]
        if name in seen:
            raise JobspecError(f"group '{name}' defined more than once")
        seen.add(name)
        job.task_groups.append(_parse_group(name, group_block.body, job.type))

    return job


def _parse_group(name: str, body: Body, job_type: str) -> TaskGroup:
    """reference: parse.go:172-260"""
    group = TaskGroup(
        name=name,
        count=int(body.get("count", 1)),
        constraints=_parse_constraints(body),
        meta=_stringify_map(body.merged_map("meta")),
        tasks=_parse_tasks(body),
        restart_policy=new_restart_policy(job_type),
    )
    restarts = body.blocks("restart")
    if restarts:
        if len(restarts) > 1:
            raise JobspecError("only one 'restart' block allowed")
        r = restarts[0].body
        group.restart_policy = RestartPolicy(
            attempts=int(r.get("attempts", 0)),
            interval=parse_duration(r.get("interval", 0)),
            delay=parse_duration(r.get("delay", 0)),
        )
    return group


def _parse_tasks(body: Body) -> List[Task]:
    """reference: parse.go:349-452"""
    tasks: List[Task] = []
    seen = set()
    for task_block in body.blocks("task"):
        if not task_block.labels:
            raise JobspecError("task block requires a name label")
        name = task_block.labels[0]
        if name in seen:
            raise JobspecError(f"task '{name}' defined more than once")
        seen.add(name)
        tb = task_block.body

        task = Task(
            name=name,
            driver=str(tb.get("driver", "")),
            env=_stringify_map(tb.merged_map("env")),
            config=_config_map(tb.merged_map("config")),
            constraints=_parse_constraints(tb),
            meta=_stringify_map(tb.merged_map("meta")),
        )

        resources = tb.blocks("resources")
        if resources:
            if len(resources) > 1:
                raise JobspecError("only one 'resource' block allowed per task")
            task.resources = _parse_resources(resources[0].body)
        tasks.append(task)
    return tasks


def _parse_resources(body: Body) -> Resources:
    """reference: parse.go:454-520"""
    res = Resources(
        cpu=int(body.get("cpu", 0)),
        memory_mb=int(body.get("memory", 0)),
        disk_mb=int(body.get("disk", 0)),
        iops=int(body.get("iops", 0)),
    )
    networks = body.blocks("network")
    if networks:
        if len(networks) > 1:
            raise JobspecError("only one 'network' resource allowed")
        nb = networks[0].body
        net = NetworkResource(
            mbits=int(nb.get("mbits", 0)),
            reserved_ports=[int(p) for p in nb.get("reserved_ports", [])],
            dynamic_ports=[str(p) for p in nb.get("dynamic_ports", [])],
        )
        seen_label: Dict[str, str] = {}
        for label in net.dynamic_ports:
            if not RE_DYNAMIC_PORTS.match(label):
                raise JobspecError(
                    "DynamicPort label does not conform to naming requirements "
                    + RE_DYNAMIC_PORTS.pattern
                )
            first = seen_label.get(label.lower())
            if first is not None:
                raise JobspecError(
                    f"Found a port label collision: `{label}` overlaps with "
                    f"previous `{first}`"
                )
            seen_label[label.lower()] = label
        res.networks = [net]
    return res


def _parse_constraints(body: Body) -> List[Constraint]:
    """reference: parse.go:296-347"""
    out: List[Constraint] = []
    for block in body.blocks("constraint"):
        b = block.body
        l_target = str(b.get("attribute", "") or "")
        r_target = b.get("value", "")
        operand = str(b.get("operator", "") or "")

        if b.has(structs.CONSTRAINT_VERSION):
            operand = structs.CONSTRAINT_VERSION
            r_target = b.get(structs.CONSTRAINT_VERSION)
        if b.has(structs.CONSTRAINT_REGEX):
            operand = structs.CONSTRAINT_REGEX
            r_target = b.get(structs.CONSTRAINT_REGEX)
        if b.has(structs.CONSTRAINT_DISTINCT_HOSTS):
            raw = str(b.get(structs.CONSTRAINT_DISTINCT_HOSTS)).lower()
            if raw not in ("true", "false", "1", "0", "t", "f"):
                raise JobspecError(f"invalid distinct_hosts value {raw!r}")
            if raw in ("false", "0", "f"):
                continue
            operand = structs.CONSTRAINT_DISTINCT_HOSTS

        if not operand:
            operand = "="
        out.append(
            Constraint(l_target=l_target, r_target=str(r_target), operand=operand)
        )
    return out
