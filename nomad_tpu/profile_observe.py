"""Runtime self-observatory: the process watching itself.

Six observatories watch the WORKLOAD (traces, events, latency/SLO,
capacity/solver, raft, reads) but none watches the PROCESS: ROADMAP
item 1 (group-commit the write path) needs to know where fsync and lock
wall-clock actually goes, and item 7 (the million-node cell) turns on
whether a 1M-row mirror *fits in memory* — questions no workload-facing
surface can answer. Borg's cell-scale operation rests on continuous
self-introspection of the Borgmaster itself; Omega's shared-state
posture is already our observer contract: read-only books, decision
paths untouched.

:class:`RuntimeObservatory` is a READ-ONLY observer in the established
composition-root posture: constructed only in ``server/server.py``,
statically barred from decision paths (nomadlint OBS001). It keeps
three ledgers:

- **continuous sampling profiler**: a daemon thread walks
  ``sys._current_frames()`` at a seeded-jittered cadence
  (``prng.stream(seed, "profile.sampler")`` — the schedule is a pure
  function of the seed, so two runs sample at identical offsets) and
  aggregates collapsed stacks per THREAD ROLE (the taxonomy in
  :data:`ROLES`: worker / pipeline-committer / raft / heartbeat-wheel /
  express-committer / observer / http / main / other). Flamegraph-ready
  exports: ``collapsed()`` (Brendan Gregg folded-stack lines) and
  ``speedscope()`` (speedscope.app sampled-profile JSON, one profile
  per role), plus per-role wall-share summaries.
- **lock-contention attribution**: read from the installed
  :class:`telemetry.LockWatchdog` (the runtime knob
  ``telemetry { lock_watchdog = true }``), whose construction-site
  wrappers time contended acquisitions: per-lock-site contended counts,
  wait p50/p95/p99, hold books — surfaced here as a contention table
  ranked by total wait (the group-commit arc's evidence). The
  observatory only READS the watchdog's books; installation is an
  agent-level decision made before any server lock is constructed.
- **byte-economy ledger**: per-subsystem memory accounting — mirror
  device/host buffers by shape bucket × dtype (``NodeMirror
  .byte_ledger`` / ``MirrorCache.byte_ledger``), every bounded ring
  (trace, events, admission decisions, express pending/outcomes, the
  plan pipeline's commit log), the state store's tables, and RSS
  samples (stdlib only: ``/proc/self/statm`` + ``getrusage``) — with a
  **projected 1M-row mirror footprint** computed from the MEASURED
  per-row cost (bytes / padded rows × the 1048576-row padding bucket):
  the item-7 fit-check, banked in the ``profile`` section of SIMLOAD
  artifacts.

Decision-invariance is the contract, as for every observatory before
it: the profiler publishes only on the ``Runtime`` observer topic
(``events.OBSERVER_TOPICS`` — excluded from canonical event digests by
construction), touches no decision state, and the steady-10k digest is
byte-equal with the observatory on, off, and in the profiler-off
contrast arm.

Surfaces: ``/v1/agent/profile`` (JSON + ``?format=collapsed`` /
``?format=speedscope``), ``/v1/agent/runtime`` (locks + byte economy,
JSON + ``?format=prometheus``), SDK ``client.agent().profile()`` /
``.runtime()``, ``nomad_profile_*`` / ``nomad_runtime_*`` /
``nomad_lock_*`` lines on the main Prometheus scrape, the debug
bundle's ``profile`` and ``runtime`` sections, and a ``profile``
section in every SIMLOAD artifact.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from nomad_tpu import prng, telemetry

# Thread-role taxonomy: every thread in the process maps to exactly one
# role by FIRST-MATCH prefix rule (order matters: "raft-observatory"
# must classify observer, not raft). Pinned by the golden-format tests —
# extending the taxonomy is an artifact-schema change.
ROLES = ("worker", "pipeline-committer", "raft", "heartbeat-wheel",
         "express-committer", "observer", "http", "main", "other")

_ROLE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("worker-", "worker"),
    ("plan-pipeline", "pipeline-committer"),
    ("raft-observatory", "observer"),
    ("read-observatory", "observer"),
    ("runtime-profiler", "observer"),
    ("capacity-accountant", "observer"),
    ("stats-emitter", "observer"),
    ("slo-monitor", "observer"),
    ("raft-", "raft"),
    ("heartbeat-wheel", "heartbeat-wheel"),
    ("express-commit", "express-committer"),
    ("http-server", "http"),
)


def classify_thread(name: str) -> str:
    """Thread name -> role, first matching prefix wins. HTTP request
    handlers ride ThreadingHTTPServer's default naming
    ("Thread-N (process_request_thread)")."""
    for prefix, role in _ROLE_PREFIXES:
        if name.startswith(prefix):
            return role
    if "process_request_thread" in name:
        return "http"
    if name == "MainThread":
        return "main"
    return "other"


@dataclass
class ProfileObserveConfig:
    """The ``server { profile { ... } }`` block, parse-time validated
    (the CapacityConfig posture: typos and nonsense ranges fail config
    load, not first use)."""

    enabled: bool = True
    # Base sampling cadence of the stack profiler. 20 Hz keeps the
    # walk's cost well under the <5% plan-p50 overhead budget while
    # still resolving 50ms-scale stalls.
    sample_interval: float = 0.05
    # Jitter fraction applied per tick: interval * (1 ± jitter), drawn
    # from the seeded stream so the schedule is reproducible AND never
    # phase-locks with a periodic workload (the classic profiler bias).
    jitter: float = 0.2
    # Seed of the prng.stream("profile.sampler") cadence stream.
    seed: int = 42
    # Frames kept per stack (leaf-preserving truncation).
    max_depth: int = 24
    # Distinct (role, stack) rows retained; overflow is counted, never
    # silent (the no-silent-caps posture).
    max_stacks: int = 4096
    # Cadence of the byte-economy ledger refresh (mirror walk + RSS
    # sample), riding the sampler thread.
    ledger_interval: float = 1.0
    # Cadence of Runtime-topic snapshot events (0 disables). Observer
    # topic: excluded from the canonical event digest by construction.
    events_interval: float = 10.0

    @classmethod
    def parse(cls, spec: Optional[Dict[str, Any]]) -> "ProfileObserveConfig":
        if spec is None:
            return cls()
        if not isinstance(spec, dict):
            raise ValueError("profile config must be a mapping")
        known = set(cls.__dataclass_fields__)
        unknown = [k for k in spec if k not in known]
        if unknown:
            raise ValueError(
                f"unknown profile config key(s): {sorted(unknown)} "
                f"(have: {sorted(known)})"
            )
        ints = ("seed", "max_depth", "max_stacks")
        out = cls(**{
            k: (bool(v) if k == "enabled"
                else int(v) if k in ints else float(v))
            for k, v in spec.items()
        })
        if out.sample_interval <= 0:
            raise ValueError("profile.sample_interval must be > 0")
        if not 0.0 <= out.jitter < 1.0:
            raise ValueError("profile.jitter must be in [0, 1)")
        if out.seed < 0:
            raise ValueError("profile.seed must be >= 0")
        if out.max_depth <= 0:
            raise ValueError("profile.max_depth must be > 0")
        if out.max_stacks <= 0:
            raise ValueError("profile.max_stacks must be > 0")
        if out.ledger_interval <= 0:
            raise ValueError("profile.ledger_interval must be > 0")
        if out.events_interval < 0:
            raise ValueError("profile.events_interval must be >= 0")
        return out


def sample_schedule(seed: int, interval: float, jitter: float,
                    n: int) -> List[float]:
    """The first ``n`` inter-sample gaps of the profiler's cadence — a
    PURE function of (seed, interval, jitter): the sampler consumes the
    identical stream, so same seed → same schedule (the determinism
    test's pin). Jitter is uniform in interval * [1-j, 1+j]."""
    rng = prng.stream(seed, "profile.sampler")
    return [interval * (1.0 + jitter * (2.0 * rng.random() - 1.0))
            for _ in range(n)]


def frame_label(frame) -> str:
    """Stable frame naming for the exports: ``<module-basename>:<func>``
    — machine-independent (no absolute paths, no line numbers: a
    comment-shift must not churn every banked flamegraph). Pinned by
    the golden-format test."""
    code = frame.f_code
    base = os.path.splitext(os.path.basename(code.co_filename))[0]
    return f"{base}:{code.co_name}"


def collapse_frames(frame, max_depth: int) -> Tuple[str, ...]:
    """One thread's stack as a root-first label tuple, leaf-preserving
    truncation (the leaf is where the time is; a too-deep root prefix
    folds into a literal ``…`` marker)."""
    labels: List[str] = []
    while frame is not None:
        labels.append(frame_label(frame))
        frame = frame.f_back
    labels.reverse()
    if len(labels) > max_depth:
        labels = ["…"] + labels[-(max_depth - 1):]
    return tuple(labels)


# -- byte-economy helpers ----------------------------------------------------


def rss_bytes() -> Dict[str, int]:
    """Current + peak resident set, stdlib only (no psutil in the
    image): current from /proc/self/statm (0 off-Linux), peak from
    getrusage (ru_maxrss is KiB on Linux)."""
    current = 0
    try:
        with open("/proc/self/statm") as f:
            current = int(f.read().split()[1]) * (
                os.sysconf("SC_PAGE_SIZE"))
    except (OSError, IndexError, ValueError):
        pass
    peak = 0
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        pass
    return {"current_bytes": current, "peak_bytes": peak}


def _deep_sizeof(obj: Any, depth: int = 2, _budget: List[int] = None) -> int:
    """Bounded-depth recursive sys.getsizeof: containers recurse into
    members, objects into their __dict__, everything capped at a node
    budget — an APPROXIMATION for the ledger (shared references double-
    count; deep payloads under-count), honest about being one."""
    if _budget is None:
        _budget = [256]
    if _budget[0] <= 0:
        return 0
    _budget[0] -= 1
    try:
        size = sys.getsizeof(obj)
    except TypeError:
        return 0
    if depth <= 0 or isinstance(obj, (str, bytes, int, float, bool,
                                      type(None))):
        return size
    if isinstance(obj, dict):
        for k, v in list(obj.items())[:64]:
            size += _deep_sizeof(k, depth - 1, _budget)
            size += _deep_sizeof(v, depth - 1, _budget)
    elif isinstance(obj, (list, tuple, set, frozenset)):
        for v in list(obj)[:64]:
            size += _deep_sizeof(v, depth - 1, _budget)
    else:
        d = getattr(obj, "__dict__", None)
        if d:
            size += _deep_sizeof(d, depth - 1, _budget)
    return size


def container_footprint(obj: Any, sample: int = 32) -> Dict[str, Any]:
    """One bounded ring's (deque / OrderedDict / list) byte estimate:
    shallow container size + per-entry cost extrapolated from the first
    ``sample`` entries."""
    try:
        n = len(obj)
    except TypeError:
        n = 0
    cap = getattr(obj, "maxlen", None)
    if cap is None:
        cap = getattr(obj, "capacity", None)
    per = 0
    if n:
        it = iter(obj.values()) if isinstance(obj, dict) else iter(obj)
        head = []
        for _ in range(min(sample, n)):
            try:
                head.append(next(it))
            except (StopIteration, RuntimeError):
                break  # a concurrent writer moved the ring under us
        if head:
            per = int(sum(_deep_sizeof(e) for e in head) / len(head))
    try:
        shallow = sys.getsizeof(obj)
    except TypeError:
        shallow = 0
    return {
        "entries": n,
        "capacity": cap,
        "per_entry_bytes": per,
        "approx_bytes": int(shallow + per * n),
    }


class RuntimeObservatory:
    """The process's self-observatory: sampling profiler + lock
    contention + byte economy. All getters re-read per refresh (snapshot
    installs rebind fsm.state; restarts rebind rings). All derived state
    lives under ``_lock``; no decision path ever takes it."""

    def __init__(self, config: Optional[ProfileObserveConfig] = None,
                 events=None,
                 store_getter: Optional[Callable[[], Any]] = None,
                 rings_getter: Optional[Callable[[], Dict[str, Any]]] = None,
                 tables_getter: Optional[Callable[[], Dict[str, Any]]] = None):
        self.config = config or ProfileObserveConfig()
        self._events = events
        self._store = store_getter or (lambda: None)
        self._rings = rings_getter or (lambda: {})
        self._tables = tables_getter or (lambda: {})
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Profiler books (under _lock).
        self.samples = 0            # sampling passes
        self.thread_samples = 0     # individual thread stacks ingested
        self._stacks: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        self._role_samples: Dict[str, int] = {}
        self.stack_overflow = 0     # stacks dropped past max_stacks
        # Byte-ledger books (replaced wholesale under _lock per refresh).
        self._ledger: Dict[str, Any] = {}
        self._rss_mb = telemetry.AggregateSample()
        self.polls = 0
        self.events_published = 0

    # -- profiler -------------------------------------------------------------

    def _ingest(self, role: str, stack: Tuple[str, ...]) -> None:
        """Fold one sampled thread stack into the books (caller holds
        no lock; this takes _lock). The seam the golden-format tests
        drive directly."""
        with self._lock:
            self.thread_samples += 1
            self._role_samples[role] = self._role_samples.get(role, 0) + 1
            key = (role, stack)
            count = self._stacks.get(key)
            if count is not None:
                self._stacks[key] = count + 1
            elif len(self._stacks) < self.config.max_stacks:
                self._stacks[key] = 1
            else:
                self.stack_overflow += 1

    def sample_once(self) -> int:
        """One profiler pass: snapshot every live thread's stack and
        fold it into the books. Returns threads sampled. Safe to call
        from tests without the thread; the sampler thread itself is
        excluded (it would only ever see itself in sample_once)."""
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        n = 0
        for ident, frame in frames.items():
            if ident == me:
                continue
            name = names.get(ident, f"thread-{ident}")
            self._ingest(classify_thread(name),
                         collapse_frames(frame, self.config.max_depth))
            n += 1
        with self._lock:
            self.samples += 1
        return n

    # -- byte-economy ledger --------------------------------------------------

    def refresh(self) -> None:
        """One ledger poll: mirror buffers, bounded rings, state-store
        tables, observatory tables, RSS. Safe to call from tests
        without the thread."""
        ledger: Dict[str, Any] = {}
        ledger["mirror"] = self._mirror_ledger()
        rings = {}
        for name, obj in sorted((self._rings() or {}).items()):
            if obj is None:
                continue
            rings[name] = container_footprint(obj)
        ledger["rings"] = rings
        ledger["store"] = self._store_ledger()
        tables = {}
        for name, obj in sorted((self._tables() or {}).items()):
            if obj is None:
                continue
            tables[name] = {"approx_bytes": _deep_sizeof(obj, depth=3)}
        ledger["tables"] = tables
        rss = rss_bytes()
        self._rss_mb.ingest(rss["current_bytes"] / 1e6)
        ledger["rss"] = {**rss, "sampled_mb": _q(self._rss_mb)}
        tracked = (
            (ledger["mirror"].get("total_bytes") or 0)
            + sum(r["approx_bytes"] for r in rings.values())
            + (ledger["store"].get("approx_bytes") or 0)
            + sum(t["approx_bytes"] for t in tables.values())
        )
        ledger["tracked_bytes"] = tracked
        with self._lock:
            self.polls += 1
            self._ledger = ledger

    @staticmethod
    def _mirror_ledger() -> Dict[str, Any]:
        """The mirror cache's bucket×dtype byte books + the measured-
        per-row 1M-node projection (nomad_tpu/tpu/mirror.py owns the
        math; this just reads it). Degrades to a disabled stub when the
        device stack is absent (client-only agents)."""
        try:
            from nomad_tpu.tpu.mirror import GLOBAL_MIRROR_CACHE

            return GLOBAL_MIRROR_CACHE.byte_ledger()
        except Exception as e:
            return {"error": str(e), "total_bytes": 0}

    def _store_ledger(self) -> Dict[str, Any]:
        store = self._store()
        if store is None:
            return {"approx_bytes": 0}
        counts = {}
        for table in ("jobs", "nodes", "allocs", "evals"):
            try:
                counts[table] = len(list(getattr(store, table)()))
            except Exception:
                counts[table] = None
        return {
            "counts": counts,
            "approx_bytes": _deep_sizeof(store, depth=3),
        }

    # -- exposition -----------------------------------------------------------

    def _profiler_view(self) -> Dict[str, Any]:
        with self._lock:
            total = self.thread_samples
            roles = {
                role: {
                    "samples": n,
                    "wall_share": round(n / total, 4) if total else 0.0,
                }
                for role, n in sorted(self._role_samples.items())
            }
            return {
                "samples": self.samples,
                "thread_samples": total,
                "roles": roles,
                "distinct_stacks": len(self._stacks),
                "stack_overflow": self.stack_overflow,
                "schedule": {
                    "seed": self.config.seed,
                    "sample_interval_s": self.config.sample_interval,
                    "jitter": self.config.jitter,
                },
            }

    def _locks_view(self) -> Dict[str, Any]:
        wd = telemetry.active_lock_watchdog()
        if wd is None:
            return {"installed": False}
        return wd.stats()

    def profile_view(self) -> Dict[str, Any]:
        """The ``/v1/agent/profile`` JSON body."""
        return {
            "profiler": self._profiler_view(),
            "observer": self._observer_view(),
        }

    def runtime_view(self) -> Dict[str, Any]:
        """The ``/v1/agent/runtime`` JSON body."""
        with self._lock:
            ledger = dict(self._ledger)
        return {
            "locks": self._locks_view(),
            "bytes": ledger,
            "observer": self._observer_view(),
        }

    def _observer_view(self) -> Dict[str, Any]:
        return {"polls": self.polls,
                "events_published": self.events_published}

    def snapshot(self) -> Dict[str, Any]:
        """The full self-observatory report (the SIMLOAD ``profile``
        section + bundle body): wall shares, the ranked contention
        table, the byte economy with the 1M-row projection."""
        out = self.profile_view()
        rt = self.runtime_view()
        out["locks"] = rt["locks"]
        out["bytes"] = rt["bytes"]
        return out

    def summary(self) -> Dict[str, Any]:
        """Compact agent-info line: top role by wall share, RSS, mirror
        bytes, worst lock site."""
        prof = self._profiler_view()
        top_role, top_share = "", 0.0
        for role, row in prof["roles"].items():
            if row["wall_share"] >= top_share:
                top_role, top_share = role, row["wall_share"]
        with self._lock:
            ledger = self._ledger
        locks = self._locks_view()
        contention = locks.get("contention") or []
        return {
            "samples": prof["samples"],
            "top_role": top_role,
            "top_role_share": top_share,
            "rss_mb": round(
                (ledger.get("rss", {}).get("current_bytes", 0)) / 1e6, 1),
            "mirror_bytes": ledger.get("mirror", {}).get("total_bytes", 0),
            "contended_sites": sum(
                1 for row in contention if row["contended"]),
            "lock_wait_total_ms": round(
                sum(row["wait_total_ms"] for row in contention), 3),
        }

    def collapsed(self) -> str:
        """Folded-stack lines (flamegraph.pl / speedscope import
        format): ``role;frame;frame count``, sorted for byte-stable
        output."""
        with self._lock:
            rows = sorted(self._stacks.items())
        return "".join(
            f"{';'.join((role,) + stack)} {count}\n"
            for (role, stack), count in rows
        )

    def speedscope(self) -> Dict[str, Any]:
        """speedscope.app file-format JSON: one sampled profile per
        role over a shared frame table, weights = sample counts.
        Deterministic given the books (sorted frames, sorted stacks)."""
        with self._lock:
            rows = sorted(self._stacks.items())
        frame_names: List[str] = sorted(
            {f for (_role, stack), _n in rows for f in stack})
        index = {name: i for i, name in enumerate(frame_names)}
        profiles = []
        for role in sorted({role for (role, _stack), _n in rows}):
            samples, weights = [], []
            for (r, stack), count in rows:
                if r != role:
                    continue
                samples.append([index[f] for f in stack])
                weights.append(count)
            profiles.append({
                "type": "sampled",
                "name": role,
                "unit": "none",
                "startValue": 0,
                "endValue": sum(weights),
                "samples": samples,
                "weights": weights,
            })
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "name": "nomad_tpu runtime profile",
            "exporter": "nomad_tpu.profile_observe",
            "shared": {"frames": [{"name": n} for n in frame_names]},
            "profiles": profiles,
        }

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if not self.config.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="runtime-profiler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        import time as _time

        cfg = self.config
        rng = prng.stream(cfg.seed, "profile.sampler")
        next_ledger = _time.monotonic()
        next_event = (
            _time.monotonic() + cfg.events_interval
            if cfg.events_interval else None
        )
        while True:
            gap = cfg.sample_interval * (
                1.0 + cfg.jitter * (2.0 * rng.random() - 1.0))
            if self._stop.wait(gap):
                return
            try:
                self.sample_once()
                now = _time.monotonic()
                if now >= next_ledger:
                    next_ledger = now + cfg.ledger_interval
                    self.refresh()
                if next_event is not None and now >= next_event:
                    next_event = now + cfg.events_interval
                    self.publish_event()
            except Exception:
                # The observer must never take the agent down; the
                # sampler retries next tick. Counted, not silent.
                telemetry.incr_counter(("profile_observe", "poll_errors"))

    def publish_event(self) -> None:
        """One Runtime-topic snapshot event (trimmed payload). Observer
        topic: excluded from canonical event digests by construction
        (events.OBSERVER_TOPICS), so publishing cadence can never
        perturb the determinism contract."""
        if self._events is None:
            return
        self._events.publish(
            "Runtime", "RuntimeSnapshot", key="runtime",
            payload=self.summary(),
        )
        self.events_published += 1


def _q(sample) -> Dict[str, float]:
    return {
        "mean": round(sample.mean, 4),
        "max": round(sample.max, 4),
        **{k: round(v, 4) for k, v in sample.quantiles().items()},
    }
