"""Raft & recovery observatory: write-path attribution, replication lag,
log/snapshot economy, and the restart-replay timeline.

ROADMAP item 2 says the replicated write path must "survive production
traffic and restarts" — but until now it was a black box: "every plan is
one raft entry" was a sentence, not a measured cost; follower lag,
log-growth vs compaction economy, and how long a cold restart takes to
replay back to serving were all unobserved. Before the durability arc
(group-commit, log batching) can be built honestly, its baseline must be
measurable — this module is to item 2 what the capacity observatory
(``nomad_tpu/capacity.py``) was to the defrag arc.

:class:`RaftObservatory` is a READ-ONLY observer (the Omega shared-state
posture): it drains the plain-data books the raft node itself keeps —
``RaftNode`` records one bounded anchor record per leader-submitted
entry (submit → persisted → first-ack → committed → fsm-apply →
future-resolve wall stamps, zero imports of this module) plus log/
snapshot/peer counters, and ``server/fsm.py`` stamps its last
snapshot-restore wall and row counts — and aggregates them. It holds no
hot-path hook, takes no lock any decision path takes, and decision-path
modules are statically barred from importing it (nomadlint OBS001, the
same composition-root rule as the capacity accountant).

What it reports (the ``/v1/agent/raft`` body):

- **write-path attribution**: per ``msg_type``, a stage PARTITION of
  submit→applied — ``append_persist`` / ``replicate`` / ``quorum`` /
  ``apply_wait`` / ``fsm_apply`` / ``future_resolve`` — with p50/p95/p99
  per stage and bytes-per-entry. The stages are consecutive anchor
  differences (a missing anchor collapses to zero width), so the stage
  sums reconcile with the measured submit→applied by construction — the
  same contract ``nomad_tpu/lifecycle.py`` pins for the eval waterfall.
- **replication & log economy**: per-follower lag (match-index delta and
  last-ack age), leader commit-index advance rate, log length/bytes,
  compaction and snapshot counters with wall cost and on-disk size, and
  the entries-retained-vs-truncated split (the ``snapshot_threshold`` /
  ``trailing_logs`` economy).
- **recovery timeline**: a cold restart's structured report — snapshot-
  restore wall (+ the FSM's restored row counts), log entries replayed
  with per-type counts and replay rate, time-to-leader, and
  time-to-serving (leadership established, broker restored).

Surfaces: ``/v1/agent/raft`` (JSON + ``?format=prometheus``), SDK
``client.agent().raft()``, periodic ``Raft``-topic snapshot events
(observer topic — excluded from the canonical determinism digest by
construction, ``events.OBSERVER_TOPICS``), the debug bundle's ``raft``
section, ``nomad_raft_*`` lines on the main Prometheus scrape, and a
``raft`` section in every SIMLOAD artifact (the ``restart-under-load``
scenario banks the recovery timeline).
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from nomad_tpu import telemetry

# The write-path anchor chain, in wall order. Each stage below is the
# difference between consecutive anchors; an anchor the entry never hit
# (e.g. first_ack on a single-member cluster) carries the previous
# anchor's stamp forward, so its stage is exactly zero wide and the
# partition property (stage sums == resolved - submit) holds regardless.
ANCHORS = ("submit", "persisted", "first_ack", "committed",
           "fsm_start", "fsm_end", "resolved")

# Stage i spans ANCHORS[i] -> ANCHORS[i+1].
STAGES = ("append_persist", "replicate", "quorum", "apply_wait",
          "fsm_apply", "future_resolve")


def stage_partition(anchors: Dict[str, float]) -> Dict[str, float]:
    """Reduce one entry's anchor stamps into the stage partition (ms).

    Contract (unit-pinned in tests/test_raft_observe.py): the returned
    stage widths are non-negative and sum EXACTLY to
    ``resolved - submit`` — missing or out-of-order intermediate anchors
    clamp to the running cursor instead of going negative, the same
    reconciliation discipline as lifecycle.py's waterfall."""
    cursor = anchors.get("submit", 0.0)
    out: Dict[str, float] = {}
    for stage, anchor in zip(STAGES, ANCHORS[1:]):
        t = anchors.get(anchor)
        if t is None or t < cursor:
            t = cursor
        out[stage] = (t - cursor) * 1000.0
        cursor = t
    return out


@dataclass
class RaftObserveConfig:
    """The ``server { raft_observe { ... } }`` block, parse-time
    validated (the CapacityConfig posture: typos and nonsense ranges
    fail config load, not first use)."""

    enabled: bool = True
    # Cadence of the observatory's drain of the raft node's books. The
    # node's record ring is bounded (overflow is counted as
    # records_dropped, never silent), so any cadence is safe.
    poll_interval: float = 1.0
    # Cadence of Raft-topic snapshot events (0 disables). Observer
    # topic: excluded from the canonical event digest by construction.
    events_interval: float = 10.0

    @classmethod
    def parse(cls, spec: Optional[Dict[str, Any]]) -> "RaftObserveConfig":
        if spec is None:
            return cls()
        if not isinstance(spec, dict):
            raise ValueError("raft_observe config must be a mapping")
        known = set(cls.__dataclass_fields__)
        unknown = [k for k in spec if k not in known]
        if unknown:
            raise ValueError(
                f"unknown raft_observe config key(s): {sorted(unknown)} "
                f"(have: {sorted(known)})"
            )
        out = cls(**{
            k: (bool(v) if k == "enabled" else float(v))
            for k, v in spec.items()
        })
        if out.poll_interval <= 0:
            raise ValueError("raft_observe.poll_interval must be > 0")
        if out.events_interval < 0:
            raise ValueError("raft_observe.events_interval must be >= 0")
        return out


class _MsgBooks:
    """Per-msg_type aggregates: entry count, bytes, total submit→applied
    quantiles, and per-stage quantiles (reservoir-backed
    telemetry.AggregateSample — the /v1/agent/metrics posture)."""

    __slots__ = ("count", "bytes_total", "bytes_sample", "total",
                 "stages")

    def __init__(self):
        self.count = 0
        self.bytes_total = 0
        self.bytes_sample = telemetry.AggregateSample()
        self.total = telemetry.AggregateSample()
        self.stages = {s: telemetry.AggregateSample() for s in STAGES}

    def ingest(self, record: Dict[str, Any]) -> None:
        anchors = record.get("anchors") or {}
        stages = stage_partition(anchors)
        total_ms = sum(stages.values())
        self.count += 1
        nbytes = int(record.get("bytes", 0))
        self.bytes_total += nbytes
        self.bytes_sample.ingest(float(nbytes))
        self.total.ingest(total_ms)
        for stage, ms in stages.items():
            self.stages[stage].ingest(ms)

    @staticmethod
    def _q(sample) -> Dict[str, float]:
        return {
            "mean": round(sample.mean, 4),
            "max": round(sample.max, 4),
            **{k: round(v, 4) for k, v in sample.quantiles().items()},
        }

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "bytes_total": self.bytes_total,
            "bytes_per_entry": self._q(self.bytes_sample),
            "total_ms": self._q(self.total),
            "stages_ms": {s: self._q(agg)
                          for s, agg in self.stages.items()},
        }


class RaftObservatory:
    """Aggregates the raft node's plain-data observability books.

    ``raft_getter`` re-reads per refresh (the InProcRaft → RaftNode and
    restart rebind cases); a node without the book surface (DevMode
    InProcRaft) degrades to the applied-index view. All aggregate state
    lives under ``_lock``; no decision path ever takes it."""

    # Commit-index samples retained for the advance-rate window.
    RATE_SAMPLES = 600

    def __init__(self, raft_getter: Callable[[], Any],
                 config: Optional[RaftObserveConfig] = None,
                 events=None,
                 fsm_getter: Optional[Callable[[], Any]] = None):
        self._raft = raft_getter
        self._fsm = fsm_getter
        self.config = config or RaftObserveConfig()
        self._events = events
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cursor = 0
        self._raft_id = None  # id() of the node the cursor belongs to
        self._msg: Dict[str, _MsgBooks] = {}
        # (monotonic t, commit_index) ring for the advance-rate series.
        self._commit_samples: "deque" = deque(maxlen=self.RATE_SAMPLES)
        self.polls = 0
        self.records_ingested = 0
        self.records_dropped = 0
        self.events_published = 0

    # -- refresh -------------------------------------------------------------

    def refresh(self) -> None:
        """One poll: drain finalized write-path records from the raft
        node and fold them into the per-msg_type books. Safe to call
        from tests without the thread."""
        raft = self._raft()
        if raft is None:
            return
        drain = getattr(raft, "write_path_records", None)
        with self._lock:
            self.polls += 1
            if id(raft) != self._raft_id:
                # A restart (or InProc→Raft rebind) replaced the node:
                # its record sequence starts over. Books are cumulative
                # across the process (the restart story WANTS the pre-
                # and post-kill write costs side by side); only the
                # cursor resets.
                self._raft_id = id(raft)
                self._cursor = 0
            if drain is not None:
                seq, records = drain(self._cursor)
                missed = (seq - self._cursor) - len(records)
                if missed > 0:
                    # Counted even across a restart's cursor reset (or a
                    # late attach): a finalized record the observatory
                    # never ingested is a drop, never silent.
                    self.records_dropped += missed
                self._cursor = seq
                for rec in records:
                    self._msg.setdefault(
                        rec.get("msg_type", "?"), _MsgBooks()
                    ).ingest(rec)
                    self.records_ingested += 1
            import time as _time

            self._commit_samples.append(
                (_time.monotonic(), int(getattr(raft, "commit_index",
                                                raft.applied_index)))
            )

    def absorb(self, other: Optional["RaftObservatory"]) -> None:
        """Adopt a predecessor observatory's cumulative books. The
        restart scenario replaces the whole server object mid-run; the
        write-path attribution must span both lives (pre-kill plan
        commits next to post-restart ones). The predecessor must be
        stopped — it is drained once more here and never touched again.
        Locks are taken sequentially, never nested."""
        if other is None:
            return
        other.refresh()  # final drain of the dead node's record ring
        with other._lock:
            msg = dict(other._msg)
            ingested = other.records_ingested
            dropped = other.records_dropped
            polls = other.polls
            samples = list(other._commit_samples)
        with self._lock:
            for msg_type, books in msg.items():
                self._msg.setdefault(msg_type, books)
            self.records_ingested += ingested
            self.records_dropped += dropped
            self.polls += polls
            for s in samples:
                self._commit_samples.append(s)

    def _advance_rate(self) -> Dict[str, Any]:
        """Commit-index advance rate over the retained sample window
        (entries committed per second, as the observatory saw it)."""
        with self._lock:
            samples = list(self._commit_samples)
        if len(samples) < 2:
            return {"entries_per_s": 0.0, "window_s": 0.0}
        t0, c0 = samples[0]
        t1, c1 = samples[-1]
        dt = max(t1 - t0, 1e-9)
        return {
            "entries_per_s": round(max(c1 - c0, 0) / dt, 2),
            "window_s": round(dt, 1),
        }

    # -- exposition ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``/v1/agent/raft`` body."""
        raft = self._raft()
        observe = getattr(raft, "observe_stats", None)
        if observe is not None:
            core = observe()
        else:
            # DevMode InProcRaft: no replication layer to attribute.
            core = {
                "state": "inproc",
                "applied_index": (raft.applied_index
                                  if raft is not None else 0),
            }
        # A replication layer without a recovery record (DevMode
        # InProcRaft) still serves a stable shape: never cold-started.
        recovery = dict(getattr(raft, "recovery", None)
                        or {"cold_start": False})
        fsm = self._fsm() if self._fsm is not None else None
        restore = getattr(fsm, "last_restore", None)
        if restore is not None:
            recovery["fsm_restore"] = dict(restore)
        replayed = recovery.get("entries_replayed") or 0
        replay_wall_ms = recovery.get("replay_wall_ms")
        if replayed and replay_wall_ms:
            recovery["replay_entries_per_s"] = round(
                replayed / (replay_wall_ms / 1000.0), 1)
        with self._lock:
            write_path = {m: b.snapshot()
                          for m, b in sorted(self._msg.items())}
            observer = {
                "polls": self.polls,
                "records_ingested": self.records_ingested,
                "records_dropped": self.records_dropped,
                "events_published": self.events_published,
            }
        return {
            "raft": core,
            "write_path": write_path,
            "replication": {
                "peers": core.get("peers", {}),
                "commit_advance": self._advance_rate(),
            },
            "log": core.get("log", {}),
            "snapshot": core.get("snapshot", {}),
            "recovery": recovery,
            "observer": observer,
        }

    def summary(self) -> Dict[str, Any]:
        """Compact agent-info line: applied index, log economy headline,
        worst write-path p95."""
        snap = self.snapshot()
        worst = 0.0
        for books in snap["write_path"].values():
            worst = max(worst, books["total_ms"].get("p95", 0.0))
        return {
            "applied_index": snap["raft"].get("applied_index", 0),
            "log_entries": snap["log"].get("entries", 0),
            "write_p95_ms_worst": round(worst, 3),
            "recovered": bool(snap["recovery"].get("cold_start")),
        }

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if not self.config.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="raft-observatory"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        import time as _time

        next_event = (
            _time.monotonic() + self.config.events_interval
            if self.config.events_interval else None
        )
        while not self._stop.wait(self.config.poll_interval):
            try:
                self.refresh()
                if (next_event is not None
                        and _time.monotonic() >= next_event):
                    next_event = (
                        _time.monotonic() + self.config.events_interval
                    )
                    self.publish_event()
            except Exception:
                # The observer must never take the agent down; the poll
                # loop retries next tick. Counted, not silent.
                telemetry.incr_counter(("raft_observe", "poll_errors"))

    def publish_event(self) -> None:
        """One Raft-topic snapshot event (trimmed payload). Observer
        topic: excluded from canonical event digests by construction
        (events.OBSERVER_TOPICS), so publishing cadence can never
        perturb the determinism contract."""
        if self._events is None:
            return
        snap = self.snapshot()
        self._events.publish(
            "Raft", "RaftSnapshot", key="raft",
            payload={
                "applied_index": snap["raft"].get("applied_index", 0),
                "commit_index": snap["raft"].get("commit_index", 0),
                "log_entries": snap["log"].get("entries", 0),
                "log_bytes": snap["log"].get("bytes", 0),
                "peers": {
                    pid: {"lag_entries": p.get("lag_entries")}
                    for pid, p in snap["replication"]["peers"].items()
                },
                "write_p95_ms": {
                    m: b["total_ms"].get("p95", 0.0)
                    for m, b in snap["write_path"].items()
                },
            },
        )
        self.events_published += 1


def fsm_state_digest(store) -> str:
    """Canonical digest of a state store's replicated contents — the
    restart contract's yardstick: a cold restart's replayed FSM must
    reproduce the pre-kill digest exactly (tests/test_raft_observe.py
    e2e; the restart-under-load scenario asserts the placement subset).
    Reduces each table to sorted, order-independent rows of the fields
    replication is responsible for."""
    snap = store.snapshot()
    doc = {
        "nodes": sorted(
            (n.id, n.status, bool(n.drain), n.modify_index)
            for n in snap.nodes()
        ),
        "jobs": sorted(
            (j.id, j.type, j.modify_index) for j in snap.jobs()
        ),
        "evals": sorted(
            (e.id, e.status, e.modify_index) for e in snap.evals()
        ),
        "allocs": sorted(
            (a.id, a.node_id, a.job_id, a.desired_status,
             a.client_status)
            for a in snap.allocs()
        ),
        "indexes": {
            t: snap.get_index(t)
            for t in ("nodes", "jobs", "evals", "allocs")
        },
    }
    return hashlib.sha256(
        json.dumps(doc, separators=(",", ":"), sort_keys=True).encode()
    ).hexdigest()
