"""Canonical test fixtures, mirroring the reference's mock package
(/root/reference/nomad/mock/mock.go) so ported scheduler tests anchor to the
same cluster shapes (4000 CPU / 8GB node; service job with count=10 exec web
task; system job; pending eval; running alloc).
"""

from __future__ import annotations

from nomad_tpu import structs
from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Evaluation,
    Job,
    NetworkResource,
    Node,
    Plan,
    PlanResult,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    generate_uuid,
)


def node() -> Node:
    """reference: mock.go:8-55"""
    return Node(
        id=generate_uuid(),
        datacenter="dc1",
        name="foobar",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "version": "0.1.0",
            "driver.exec": "1",
        },
        resources=Resources(
            cpu=4000,
            memory_mb=8192,
            disk_mb=100 * 1024,
            iops=150,
            networks=[
                NetworkResource(device="eth0", cidr="192.168.0.100/32", mbits=1000)
            ],
        ),
        reserved=Resources(
            cpu=100,
            memory_mb=256,
            disk_mb=4 * 1024,
            networks=[
                NetworkResource(
                    device="eth0",
                    ip="192.168.0.100",
                    reserved_ports=[22],
                    mbits=1,
                )
            ],
        ),
        links={"consul": "foobar.dc1"},
        meta={"pci-dss": "true"},
        node_class="linux-medium-pci",
        status=structs.NODE_STATUS_READY,
    )


def job() -> Job:
    """reference: mock.go:57-120"""
    return Job(
        region="global",
        id=generate_uuid(),
        name="my-job",
        type=structs.JOB_TYPE_SERVICE,
        priority=50,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[
            Constraint(l_target="$attr.kernel.name", r_target="linux", operand="=")
        ],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                restart_policy=RestartPolicy(attempts=3, interval=600.0, delay=60.0),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date", "args": "+%s"},
                        env={"FOO": "bar"},
                        resources=Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[
                                NetworkResource(mbits=50, dynamic_ports=["http"])
                            ],
                        ),
                    )
                ],
                meta={
                    "elb_check_type": "http",
                    "elb_check_interval": "30s",
                    "elb_check_min": "3",
                },
            )
        ],
        meta={"owner": "armon"},
        status=structs.JOB_STATUS_PENDING,
        create_index=42,
        modify_index=99,
    )


def system_job() -> Job:
    """reference: mock.go:122-177"""
    return Job(
        region="global",
        id=generate_uuid(),
        name="my-job",
        type=structs.JOB_TYPE_SYSTEM,
        priority=100,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[
            Constraint(l_target="$attr.kernel.name", r_target="linux", operand="=")
        ],
        task_groups=[
            TaskGroup(
                name="web",
                count=1,
                restart_policy=RestartPolicy(attempts=3, interval=600.0, delay=60.0),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date", "args": "+%s"},
                        resources=Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[
                                NetworkResource(mbits=50, dynamic_ports=["http"])
                            ],
                        ),
                    )
                ],
            )
        ],
        meta={"owner": "armon"},
        status=structs.JOB_STATUS_PENDING,
        create_index=42,
        modify_index=99,
    )


def evaluation() -> Evaluation:
    """reference: mock.go:179-188"""
    return Evaluation(
        id=generate_uuid(),
        priority=50,
        type=structs.JOB_TYPE_SERVICE,
        job_id=generate_uuid(),
        status=structs.EVAL_STATUS_PENDING,
    )


def alloc() -> Allocation:
    """reference: mock.go:190-230"""
    j = job()
    a = Allocation(
        id=generate_uuid(),
        eval_id=generate_uuid(),
        node_id="foo",
        task_group="web",
        resources=Resources(
            cpu=500,
            memory_mb=256,
            networks=[
                NetworkResource(
                    device="eth0",
                    ip="192.168.0.100",
                    reserved_ports=[12345],
                    mbits=100,
                    dynamic_ports=["http"],
                )
            ],
        ),
        task_resources={
            "web": Resources(
                cpu=500,
                memory_mb=256,
                networks=[
                    NetworkResource(
                        device="eth0",
                        ip="192.168.0.100",
                        reserved_ports=[5000],
                        mbits=50,
                        dynamic_ports=["http"],
                        offered=True,
                    )
                ],
            )
        },
        job=j,
        job_id=j.id,
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
        client_status=structs.ALLOC_CLIENT_STATUS_PENDING,
    )
    return a


def plan() -> Plan:
    return Plan(priority=50)


def plan_result() -> PlanResult:
    return PlanResult()
