"""Shared retry discipline: jittered exponential backoff + circuit breaker.

Before this module every retry loop in the control plane rolled its own
sleep schedule — fixed 50/100ms sleeps in the worker's dequeue loops, a
hand-unrolled doubling in wait_for_index, a flat 100ms in the cluster
forwarder. Under injected faults those flat sleeps either hammer a down
peer or oversleep a fast recovery; the jittered exponential here is the
one policy all of them share: with d = min(cap, base*2^n), the sleep is
drawn U(d*(1-jitter), d] — the AWS architecture-blog "equal jitter"
family (jitter=0.5 by default; 1.0 gives full jitter) — so a thundering
herd of workers retrying the same dead leader decorrelates while every
retry still waits a floor that actually backs off.

``retry_undelivered`` encodes the transport tier's ONE safe auto-retry
rule: RPCUndeliveredError means the frame provably never reached the peer
(rpc.py:78-83), so even non-idempotent calls replay safely; timeouts and
lost responses (RPCTimeoutError, rpc.py:85-88) are NEVER auto-retried here
— the request may have executed, and redelivery belongs to the layer that
owns idempotency (the broker's nack machinery, raft-upsert semantics).

``CircuitBreaker`` is the classic three-state machine (closed → open on N
consecutive failures → half-open probe after a cooldown that itself backs
off) used by tpu/solver.py to stop feeding evals to a dead device: while
open, the scheduler factory routes straight to the host-oracle CPU path
instead of failing every eval into the nack/delivery-limit reaper. State
transitions are counted in telemetry (``<name>.to_<state>`` counters plus
a ``<name>.state`` gauge: 0 closed / 1 half-open / 2 open) so a tripped
breaker is visible in /v1/agent/metrics, not just in latency.
"""

from __future__ import annotations

import random as _random
import threading
import time
from random import Random
from typing import Callable, Optional, Tuple

from nomad_tpu import telemetry


class Backoff:
    """Jittered exponential backoff with an optional deadline.

    next_delay() grows base * factor^n capped at max_delay, jittered by
    drawing uniformly from [delay*(1-jitter), delay] ("equal jitter" at
    the default jitter=0.5; jitter=1.0 is full jitter, 0 disables);
    sleep() applies it and returns False once the deadline has expired
    (callers use that as their give-up signal). reset() re-arms after a
    success. A seeded ``rng`` makes the schedule deterministic for tests.
    """

    __slots__ = ("base", "max_delay", "factor", "jitter", "deadline",
                 "attempts", "_rng")

    def __init__(self, base: float = 0.05, max_delay: float = 2.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 deadline: Optional[float] = None,
                 rng: Optional[Random] = None):
        self.base = base
        self.max_delay = max_delay
        self.factor = factor
        self.jitter = jitter
        # Absolute time.monotonic() stamp, or None for no deadline.
        self.deadline = (
            time.monotonic() + deadline if deadline is not None else None
        )
        self.attempts = 0
        # None = the module's shared PRNG: Backoff objects are built on
        # hot paths (one per wait_for_index call), and instantiating a
        # fresh os.urandom-seeded Random there is a syscall + MT init
        # that jitter=0 users never even draw from.
        self._rng = rng

    def reset(self) -> None:
        self.attempts = 0

    @property
    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline

    def next_delay(self) -> float:
        # Exponent capped: a worker soaking a no-leader period for hours
        # keeps counting attempts, and float 2.0**1024 raises
        # OverflowError — the cap saturates the growth far past any real
        # max_delay without ever overflowing.
        exp = min(self.attempts, 64)
        delay = min(self.max_delay, self.base * (self.factor ** exp))
        self.attempts += 1
        if self.jitter > 0:
            draw = (self._rng or _random).random()
            delay *= 1.0 - self.jitter * draw
        return delay

    def sleep(self, stop: Optional[threading.Event] = None) -> bool:
        """Sleep the next delay (clamped to the deadline). Returns True to
        keep retrying, False when the deadline expired or ``stop`` was set
        mid-sleep."""
        if self.expired:
            return False
        delay = self.next_delay()
        if self.deadline is not None:
            delay = min(delay, max(self.deadline - time.monotonic(), 0.0))
        if stop is not None:
            if stop.wait(delay):
                return False
        else:
            time.sleep(delay)
        return not self.expired


# Ceiling on honoring a server's retry-after hint in one sleep: a hint of
# minutes is the server's honest schedule, but a synchronous caller
# blocked that long has usually out-lived its own deadline — surface the
# typed rejection instead and let the caller decide.
MAX_RETRY_AFTER_SLEEP = 30.0


def retry_undelivered(fn: Callable, retries: int = 2,
                      backoff: Optional[Backoff] = None,
                      rate_limit_retries: int = 2):
    """Run ``fn`` retrying only failures that are PROVABLY side-effect
    free to replay.

    Two such classes exist (rpc.py:78-88 + structs.RejectError):

    - RPCUndeliveredError: the frame never reached the peer — the handler
      never ran, so even non-idempotent RPCs replay safely.
    - A typed ``RATE_LIMITED`` rejection (the admission front door,
      server/admission.py): raised BEFORE any raft apply, so nothing
      executed; the retry sleeps max(the server's retry-after hint,
      the jittered backoff) — honoring the hint instead of hot-looping,
      bounded by ``rate_limit_retries``.

    Every other rejection reason (QUEUE_FULL, SHED, WATCH_LIMIT)
    surfaces immediately as a typed RejectError — still retry-SAFE, but
    retrying into a full queue or an overloaded cluster is exactly the
    feedback loop backpressure exists to break; the caller owns that
    decision. Anything else (RemoteError, RPCTimeoutError, plain
    RPCError) may have executed remotely and surfaces unchanged.
    """
    from nomad_tpu.rpc import RemoteError, RPCUndeliveredError
    from nomad_tpu.structs import REJECT_RATE_LIMITED, parse_reject

    bo = backoff or Backoff(base=0.05, max_delay=0.5)
    attempt = 0
    rl_attempt = 0
    while True:
        try:
            return fn()
        except RPCUndeliveredError:
            attempt += 1
            if attempt > retries:
                raise
            telemetry.incr_counter(("rpc", "client", "retry_undelivered"))
            if not bo.sleep():
                raise
        except RemoteError as e:
            rejection = parse_reject(str(e))
            if rejection is None:
                raise
            if (rejection.reason != REJECT_RATE_LIMITED
                    or rl_attempt >= rate_limit_retries
                    # A hint past the ceiling means the server scheduled
                    # the next token far out: sleeping a clamped slice
                    # and replaying is a GUARANTEED re-rejection —
                    # surface the typed rejection and let the caller
                    # decide (the ceiling's whole point).
                    or rejection.retry_after > MAX_RETRY_AFTER_SLEEP
                    # Ditto when the caller's own deadline has expired
                    # (or would expire mid-sleep): never sleep past a
                    # budget just to raise afterwards.
                    or bo.expired):
                raise rejection from e
            delay = max(rejection.retry_after, bo.next_delay())
            if bo.deadline is not None:
                remaining = bo.deadline - time.monotonic()
                if delay > remaining:
                    raise rejection from e
            rl_attempt += 1
            telemetry.incr_counter(("rpc", "client", "retry_rate_limited"))
            time.sleep(delay)


# Circuit breaker states. Gauge values chosen so "bigger = less healthy".
CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Three-state breaker with backoff-growing cooldown and half-open
    probing.

    - closed: all calls allowed; ``threshold`` consecutive failures trip it.
    - open: allow() is False until ``cooldown`` elapses (cooldown doubles
      per consecutive trip, capped at ``max_cooldown``), then the next
      allow() transitions to half-open and grants ONE probe.
    - half-open: exactly one in-flight probe; success closes the breaker
      (and resets the cooldown), failure re-opens with a longer cooldown.
      A probe that never reports (caller died mid-solve) is reclaimed
      after ``cooldown`` so the breaker can't wedge half-open forever.
    """

    def __init__(self, threshold: int = 3, cooldown: float = 15.0,
                 max_cooldown: float = 300.0,
                 name: Tuple[str, ...] = ("breaker",)):
        self.threshold = int(threshold)
        self.cooldown = float(cooldown)
        self.max_cooldown = float(max_cooldown)
        self.name = tuple(name)
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive failures while closed
        self._trips = 0             # consecutive opens (grows the cooldown)
        self._opened_at = 0.0
        self._probe_started = 0.0   # half-open probe grant time (0 = none)

    # -- inspection --------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "trips": self._trips,
                "threshold": self.threshold,
                "cooldown": self._current_cooldown(),
            }

    # -- state machine -----------------------------------------------------

    def _current_cooldown(self) -> float:
        # Exponent capped like Backoff.next_delay: trips grow unbounded
        # on a permanently-dead device and 2.0**1024 would overflow.
        grown = self.cooldown * (2.0 ** min(max(0, self._trips - 1), 32))
        return min(grown, self.max_cooldown)

    def _transition(self, state: str) -> None:
        # Lock held. Telemetry from inside the lock is fine: sinks are
        # lock-cheap and transitions are rare by construction.
        if state == self._state:
            return
        prev = self._state
        self._state = state
        telemetry.incr_counter(self.name + (f"to_{state}",))
        telemetry.set_gauge(self.name + ("state",), _STATE_GAUGE[state])
        # Event-stream visibility (nomad_tpu.events): a breaker flip is a
        # cluster-behavior change (evals reroute to the host path) that
        # polling individual metrics only shows after the fact. Broadcast:
        # breakers are process-scoped, not owned by any one server.
        from nomad_tpu import events

        events.broadcast(
            "Breaker", "BreakerStateChanged", key=".".join(self.name),
            payload={"from": prev, "to": state, "trips": self._trips},
        )

    def allow(self) -> bool:
        """Whether a call may take the guarded path right now. In open
        state, the first caller after the cooldown gets the half-open
        probe; everyone else keeps getting False until that probe
        resolves."""
        now = time.monotonic()
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now - self._opened_at < self._current_cooldown():
                    return False
                self._transition(HALF_OPEN)
                self._probe_started = now
                return True
            # half-open: one probe at a time. An abandoned probe (the
            # granted eval never reached a device dispatch — a stop-only
            # or deregister eval, or its caller died) reclaims after the
            # BASE cooldown, not the trip-grown one: the grown cooldown
            # paces re-probing a failing device, but a probe nobody
            # resolved says nothing about the device and must not stall
            # recovery for minutes.
            if self._probe_started and (
                now - self._probe_started < self.cooldown
            ):
                return False
            self._probe_started = now
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_started = 0.0
            if self._state != CLOSED:
                self._trips = 0
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_started = 0.0
            if self._state == HALF_OPEN:
                # The probe failed: back off harder.
                self._trips += 1
                self._opened_at = time.monotonic()
                self._transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.threshold:
                self._trips += 1
                self._opened_at = time.monotonic()
                self._transition(OPEN)

    def reset(self) -> None:
        """Force-close (tests, operator intervention)."""
        with self._lock:
            self._failures = 0
            self._trips = 0
            self._probe_started = 0.0
            self._transition(CLOSED)
