"""SCADA-analog uplink: expose the agent HTTP API over a dialed tunnel.

Reference: /root/reference/command/agent/scada.go — the agent dials a
broker (Atlas/SCADA at HashiCorp), authenticates with an infrastructure
name + token, and registers an "http" capability; the broker then opens
yamux streams back through the dialed connection and each stream is served
as an inbound HTTP request (scada.go:26-60 provider config/capability,
:76-195 the listener shim feeding streams to the HTTP server).

The tpu-native analog keeps the capability but not the defunct SaaS
endpoint: the uplink only activates when an explicit ``atlas.endpoint`` is
configured (there is no hardcoded third-party default). Transport is the
framework's own framed-JSON mux (nomad_tpu.rpc) in the reverse direction —
the provider dials out, then answers broker-originated request frames:

    broker -> provider: {"seq": n, "method": "http",
                         "args": {"verb", "path", "body"}}
    provider -> broker: {"seq": n, "error": null,
                         "result": {"status", "headers", "body"}}

Each request is proxied to the agent's real HTTP listener, so the uplink
serves exactly the /v1 surface with identical envelopes and index headers
(the same property the reference gets by handing yamux streams to the
shared HTTP server). ``UplinkBroker`` is the in-process broker used by
tests and by anyone standing up their own dashboard tier.
"""

from __future__ import annotations

import hmac
import http.client
import json
import logging
import socket
import threading
from typing import Any, Dict, Optional

from nomad_tpu import __version__
from nomad_tpu.structs import MAX_QUERY_TIME, MAX_QUERY_TIME_PAD
from nomad_tpu.rpc import (
    SEND_TIMEOUT,
    _hard_close,
    _recv_frame,
    _send_frame,
    _set_send_timeout,
    serve_frames,
)


def _auth_proof(token: str, nonce: str, infrastructure: str) -> str:
    """HMAC-SHA256 over the broker's fresh nonce + the infrastructure
    name, keyed by the shared token. Binding the infrastructure stops a
    proof observed for one infra being spliced onto a handshake for
    another; the fresh nonce stops replay outright."""
    import hashlib

    return hmac.new(
        token.encode(), f"{nonce}:{infrastructure}".encode(), hashlib.sha256
    ).hexdigest()


def _split_endpoint(endpoint: str) -> tuple:
    """host:port split tolerating bracketed IPv6 ([::1]:7545).
    Raises ValueError on portless, non-numeric-port, or bare-IPv6
    endpoints so misconfiguration fails fast at agent construction, not
    silently in the dial loop."""
    host, sep, port = endpoint.rpartition(":")
    if not sep or not host or not port.isdigit():
        raise ValueError(f"uplink endpoint {endpoint!r} must be host:port")
    if ":" in host and not (host.startswith("[") and host.endswith("]")):
        raise ValueError(
            f"IPv6 uplink endpoint {endpoint!r} must be bracketed: [host]:port"
        )
    return host.strip("[]"), int(port)


def _enable_keepalive(sock: socket.socket) -> None:
    """Kernel TCP keepalives: detect a silently-dead peer (NAT mapping
    expiry, power loss — no FIN ever arrives) within ~75s so the recv
    loop unblocks and the provider redials. The reference gets this from
    yamux keepalives (scada.go transport)."""
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    for opt, val in (("TCP_KEEPIDLE", 30), ("TCP_KEEPINTVL", 15),
                     ("TCP_KEEPCNT", 3)):
        if hasattr(socket, opt):
            sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)

# Reconnect backoff (scada.go DefaultBackoff posture: bounded retry).
BACKOFF_BASE = 0.25
BACKOFF_MAX = 15.0
HANDSHAKE_TIMEOUT = 10.0


def scada_unavailable_reason() -> str:
    return (
        "no uplink endpoint configured: the reference dials a hardcoded "
        "third-party SaaS (scada.hashicorp.com); nomad-tpu only uplinks to "
        "an explicit atlas.endpoint (see nomad_tpu.scada.UplinkBroker)"
    )


class UplinkProvider:
    """Agent-side uplink (scada.go ProviderService/ProviderConfig analog).

    Dials ``endpoint``, handshakes with infrastructure/token, then serves
    broker-originated "http" frames by proxying them to the local agent
    HTTP listener. Redials with capped exponential backoff on any failure.
    """

    def __init__(self, endpoint: str, infrastructure: str, token: str,
                 http_addr: str, meta: Optional[Dict[str, str]] = None,
                 logger: Optional[logging.Logger] = None,
                 tls_context=None):
        self.endpoint = endpoint
        _split_endpoint(endpoint)  # fail fast on a malformed endpoint
        self.infrastructure = infrastructure
        self.token = token
        # Optional ssl.SSLContext for the dialed tunnel (the reference
        # SCADA client dialed its broker over TLS). Auth never depends on
        # it: the token itself stays off the wire either way (see
        # _session's challenge-response).
        self.tls_context = tls_context
        # http_addr is "host:port" of the agent's own HTTP listener.
        self.http_addr = http_addr
        self.meta = dict(meta or {})
        self.logger = logger or logging.getLogger("nomad_tpu.scada")
        self._shutdown = threading.Event()
        self._sock_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="scada-uplink"
        )
        self.sessions = 0  # completed handshakes, for Stats()/tests

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._sock_lock:
            sock = self._sock
            self._sock = None
        if sock is not None:
            _hard_close(sock)

    # -- dial loop -----------------------------------------------------------

    def _run(self) -> None:
        backoff = BACKOFF_BASE
        failures = 0
        while not self._shutdown.is_set():
            served = self.sessions
            try:
                self._session()
            except _AuthError as e:
                # Bad token/infrastructure: retrying fast is pointless.
                self.logger.warning("uplink: broker rejected handshake: %s", e)
                backoff = BACKOFF_MAX
            except Exception as e:
                failures += 1
                # Persistent dial failures surface at warning so an
                # unreachable endpoint is visible in normal logs.
                log = (self.logger.warning if failures % 8 == 0
                       else self.logger.debug)
                log("uplink: session failed (%d consecutive): %s",
                    failures, e)
            if self.sessions > served:
                failures = 0
                # A completed handshake resets backoff even though the
                # session ultimately ended in a disconnect exception.
                backoff = BACKOFF_BASE
            if self._shutdown.wait(backoff):
                return
            backoff = min(backoff * 2, BACKOFF_MAX)

    def _session(self) -> None:
        host, port = _split_endpoint(self.endpoint)
        sock = socket.create_connection((host, port), timeout=HANDSHAKE_TIMEOUT)
        if self.tls_context is not None:
            sock = self.tls_context.wrap_socket(
                sock, server_hostname=host.strip("[]")
            )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Kernel send timeout: a broker that stops reading must not wedge
        # handler threads in sendall under the write lock (same discipline
        # as rpc.py conns).
        _set_send_timeout(sock, SEND_TIMEOUT)
        _enable_keepalive(sock)
        with self._sock_lock:
            if self._shutdown.is_set():
                _hard_close(sock)
                return
            self._sock = sock
        try:
            # Challenge-response handshake: the shared token NEVER crosses
            # the wire (an on-path observer of a plaintext tunnel learns
            # nothing replayable — the proof binds a fresh broker nonce +
            # the infrastructure name).
            _send_frame(sock, {
                "seq": 0, "method": "handshake", "args": {
                    "service": "nomad-tpu",
                    "version": __version__,
                    "infrastructure": self.infrastructure,
                    "auth": "hmac-v1",
                    "capabilities": {"http": 1},
                    "meta": self.meta,
                },
            })
            resp = _recv_frame(sock)
            if resp.get("error"):
                raise _AuthError(resp["error"])
            nonce = str((resp.get("result") or {}).get("nonce", ""))
            if not nonce:
                raise _AuthError("broker sent no auth challenge")
            _send_frame(sock, {
                "seq": 1, "method": "auth", "args": {
                    "proof": _auth_proof(self.token, nonce,
                                         self.infrastructure),
                },
            })
            resp = _recv_frame(sock)
            if resp.get("error"):
                raise _AuthError(resp["error"])
            sock.settimeout(None)
            self.sessions += 1
            self.logger.info("uplink: connected to %s as %r",
                             self.endpoint, self.infrastructure)
            self._serve(sock)
        finally:
            with self._sock_lock:
                if self._sock is sock:
                    self._sock = None
            _hard_close(sock)

    def _serve(self, sock: socket.socket) -> None:
        """Answer broker request frames until the connection drops —
        the shared rpc.py serve loop (per-request threads, write lock,
        bounded in-flight)."""
        serve_frames(sock, self._dispatch, self._shutdown, self.logger,
                     thread_name="scada-stream")

    def _dispatch(self, req: Any) -> dict:
        if not isinstance(req, dict):
            return {"seq": None, "error": "malformed frame", "result": None}
        seq = req.get("seq")
        method = req.get("method", "")
        if method == "ping":
            return {"seq": seq, "error": None, "result": "pong"}
        if method != "http":
            return {"seq": seq, "error": f"unknown method {method!r}",
                    "result": None}
        args = req.get("args", {})
        try:
            return {"seq": seq, "error": None,
                    "result": self._proxy_http(args)}
        except Exception as e:
            return {"seq": seq, "error": f"{type(e).__name__}: {e}",
                    "result": None}

    def _proxy_http(self, args: dict) -> dict:
        """One tunneled HTTP exchange against the agent's own listener —
        the mux-frame analog of scada.go's listener shim handing a yamux
        stream to the shared HTTP server."""
        verb = args.get("verb", "GET").upper()
        path = args.get("path", "/")
        body = args.get("body")
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        # The local hop lives as long as the CALLER's budget (timeout_s,
        # sent by UplinkBroker.http) so an abandoned long-poll frees its
        # in-flight slot when the broker side gives up — capped just past
        # the server's MaxQueryTime clamp.
        raw = args.get("timeout_s")
        try:
            budget = 30.0 if raw is None else float(raw)
        except (TypeError, ValueError):
            budget = 30.0
        cap = MAX_QUERY_TIME + MAX_QUERY_TIME_PAD
        conn = http.client.HTTPConnection(
            self.http_addr, timeout=max(1.0, min(budget, cap))
        )
        try:
            conn.request(verb, path, body=data, headers=headers)
            resp = conn.getresponse()
            payload = resp.read().decode("utf-8", "replace")
            return {
                "status": resp.status,
                "headers": {k: v for k, v in resp.getheaders()
                            if k.lower().startswith("x-nomad-")
                            or k.lower() == "content-type"},
                "body": payload,
            }
        finally:
            conn.close()

    def stats(self) -> Dict[str, Any]:
        with self._sock_lock:
            connected = self._sock is not None
        return {"endpoint": self.endpoint, "connected": connected,
                "sessions": self.sessions}


class _AuthError(Exception):
    pass


class _BrokerSession:
    """Broker-side view of one connected provider."""

    def __init__(self, sock: socket.socket, handshake: dict):
        self.sock = sock
        self.handshake = handshake
        self.infrastructure = handshake.get("infrastructure", "")
        self.write_lock = threading.Lock()
        self.lock = threading.Lock()
        self.pending: Dict[int, "_SessWaiter"] = {}
        self.seq = 0
        self.dead = False


class _SessWaiter:
    __slots__ = ("event", "resp")

    def __init__(self):
        self.event = threading.Event()
        self.resp: Optional[dict] = None


class UplinkBroker:
    """In-process uplink broker: the dashboard-tier counterparty a
    deployment (or a test) runs to reach agents behind NAT. Accepts
    provider dials, validates the token, and exposes ``http()`` to issue
    requests through any connected session."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 token: str = "", logger: Optional[logging.Logger] = None,
                 ssl_context=None):
        self.token = token
        self.logger = logger or logging.getLogger("nomad_tpu.scada.broker")
        self._ssl_context = ssl_context
        self._listener = socket.create_server((host, port))
        self.addr = "{}:{}".format(*self._listener.getsockname())
        self._shutdown = threading.Event()
        self._lock = threading.Lock()
        self._sessions: Dict[str, _BrokerSession] = {}
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"scada-broker-{self.addr}").start()

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for sess in sessions:
            _hard_close(sess.sock)

    def sessions(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v.handshake) for k, v in self._sessions.items()}

    def drop(self, infrastructure: str) -> None:
        """Sever a session (test hook for provider reconnect)."""
        with self._lock:
            sess = self._sessions.pop(infrastructure, None)
        if sess is not None:
            sess.dead = True
            _hard_close(sess.sock)

    # -- accept + demux ------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        accepted = False
        try:
            conn.settimeout(HANDSHAKE_TIMEOUT)
            if self._ssl_context is not None:
                conn = self._ssl_context.wrap_socket(conn, server_side=True)
            _set_send_timeout(conn, SEND_TIMEOUT)
            _enable_keepalive(conn)
            hello = _recv_frame(conn)
            if not isinstance(hello, dict) or not isinstance(
                hello.get("args", {}), dict
            ):
                return
            args = hello.get("args", {})
            if hello.get("method") != "handshake":
                _send_frame(conn, {"seq": hello.get("seq"),
                                   "error": "handshake required",
                                   "result": None})
                return
            # Challenge-response: a fresh nonce per session; the provider
            # proves token possession without ever sending it. Legacy
            # raw-token handshakes are refused — the secret must not be
            # coaxed onto the wire by a spoofed broker.
            if "token" in args:
                _send_frame(conn, {"seq": hello.get("seq"),
                                   "error": "raw-token handshake refused; "
                                            "use hmac-v1 challenge auth",
                                   "result": None})
                return
            import secrets

            nonce = secrets.token_hex(16)
            _send_frame(conn, {"seq": hello.get("seq"), "error": None,
                               "result": {"nonce": nonce}})
            auth = _recv_frame(conn)
            if not isinstance(auth, dict):
                auth = {}
            proof = str((auth.get("args") or {}).get("proof", ""))
            want = _auth_proof(self.token,
                               nonce, str(args.get("infrastructure", "")))
            if auth.get("method") != "auth" or not hmac.compare_digest(
                proof, want
            ):
                _send_frame(conn, {"seq": auth.get("seq"),
                                   "error": "invalid token",
                                   "result": None})
                return
            _send_frame(conn, {"seq": auth.get("seq"), "error": None,
                               "result": {"ok": True}})
            conn.settimeout(None)
            accepted = True
        except Exception:
            # Non-protocol bytes (a TLS probe, a port scan) raise RPCError
            # or worse — never let a daemon thread die with a traceback.
            # Logged at debug with the stack so an internal handshake bug
            # is still distinguishable from scanner noise.
            self.logger.debug("broker: handshake failed", exc_info=True)
            return
        finally:
            if not accepted:
                conn.close()
        # args can't carry the secret: raw-token hellos were refused above
        # and the hmac proof lived in the separate auth frame.
        sess = _BrokerSession(conn, args)
        with self._lock:
            old = self._sessions.pop(sess.infrastructure, None)
            self._sessions[sess.infrastructure] = sess
        if old is not None:
            _hard_close(old.sock)
        self.logger.info("broker: provider %r connected",
                         sess.infrastructure)
        try:
            while not self._shutdown.is_set():
                resp = _recv_frame(conn)
                with sess.lock:
                    waiter = sess.pending.pop(resp.get("seq"), None)
                if waiter is not None:
                    waiter.resp = resp
                    waiter.event.set()
        except Exception:
            # Includes RPCError from an oversized frame: the session is
            # torn down below and the provider redials.
            pass
        finally:
            sess.dead = True
            with sess.lock:
                pending = list(sess.pending.values())
                sess.pending.clear()
            for waiter in pending:
                waiter.event.set()
            with self._lock:
                if self._sessions.get(sess.infrastructure) is sess:
                    self._sessions.pop(sess.infrastructure, None)
            conn.close()

    # -- request API ---------------------------------------------------------

    def _request(self, infrastructure: str, method: str, args: dict,
                 timeout: float) -> Any:
        """Shared request lifecycle: find the session, register a waiter,
        send, wait. Raises KeyError if no session, RuntimeError on tunnel
        errors or a remote error frame."""
        with self._lock:
            sess = self._sessions.get(infrastructure)
        if sess is None or sess.dead:
            raise KeyError(f"no uplink session for {infrastructure!r}")
        with sess.lock:
            if sess.dead:
                # The reader's cleanup may already have drained pending;
                # registering after that would never be signaled.
                raise RuntimeError("uplink session died")
            sess.seq += 1
            seq = sess.seq
            waiter = _SessWaiter()
            sess.pending[seq] = waiter
        try:
            with sess.write_lock:
                _send_frame(sess.sock, {"seq": seq, "method": method,
                                        "args": args})
        except Exception as e:
            # Catches serialization TypeErrors too — the waiter must not
            # leak. A transport failure may have left a partial frame on
            # the wire, so the session is invalidated (ConnPool.call's
            # posture on the same path); the provider will redial.
            with sess.lock:
                sess.pending.pop(seq, None)
            if isinstance(e, OSError):
                sess.dead = True
                _hard_close(sess.sock)
            raise RuntimeError(f"uplink send failed: {e}") from e
        if not waiter.event.wait(timeout):
            with sess.lock:
                sess.pending.pop(seq, None)
            raise RuntimeError("uplink request timed out")
        resp = waiter.resp
        if resp is None:
            raise RuntimeError("uplink session died")
        if resp.get("error"):
            raise RuntimeError(resp["error"])
        return resp["result"]

    def http(self, infrastructure: str, verb: str, path: str,
             body: Any = None, timeout: float = 30.0) -> dict:
        """Issue an HTTP request through a connected provider; returns
        {"status", "headers", "body"}. ``timeout`` is also shipped to the
        provider so its local hop (and in-flight slot) never outlives the
        caller — pass a larger value for blocking queries (?index&wait)."""
        return self._request(
            infrastructure, "http",
            {"verb": verb, "path": path, "body": body,
             "timeout_s": timeout}, timeout,
        )

    def ping(self, infrastructure: str, timeout: float = 10.0) -> bool:
        try:
            return self._request(infrastructure, "ping", {}, timeout) == "pong"
        except (KeyError, RuntimeError):
            return False
