"""Atlas/SCADA stub.

Reference: /root/reference/command/agent/scada.go — dials HashiCorp's Atlas
infrastructure and exposes the agent HTTP API over a yamux tunnel so the
hosted dashboard can reach it (scada.go:26-60, listener shim :76-195).

That capability is deliberately not reproduced: it exists solely to uplink
to a third-party SaaS endpoint (scada.hashicorp.com), which a cluster
scheduler deployment on TPU pods has no use for and which this build's
environment cannot reach. The ``atlas`` config block still parses
(nomad_tpu.agent_config.Atlas) so reference configs load unchanged; when it
is set, the agent logs why the uplink is off.
"""

from __future__ import annotations


def scada_unavailable_reason() -> str:
    return (
        "the Atlas/SCADA uplink (a tunnel to HashiCorp's hosted dashboard) "
        "is not implemented in nomad-tpu; the atlas config block is parsed "
        "and ignored"
    )
