"""Benchmark: the BASELINE.json north-star configuration.

Config 3 of BASELINE.md: a 10k-node cluster and a single batch job with
100k task groups (driver + datacenter constraints), placed by the TPU
dense-solve scheduler. The reference publishes no numbers (BASELINE.md);
the driver-defined target is p50 < 200ms for the placement solve, i.e.
500k placements/sec.

Measured phases per evaluation:
- solve: TPUStack.select_many end-to-end — eligibility masks, usage
  tensorization, the device round-solve, and placement extraction. This is
  the reformulated Stack.Select loop (the north-star metric).
- e2e:   the full TPUGenericScheduler.process, including Python-side diff,
  100k Allocation-object materialization and plan/state apply (the part a
  native runtime will take over in later rounds).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import contextlib
import gc
import json
import os
import statistics
import sys
import threading
import time
import traceback

N_NODES = int(os.environ.get("NOMAD_TPU_BENCH_NODES", 10_000))
N_TASKS = int(os.environ.get("NOMAD_TPU_BENCH_TASKS", 100_000))
RUNS = int(os.environ.get("NOMAD_TPU_BENCH_RUNS", 9))
TARGET_PLACEMENTS_PER_SEC = N_TASKS / 0.2  # the north star: tasks in 200ms p50

# A cold tunneled TPU can take minutes to answer jax.devices(); the bench
# REQUIRES the device backend, so it waits generously instead of letting the
# scheduler factories silently fall back to the host path (round-1 failure
# mode: 15s probe timeout -> host fallback -> empty timing list -> crash).
DEVICE_WAIT_S = float(os.environ.get("NOMAD_TPU_BENCH_DEVICE_WAIT", "600"))
ALLOW_CPU = os.environ.get("NOMAD_TPU_BENCH_ALLOW_CPU", "") == "1"
# Headline-only: skip the aux configs, the coalesced run and the breakdown
# sweep. The watcher's first capture in a relay window uses this — windows
# have historically died within minutes, so the first number banked must be
# the cheapest one that still answers "what does the TPU do at 10k nodes".
HEADLINE_ONLY = os.environ.get("NOMAD_TPU_BENCH_HEADLINE_ONLY", "") == "1"


_EMITTED = threading.Event()

# Mid-run device death (the relay tunnel has died DURING a bench run,
# wedging the next device op forever) would otherwise produce NO output at
# all — the except-path only covers failures that raise. The watchdog
# guarantees the one-line contract regardless.
WATCHDOG_S = float(os.environ.get("NOMAD_TPU_BENCH_WATCHDOG", "2400"))


def emit(payload: dict) -> None:
    """The one-line JSON contract: always printed, even on failure.
    The flag is set BEFORE printing so a watchdog expiring mid-emit can
    never add a second line."""
    _EMITTED.set()
    print(json.dumps(payload), flush=True)


def _dist(times: list, warmup: int) -> dict:
    """Dispersion summary for a list of wall times (seconds): p10/p50/p90
    in ms plus run count and warmup policy. Same-box captures have been
    observed to swing ~2x between single samples (GC, dispatcher timing),
    so every published number carries its spread instead of a bare p50."""
    ts = sorted(times)
    if len(ts) >= 3:
        qs = statistics.quantiles(ts, n=10, method="inclusive")
        p10, p90 = qs[0], qs[8]
    else:
        p10, p90 = ts[0], ts[-1]
    return {
        "p10_ms": round(p10 * 1000, 3),
        "p50_ms": round(statistics.median(ts) * 1000, 3),
        "p90_ms": round(p90 * 1000, 3),
        "runs": len(ts),
        "warmup_runs": warmup,
    }


@contextlib.contextmanager
def _quiesced():
    """Timed-region hygiene: collect pending garbage BEFORE the clock
    starts, then keep the collector off so a generation-2 pass (the
    multi-ms stalls behind the observed 41M->68M placements/s swings)
    cannot land inside a measured run."""
    gc.collect()
    was_enabled = gc.isenabled()
    gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()


def _start_watchdog() -> None:
    def run():
        if _EMITTED.wait(WATCHDOG_S):
            return
        status = {}
        try:
            from nomad_tpu.scheduler import device_probe_status

            status = device_probe_status()
        except Exception:
            pass
        if _EMITTED.is_set():
            # The run finished while we were gathering the probe status:
            # the real line is already out, never add a second.
            return
        emit({
            "metric": "placements_per_sec@10k_nodes_x_100k_tasks",
            "value": 0,
            "unit": "placements/s",
            "vs_baseline": 0,
            "backend": "unknown",
            "error": (
                f"bench watchdog: no result after {WATCHDOG_S:.0f}s — a "
                "device op is wedged mid-run (relay died during the "
                "bench?), or the CPU-fallback measurement itself overran "
                "the budget; probe status attached"
            ),
            "probe": status,
        })
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)

    threading.Thread(target=run, daemon=True, name="bench-watchdog").start()


def acquire_device():
    """Block until the device solver is up; returns the backend name.

    Raises RuntimeError if the backend cannot be acquired or is the CPU
    (unless NOMAD_TPU_BENCH_ALLOW_CPU=1 for local smoke runs).
    """
    from nomad_tpu.scheduler import device_probe_status, wait_for_device

    solver = wait_for_device(timeout=DEVICE_WAIT_S)
    status = device_probe_status()
    if solver is None:
        raise RuntimeError(
            f"device backend unavailable after {DEVICE_WAIT_S:.0f}s: {status}"
        )
    backend = str(status.get("backend", "unknown"))
    if backend == "cpu" and not ALLOW_CPU:
        raise RuntimeError(
            "bench requires a TPU backend but jax initialized on the CPU; "
            "set NOMAD_TPU_BENCH_ALLOW_CPU=1 to force a local smoke run"
        )
    return backend


def build_cluster():
    from nomad_tpu import structs
    from nomad_tpu.structs import (
        Constraint,
        Job,
        Node,
        Resources,
        RestartPolicy,
        Task,
        TaskGroup,
        generate_uuid,
    )

    nodes = []
    for i in range(N_NODES):
        nodes.append(
            Node(
                id=f"node-{i:05d}",
                datacenter="dc1" if i % 2 == 0 else "dc2",
                name=f"n{i}",
                attributes={"kernel.name": "linux", "driver.exec": "1"},
                resources=Resources(
                    cpu=4000, memory_mb=8192, disk_mb=100 * 1024, iops=150
                ),
                status=structs.NODE_STATUS_READY,
            )
        )

    job = Job(
        region="global",
        id=generate_uuid(),
        name="bench-batch",
        type=structs.JOB_TYPE_BATCH,
        priority=50,
        datacenters=["dc1"],  # datacenter constraint: half the cluster
        constraints=[
            Constraint(l_target="$attr.kernel.name", r_target="linux", operand="=")
        ],
        task_groups=[
            TaskGroup(
                name="work",
                count=N_TASKS,
                restart_policy=RestartPolicy(attempts=1, interval=600.0, delay=5.0),
                tasks=[
                    Task(
                        name="work",
                        driver="exec",
                        resources=Resources(cpu=100, memory_mb=128),
                    )
                ],
            )
        ],
    )
    return nodes, job


class _TimingStack:
    """Wraps TPUStack.solve_group to capture the solve wall time: masks +
    usage tensorization + device dispatch + readback (+ any host work the
    scheduler overlaps with the transfer)."""

    solve_times = []

    @classmethod
    def install(cls):
        from nomad_tpu.tpu.solver import TPUStack

        def wrap(orig):
            def timed(self, tg, count, overlap=None):
                start = time.perf_counter()
                out = orig(self, tg, count, overlap=overlap)
                cls.solve_times.append(time.perf_counter() - start)
                return out

            return timed

        TPUStack.solve_group = wrap(TPUStack.solve_group)
        TPUStack.solve_group_counts = wrap(TPUStack.solve_group_counts)


def build_state(nodes, job):
    """One live store, as on a real server: every eval snapshots it and the
    device mirror stays warm across evals (nomad_tpu.tpu.mirror.MirrorCache)."""
    from nomad_tpu.state import StateStore

    state = StateStore()
    for i, node in enumerate(nodes):
        state.upsert_node(i + 1, node)
    state.upsert_job(N_NODES + 1, job)
    return state


def run_once(state, job, trace_ids=None):
    """One scheduler pass. When ``trace_ids`` is a list, the eval runs
    under a root trace span (the worker posture) so the solver records
    its per-stage spans, and the eval id is appended for later span
    retrieval — the tracing-overhead arm of the headline."""
    import logging

    from nomad_tpu import structs, trace
    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.structs import Evaluation, PlanResult, generate_uuid

    class _Planner:
        plan = None

        def submit_plan(self, plan):
            # Real leader-side verification (plan_apply.go evaluatePlan via
            # the native bulk verifier); the raft commit itself is elided.
            from nomad_tpu.server.plan_apply import evaluate_plan

            _Planner.plan = plan
            result = evaluate_plan(state.snapshot(), plan)
            result.alloc_index = N_NODES + 2
            return result, None

        def update_eval(self, ev):
            pass

        def create_eval(self, ev):
            pass

    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    sched = new_scheduler(
        "tpu-batch", state.snapshot(), _Planner(), logging.getLogger("bench")
    )
    start = time.perf_counter()
    if trace_ids is not None:
        span = trace.get_tracer().start_span(ev.id, "eval", root=True)
        with trace.use_span(span):
            sched.process(ev)
        span.finish()
        trace_ids.append(ev.id)
    else:
        sched.process(ev)
    e2e = time.perf_counter() - start

    plan = _Planner.plan
    placed = sum(len(v) for v in plan.node_allocation.values())
    placed += sum(b.n for b in plan.alloc_batches)
    return e2e, placed


COALESCE_EVALS = 8


def run_coalesced(nodes):
    """Aux phase through the REAL server pipeline: COALESCE_EVALS jobs
    enqueued at the broker, drained by batched workers
    (eval_batch_size, server/worker.py), their device solves stacking into
    vmapped dispatches (ops/coalesce.py), plans through the plan queue and
    applier. The broker-path analog of the reference's optimistic worker
    concurrency (nomad/worker.go:45-125 + eval_broker.go:215-246).
    Returns (wall_seconds, total_placed, dispatches)."""
    from nomad_tpu import structs
    from nomad_tpu.ops.coalesce import GLOBAL_SOLVER
    from nomad_tpu.server import Server, ServerConfig
    from nomad_tpu.structs import Evaluation, generate_uuid

    srv = Server(ServerConfig(
        scheduler_backend="tpu",
        num_schedulers=2,
        eval_batch_size=COALESCE_EVALS,
        periodic_dispatch=False,
    ))
    try:
        for node in nodes:
            srv.raft.apply("node_register", {"node": node})
        jobs = []
        for i in range(2 * COALESCE_EVALS):  # half warmup, half timed
            _nodes, job = build_cluster()
            # Warm jobs use a tiny count on the SAME columnar path (>128
            # rides the water-fill; compile shapes key on node bucket and
            # batch size, not the count value), so warmup doesn't consume
            # the capacity the timed batch is measured against.
            job.task_groups[0].count = (
                129 if i < COALESCE_EVALS else N_TASKS // COALESCE_EVALS
            )
            srv.raft.apply("job_register", {"job": job})
            jobs.append(job)

        # Warmup batch: the SAME concurrent shape as the timed batch, so
        # the vmapped coalesced-dispatch programs (batch-size buckets)
        # compile before timing — steady-state throughput is the metric;
        # cold-compile behavior is covered by the prewarm/nack-touch tests.
        warm_jobs, jobs = jobs[:COALESCE_EVALS], jobs[COALESCE_EVALS:]
        warm_evals = [
            Evaluation(
                id=generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id, status=structs.EVAL_STATUS_PENDING,
            )
            for job in warm_jobs
        ]
        srv.start()
        srv.raft.apply("eval_update", {"evals": warm_evals})
        _wait_evals_complete(srv, [ev.id for ev in warm_evals], timeout=300.0)
        # Worker drain timing decides which eval-axis batch buckets the
        # warm batch hit; compile the rest deterministically.
        from nomad_tpu.ops.binpack import bucket
        from nomad_tpu.ops.coalesce import warm_batch_shapes

        dc1_nodes = sum(1 for n in nodes if n.datacenter == "dc1")
        warm_batch_shapes(bucket(dc1_nodes))

        evals = [
            Evaluation(
                id=generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id, status=structs.EVAL_STATUS_PENDING,
            )
            for job in jobs
        ]
        dispatches0 = GLOBAL_SOLVER.dispatches
        start = time.perf_counter()
        srv.raft.apply("eval_update", {"evals": evals})
        _wait_evals_complete(srv, [ev.id for ev in evals], timeout=300.0)
        wall = time.perf_counter() - start

        placed = 0
        for job in jobs:
            placed += sum(
                1 for a in srv.state_store.allocs_by_job(job.id)
                if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
            )
        return wall, placed, GLOBAL_SOLVER.dispatches - dispatches0
    finally:
        srv.shutdown()


def run_simload():
    """Control-plane arm: placements/s and plan latency through the FULL
    register→heartbeat→eval→broker→worker→solver→plan_apply→raft path —
    a simcluster scenario against a real ClusterServer over real RPC
    (nomad_tpu/simcluster). The headline above measures the solver in
    isolation; this number is the same metric with the whole control
    plane in the loop, so the two together bound where the pipeline (not
    the kernel) is the ceiling. Scenario via NOMAD_TPU_BENCH_SIMLOAD
    (default steady-1k: cheap enough to ride every capture; the 10k-node
    artifacts are banked by tools/simload.py runs)."""
    from nomad_tpu.simcluster import run_scenario

    name = os.environ.get("NOMAD_TPU_BENCH_SIMLOAD", "steady-1k")
    art = run_scenario(name, seed=42)
    return {
        "scenario": name,
        "n_nodes": art["n_nodes"],
        "placed": art["placements"]["placed"],
        "placements_per_sec": art["placements"]["placements_per_sec"],
        "plan_latency_ms_p50": art["plan_latency_ms"].get("p50_ms"),
        "plan_latency_ms_p95": art["plan_latency_ms"].get("p95_ms"),
        "device_dispatches": art["placements"]["device_dispatches"],
        "broker_ready_peak": art["peaks"]["broker_ready"],
        "plan_queue_depth_peak": art["peaks"]["plan_queue_depth"],
        "heartbeat_timers": art["heartbeat"]["timers"],
        "registration_nodes_per_sec": art["registration"]["nodes_per_sec"],
    }


def _wait_evals_complete(srv, eval_ids, timeout):
    from nomad_tpu import structs

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        done = [srv.state_store.eval_by_id(i) for i in eval_ids]
        if all(
            d is not None and d.status != structs.EVAL_STATUS_PENDING
            for d in done
        ):
            # A failed/canceled eval must surface as a bench error, not a
            # silently-low placement count.
            bad = {
                d.id: d.status for d in done
                if d.status != structs.EVAL_STATUS_COMPLETE
            }
            if bad:
                raise RuntimeError(f"evals did not complete: {bad}")
            return
        time.sleep(0.02)
    raise TimeoutError(f"evals not complete after {timeout}s")


def _mk_nodes(n, cpu=4000, mem=8192, with_net=True):
    from nomad_tpu import structs
    from nomad_tpu.structs import NetworkResource, Node, Resources

    nodes = []
    for i in range(n):
        res = Resources(cpu=cpu, memory_mb=mem, disk_mb=100 * 1024, iops=150)
        if with_net:
            res.networks = [NetworkResource(
                device="eth0", cidr="192.168.0.0/16",
                ip=f"192.168.{i % 250}.1", mbits=1000,
            )]
        nodes.append(Node(
            id=f"bench-{i:06d}",
            datacenter="dc1",
            name=f"n{i}",
            attributes={"kernel.name": "linux", "driver.exec": "1"},
            resources=res,
            status=structs.NODE_STATUS_READY,
        ))
    return nodes


def _eval_once(state, job, factory, alloc_index):
    """One scheduler pass against a live store; plans verified and applied
    to state (the Harness posture). Returns (e2e_seconds, placed)."""
    import logging

    from nomad_tpu import structs
    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.server.plan_apply import evaluate_plan
    from nomad_tpu.structs import Evaluation, generate_uuid

    applied = {"placed": 0}

    class _P:
        def submit_plan(self, plan):
            result = evaluate_plan(state.snapshot(), plan)
            result.alloc_index = alloc_index
            allocs = []
            for lst in result.node_update.values():
                allocs.extend(lst)
            for lst in result.node_allocation.values():
                allocs.extend(lst)
                applied["placed"] += len(lst)
            if allocs:
                state.upsert_allocs(alloc_index, allocs)
            # Columnar results commit columnar, exactly like the FSM.
            if result.alloc_batches:
                state.upsert_alloc_blocks(alloc_index, result.alloc_batches)
                applied["placed"] += sum(b.n for b in result.alloc_batches)
            if result.update_batches:
                state.apply_update_batches(
                    alloc_index, result.update_batches
                )
            return result, None

        def update_eval(self, ev):
            pass

        def create_eval(self, ev):
            pass

    ev = Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )
    sched = new_scheduler(
        factory, state.snapshot(), _P(), logging.getLogger("bench")
    )
    start = time.perf_counter()
    sched.process(ev)
    return time.perf_counter() - start, applied["placed"]


def _scaled(n):
    """Scale aux-config sizes with the headline override (smoke runs)."""
    return max(8, int(n * (N_NODES / 10_000)))


AUX_RUNS = max(1, int(os.environ.get("NOMAD_TPU_BENCH_AUX_RUNS", 3)))

# Config-5 pass/fail floors, env-overridable for new hardware baselines.
CONFIG5_INPLACE_BAR = float(
    os.environ.get("NOMAD_TPU_CONFIG5_INPLACE_BAR", 100_000)
)
CONFIG5_ROLLED_BAR = float(
    os.environ.get("NOMAD_TPU_CONFIG5_ROLLED_BAR", 5_000)
)


def run_config2():
    """BASELINE config 2: 1k-node / 5k-taskgroup service bin-pack, CPU+mem
    only."""
    from nomad_tpu import structs
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import Job, Resources, RestartPolicy, Task, TaskGroup, generate_uuid

    n_nodes, count = _scaled(1000), _scaled(5000)
    state = StateStore()
    for i, node in enumerate(_mk_nodes(n_nodes, cpu=14000, mem=30000,
                                       with_net=False)):
        state.upsert_node(i + 1, node)
    job = Job(
        region="global", id=generate_uuid(), name="bench-svc",
        type=structs.JOB_TYPE_SERVICE, priority=50, datacenters=["dc1"],
        task_groups=[TaskGroup(
            name="svc", count=count,
            restart_policy=RestartPolicy(attempts=2, interval=600.0, delay=5.0),
            tasks=[Task(name="t", driver="exec",
                        resources=Resources(cpu=100, memory_mb=256))],
        )],
    )
    state.upsert_job(n_nodes + 1, job)
    _eval_once(StateStoreView(state), job, "tpu-service", n_nodes + 2)  # warm
    # Each measured run gets a fresh alloc-free clone so every sample sees
    # identical initial conditions (a repeat eval on mutated state would
    # diff to zero placements).
    times = []
    placed = 0
    with _quiesced():
        for _ in range(AUX_RUNS):
            e2e, placed = _eval_once(
                StateStoreView(state), job, "tpu-service", n_nodes + 2
            )
            times.append(e2e)
    e2e = statistics.median(times)
    return {
        "n_nodes": n_nodes, "count": count, "placed": placed,
        "e2e_ms": round(e2e * 1000, 2),
        "e2e": _dist(times, warmup=1),
        "placements_per_sec": round(placed / e2e, 1) if e2e else 0,
    }


class StateStoreView:
    """Throwaway shim: a fresh store clone for warmups so the measured run
    sees the original (no existing allocs)."""

    def __new__(cls, state):
        import copy

        from nomad_tpu.state import StateStore

        s = StateStore()
        for i, node in enumerate(state.nodes()):
            s.upsert_node(i + 1, node)
        for job in state.jobs():
            s.upsert_job(10_000_000, job)
        return s


def run_config4():
    """BASELINE config 4: system scheduler, one-per-node with hard
    constraints, 10k nodes."""
    from nomad_tpu import structs
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import (
        Constraint, Job, Resources, RestartPolicy, Task, TaskGroup,
        generate_uuid,
    )

    n_nodes = _scaled(10_000)
    state = StateStore()
    for i, node in enumerate(_mk_nodes(n_nodes, with_net=False)):
        state.upsert_node(i + 1, node)
    job = Job(
        region="global", id=generate_uuid(), name="bench-sys",
        type=structs.JOB_TYPE_SYSTEM, priority=50, datacenters=["dc1"],
        constraints=[Constraint(
            l_target="$attr.kernel.name", r_target="linux", operand="=",
        )],
        task_groups=[TaskGroup(
            name="sys", count=1,
            restart_policy=RestartPolicy(attempts=2, interval=600.0, delay=5.0),
            tasks=[Task(name="t", driver="exec",
                        resources=Resources(cpu=50, memory_mb=64))],
        )],
    )
    state.upsert_job(n_nodes + 1, job)
    _eval_once(StateStoreView(state), job, "tpu-system", n_nodes + 2)  # warm
    # Steady-state posture: the mirror for each measured clone's node-table
    # generation is made resident BEFORE its timed eval (repeat evals in
    # production share a resident mirror; a cold build is not part of the
    # config-4 claim). Every sample runs on a fresh alloc-free clone so
    # the system scheduler has a full one-per-node placement to do.
    from nomad_tpu.server.plan_apply import _node_table
    from nomad_tpu.tpu.mirror import GLOBAL_MIRROR_CACHE

    times = []
    placed = 0
    with _quiesced():
        for _ in range(AUX_RUNS):
            clone = StateStoreView(state)
            snap = clone.snapshot()
            GLOBAL_MIRROR_CACHE.get(snap, job.datacenters)
            # The applier's columnar node table is likewise resident in
            # production (keyed by store generation, built by whichever
            # plan first verifies against it) — a cold build is not part
            # of the per-eval claim.
            _node_table(snap)
            e2e, placed = _eval_once(clone, job, "tpu-system", n_nodes + 2)
            times.append(e2e)
    e2e = statistics.median(times)
    return {
        "n_nodes": n_nodes, "placed": placed,
        "e2e_ms": round(e2e * 1000, 2),
        "e2e": _dist(times, warmup=1),
        "placements_per_sec": round(placed / e2e, 1) if e2e else 0,
    }


def run_config5():
    """BASELINE config 5: 50k nodes, existing allocs, rolling-update diff +
    anti-affinity — the object-diff and in-place machinery
    (/root/reference/scheduler/util.go:403-416 evictAndPlace)."""
    from nomad_tpu import structs
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import (
        Job, Resources, RestartPolicy, Task, TaskGroup, UpdateStrategy,
        generate_uuid,
    )

    n_nodes, count = _scaled(50_000), _scaled(10_000)
    state = StateStore()
    for i, node in enumerate(_mk_nodes(n_nodes, with_net=False)):
        state.upsert_node(i + 1, node)
    job = Job(
        region="global", id=generate_uuid(), name="bench-roll",
        type=structs.JOB_TYPE_SERVICE, priority=50, datacenters=["dc1"],
        update=UpdateStrategy(stagger=10.0, max_parallel=_scaled(1000)),
        task_groups=[TaskGroup(
            name="web", count=count,
            restart_policy=RestartPolicy(attempts=2, interval=600.0, delay=5.0),
            tasks=[Task(name="t", driver="exec",
                        resources=Resources(cpu=100, memory_mb=128))],
        )],
    )
    state.upsert_job(n_nodes + 1, job)
    # Phase 1 (unmeasured): initial placement seeds the existing allocs.
    _eval_once(state, job, "tpu-service", n_nodes + 2)
    # Deep-copies: existing allocs embed the job object, so an in-place
    # mutation would make the diff see no change.
    import copy

    # Phase 2a (measured): resource-only bump -> in-place update of all
    # `count` existing allocs (tasks_updated false, util.go:265-302; fit
    # re-checked with the new resources, util.go:344-358).
    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].resources.cpu += 7
    state.upsert_job(n_nodes + 3, job2)
    with _quiesced():
        inplace_e2e, _ = _eval_once(state, job2, "tpu-service", n_nodes + 4)

    # Phase 2b (measured): env change -> destructive update; rolling
    # evict+place capped at max_parallel (evictAndPlace, util.go:403-416)
    # with anti-affinity ranking against the survivors.
    job3 = copy.deepcopy(job2)
    job3.task_groups[0].tasks[0].env = {"V": "2"}
    state.upsert_job(n_nodes + 5, job3)
    with _quiesced():
        e2e, placed = _eval_once(state, job3, "tpu-service", n_nodes + 6)
    inplace_rate = round(count / inplace_e2e, 1) if inplace_e2e else 0
    rolled_rate = round(placed / e2e, 1) if e2e else 0
    return {
        "n_nodes": n_nodes, "existing": count,
        "inplace_updated": count,
        "inplace_e2e_ms": round(inplace_e2e * 1000, 2),
        "inplace_updates_per_sec": inplace_rate,
        "rolled": placed, "max_parallel": _scaled(1000),
        "e2e_ms": round(e2e * 1000, 2),
        "rolled_updates_per_sec": rolled_rate,
        # Pass/fail bars (full 50k-node scale): conservative floors under
        # the worst CPU-backend capture on record (BENCH_SELF_r04: in-place
        # 10k/58ms ≈ 171k/s, rolled 1k/120ms ≈ 8.3k/s) — a regression
        # below them means the update machinery got slower, not noisier.
        # Only asserted at full scale: smoke runs shrink the task count
        # faster than the fixed per-eval overheads they still pay.
        "bar_inplace_updates_per_sec": CONFIG5_INPLACE_BAR,
        "bar_rolled_updates_per_sec": CONFIG5_ROLLED_BAR,
        "pass": (
            None if n_nodes < 50_000
            else bool(inplace_rate >= CONFIG5_INPLACE_BAR
                      and rolled_rate >= CONFIG5_ROLLED_BAR)
        ),
        # Phases mutate state (rolling update over the phase-1 allocs), so
        # each figure is a single sample; dispersion comes from the
        # repeatable configs.
        "runs": 1, "warmup_runs": 0,
    }


BREAKDOWN = os.environ.get("NOMAD_TPU_BENCH_BREAKDOWN", "1") == "1"
# Default sweep scales track the headline cluster size so smoke runs
# (reduced NOMAD_TPU_BENCH_NODES) don't pay for a 32k-node mirror.
_BREAKDOWN_SCALES_ENV = os.environ.get("NOMAD_TPU_BENCH_BREAKDOWN_SCALES", "")
BREAKDOWN_SCALES = tuple(
    int(s) for s in _BREAKDOWN_SCALES_ENV.split(",") if s
) if _BREAKDOWN_SCALES_ENV else tuple(
    s for s in (1024, 4096, 10000, 32768) if s <= 4 * N_NODES
) or (N_NODES,)


def run_breakdown(scales=BREAKDOWN_SCALES):
    """Device-time accounting: where does a solve's wall time go?

    Splits the production water-fill solve into host staging / H2D
    transfer / device execute / D2H readback, with bytes moved, at several
    node scales. On a tunneled remote device the transfer+readback rows
    carry the round-trip cost that the aggregate solve_ms can't attribute —
    this is the data that answers whether a slow solve is a slow device or
    a slow wire, and at which scale the device overtakes the CPU backend
    (compare captures of the two backends; SURVEY §7 latency budget).

    Protocol per scale n (count = 10n tasks, the headline's ratio):
    - staging:  NodeMirror construction — host tensorization; device puts
                are dispatched async inside it, so this is host wall.
    - transfer: block_until_ready on the mirror's node tensors + clean
                usage — drains the H2D copies staged above; bytes counted.
    - execute:  solve_waterfill dispatch + block_until_ready on the
                device-resident counts (post-warmup, so no compile).
    - readback: device_get of the counts — D2H wire time; bytes counted.
    - warm_e2e: dispatch+block+readback in one timed pass, warm mirror —
                the steady-state per-eval device cost.
    """
    import jax

    from nomad_tpu.ops.binpack import device_const, solve_waterfill
    from nomad_tpu.tpu.mirror import NodeMirror
    from nomad_tpu.trace import StageTimer

    ask = (100, 128, 0, 0)  # the headline task's resource vector
    penalty_dev = device_const("f32", 0.0)
    bw_ask_dev = device_const("i32", 0)
    sweep = []
    for n in scales:
        count = 10 * n
        nodes_list = _mk_nodes(n, with_net=False)

        # Stage cuts through the SAME StageTimer the production solver's
        # trace spans use (nomad_tpu.trace) — one shared stage-timing
        # path, not a second parallel timer.
        prep_st = StageTimer()
        with prep_st.stage("staging"):
            mirror = NodeMirror(nodes_list)
            usage = mirror.clean_usage()
            eligible = mirror.device_mask(None, set(), None, None)[0]
        inputs = (mirror.total, mirror.sched_cap, mirror.bw_avail,
                  eligible, *usage)
        with prep_st.stage("transfer"):
            for arr in inputs:
                arr.block_until_ready()
        prep_ms = prep_st.durations_ms()
        transfer_bytes = int(sum(getattr(a, "nbytes", 0) for a in inputs))

        ask_dev = device_const("ask", ask)
        count_dev = device_const("i32", count)
        used0, job_count0, tg_count0, bw_used0 = usage

        def dispatch():
            return solve_waterfill(
                mirror.total, mirror.sched_cap, used0, job_count0,
                tg_count0, mirror.bw_avail, bw_used0, eligible, ask_dev,
                bw_ask_dev, count_dev, penalty_dev, False, False,
            )

        counts, unplaced = dispatch()  # warmup: compile for this bucket
        counts.block_until_ready()

        exec_times, read_times, e2e_times = [], [], []
        for _ in range(RUNS):
            st = StageTimer()
            with st.stage("execute"):
                counts, unplaced = dispatch()
                counts.block_until_ready()
                unplaced.block_until_ready()
            with st.stage("readback"):
                counts_host, _ = jax.device_get((counts, unplaced))
            d = st.durations_ms()
            exec_times.append(d["execute"] / 1000.0)
            read_times.append(d["readback"] / 1000.0)
            t = time.perf_counter()
            c2, u2 = dispatch()
            jax.device_get((c2, u2))
            e2e_times.append(time.perf_counter() - t)

        placed = int(counts_host.sum())
        warm_e2e = statistics.median(e2e_times)
        sweep.append({
            "n_nodes": n,
            "count": count,
            "placed": placed,
            "staging_ms": round(prep_ms.get("staging", 0.0), 2),
            "transfer_ms": round(prep_ms.get("transfer", 0.0), 2),
            "transfer_bytes": transfer_bytes,
            "execute_ms_p50": round(
                statistics.median(exec_times) * 1000, 3),
            "readback_ms_p50": round(
                statistics.median(read_times) * 1000, 3),
            "readback_bytes": int(counts_host.nbytes + 4),
            "warm_e2e_ms_p50": round(warm_e2e * 1000, 3),
            "placements_per_sec_warm": round(placed / warm_e2e, 1),
        })
    return sweep


_NODE_SWEEP_ENV = os.environ.get("NOMAD_TPU_BENCH_NODE_SWEEP", "")
NODE_SWEEP_SCALES = tuple(
    int(s) for s in _NODE_SWEEP_ENV.split(",") if s
) if _NODE_SWEEP_ENV else (1024, 10_000, 100_000)


def run_node_sweep(scales=NODE_SWEEP_SCALES, count=420):
    """Node-axis sweep to 100k: the ROADMAP item 1 proof arm.

    Holds the ask fixed (420 tasks — the steady-10k workload's job
    shape) and sweeps the NODE axis through 100k, measuring the warm
    water-fill solve wall per scale. The claim under test: with padded
    buffers, bucketed compiles, and (when configured) the node axis
    sharded over a device mesh, a 100k-node cell's warm per-eval solve
    stays in the same cost class as 10k — the verdict field pins the
    ratio. Uses the same clean-state staging as run_breakdown; the
    mirror build cost is reported but NOT in the warm wall (steady state
    reuses the resident mirror via MirrorCache)."""
    import jax

    from nomad_tpu.ops.binpack import device_const, solve_waterfill
    from nomad_tpu.tpu.mirror import NodeMirror

    ask_dev = device_const("ask", (100, 128, 0, 0))
    penalty_dev = device_const("f32", 0.0)
    bw_ask_dev = device_const("i32", 0)
    count_dev = device_const("i32", count)
    sweep = []
    for n in scales:
        nodes_list = _mk_nodes(n, with_net=False)
        t0 = time.perf_counter()
        mirror = NodeMirror(nodes_list)
        usage = mirror.clean_usage()
        eligible = mirror.device_mask(None, set(), None, None)[0]
        for arr in (mirror.total, mirror.sched_cap, eligible, *usage):
            arr.block_until_ready()
        staging_ms = (time.perf_counter() - t0) * 1000.0
        used0, job_count0, tg_count0, bw_used0 = usage

        def dispatch():
            return solve_waterfill(
                mirror.total, mirror.sched_cap, used0, job_count0,
                tg_count0, mirror.bw_avail, bw_used0, eligible, ask_dev,
                bw_ask_dev, count_dev, penalty_dev, False, False,
            )

        counts, unplaced = dispatch()  # compile for this node bucket
        counts.block_until_ready()
        times = []
        for _ in range(RUNS):
            t = time.perf_counter()
            c, u = dispatch()
            jax.device_get((c, u))
            times.append(time.perf_counter() - t)
        counts_host, unplaced_host = jax.device_get((counts, unplaced))
        placed = count - int(unplaced_host)
        warm_ms = statistics.median(times) * 1000.0
        sweep.append({
            "n_nodes": n,
            "padded": mirror.padded,
            "count": count,
            "placed": placed,
            "staging_ms": round(staging_ms, 2),
            "warm_solve_ms_p50": round(warm_ms, 3),
            "device_ms_per_placement": round(
                warm_ms / max(placed, 1), 4),
        })
        del mirror, usage, eligible, nodes_list, counts, unplaced
    by_n = {row["n_nodes"]: row for row in sweep}
    verdict = {}
    if 10_000 in by_n and 100_000 in by_n:
        ratio = (by_n[100_000]["warm_solve_ms_p50"]
                 / max(by_n[10_000]["warm_solve_ms_p50"], 1e-9))
        verdict = {
            "warm_100k_over_10k": round(ratio, 3),
            "same_cost_class_2x": ratio <= 2.0,
        }
    return {"sweep": sweep, **verdict}


STAGING_DELTA_SCALES = tuple(
    s for s in (1024, 4096, 10_000) if s <= N_NODES
) or (N_NODES,)


def run_staging_delta(scales=STAGING_DELTA_SCALES):
    """Delta-mirror arm: warm staging cost after a SINGLE node write.

    The BENCH_r05 breakdown showed staging (mirror build + masks + clean
    usage) at 21.57ms for 10k nodes while the device solve itself was
    ~1.3ms — and MirrorCache used to invalidate the WHOLE mirror on any
    node write. This arm measures what one node write actually costs now:
    ``delta`` re-stages through MirrorCache's change-log roll forward
    (one row patched + row-sliced device update), ``full`` forces the old
    posture (a cold cache rebuilding everything). Both stage to the same
    definition as the breakdown's staging row: mirror + eligibility mask
    + clean usage, blocked until device-resident."""
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu.mirror import MirrorCache

    dcs = ["dc1"]

    def stage(snap, cache):
        _nodes, m = cache.get(snap, dcs)
        usage = m.clean_usage()
        eligible = m.device_mask(None, set(), None, None)[0]
        for arr in (m.total, m.sched_cap, m.bw_avail, eligible, *usage):
            arr.block_until_ready()
        return m

    sweep = []
    for n in scales:
        nodes = _mk_nodes(n, with_net=False)
        state = StateStore()
        idx = 0
        for node in nodes:
            idx += 1
            state.upsert_node(idx, node)
        cache = MirrorCache()
        stage(state.snapshot(), cache)  # initial build (not measured)

        def write_one(r):
            # One node write: resource drift on a single node — the row
            # actually changes, so the delta path pays its full cost
            # (patch + row restage), not just a cache hit.
            nonlocal idx
            victim = state.node_by_id(nodes[r % n].id).copy()
            victim.resources = victim.resources.copy()
            victim.resources.cpu += 1
            idx += 1
            state.upsert_node(idx, victim)

        write_one(0)
        stage(state.snapshot(), cache)  # warm the scatter-update shapes

        delta_times, full_times = [], []
        with _quiesced():
            for r in range(1, RUNS + 1):
                write_one(r)
                snap = state.snapshot()
                t0 = time.perf_counter()
                stage(snap, cache)
                delta_times.append(time.perf_counter() - t0)
                # Forced full rebuild of the SAME state: a cold cache.
                t0 = time.perf_counter()
                stage(snap, MirrorCache())
                full_times.append(time.perf_counter() - t0)
        stats = cache.stats()
        delta_p50 = statistics.median(delta_times)
        full_p50 = statistics.median(full_times)
        sweep.append({
            "n_nodes": n,
            "delta_staging_ms_p50": round(delta_p50 * 1000, 3),
            "full_staging_ms_p50": round(full_p50 * 1000, 3),
            "speedup": round(full_p50 / delta_p50, 1) if delta_p50 else 0,
            "delta_rolls": stats["delta_rolls"],
            "full_rebuilds": stats["full_rebuilds"],
            "rows_restaged": stats["rows_restaged"],
            "runs": len(delta_times),
        })
    return sweep


def _pallas_outcome() -> str:
    """Whether the pallas water-fill kernel actually carried the solves:
    'proven' (compiled + executed on this backend), 'fallback' (it faulted
    and the jnp path took over), or 'off' (non-TPU backend / disabled)."""
    try:
        from nomad_tpu.ops.pallas_solve import _STATE, pallas_mode

        if _STATE["failed"]:
            return "fallback"
        if _STATE["proven"]:
            return "proven"
        return "off" if pallas_mode() == "off" else "untried"
    except Exception:
        return "unknown"


def _measure_headline():
    """The one headline measurement protocol (config 3): build, warm one
    pass, clear, RUNS timed passes under a quiesced GC, distributions.
    Shared by main() and the cpu-fallback path so the two emitted figures
    stay comparable. Returns (solve_dist, e2e_dist, placed, nodes,
    trace_info): the headline dists are measured with tracing DISABLED
    (comparable with prior rounds); ``trace_info`` carries a second,
    tracing-ENABLED set of RUNS over the same state — the per-stage
    solver spans (one shared stage-timing path with the breakdown) and
    the measured overhead of leaving tracing on."""
    from nomad_tpu import trace as _trace

    nodes, job = build_cluster()
    state = build_state(nodes, job)
    _TimingStack.install()

    # Warmup: compile caches for the shape buckets
    run_once(state, job)
    _TimingStack.solve_times.clear()

    # Interleaved arms: each iteration runs one tracing-DISABLED and one
    # tracing-ENABLED pass (the production worker posture: each traced
    # eval under a root span, so solver stage spans record). Interleaving
    # matters — same-box drift between two sequential sets has been
    # observed to exceed any real tracing cost, which would make a
    # sequential overhead figure pure noise.
    tracer = _trace.configure(max_traces=2 * RUNS + 8, enabled=True)
    trace_ids = []
    e2e_times, e2e_traced = [], []
    solve_untraced, solve_traced = [], []
    placed = 0
    with _quiesced():
        for _ in range(RUNS):
            tracer.enabled = False
            mark = len(_TimingStack.solve_times)
            e2e, placed = run_once(state, job)
            e2e_times.append(e2e)
            solve_untraced.extend(_TimingStack.solve_times[mark:])

            tracer.enabled = True
            mark = len(_TimingStack.solve_times)
            e2e, _p = run_once(state, job, trace_ids=trace_ids)
            e2e_traced.append(e2e)
            solve_traced.extend(_TimingStack.solve_times[mark:])

    if not solve_untraced:
        raise RuntimeError(
            "no device solves recorded — the TPU factories fell back "
            "to the host scheduler mid-run"
        )

    if not solve_traced:
        # A traced-arm-only device fallback must surface as an error, not
        # be averaged into a nonsensical overhead figure.
        trace_info = {"error": "no traced solves recorded — device "
                               "fallback during the traced arm"}
    else:
        stage_samples = {}
        tracer = _trace.get_tracer()
        for tid in trace_ids:
            for s in tracer.get_trace(tid) or []:
                if (s["name"].startswith("solver.")
                        and s["duration_ms"] is not None):
                    stage_samples.setdefault(
                        s["name"][len("solver."):], []
                    ).append(s["duration_ms"])
        sp50_off = statistics.median(solve_untraced)
        sp50_on = statistics.median(solve_traced)
        trace_info = {
            "solve_ms_p50_traced": round(sp50_on * 1000, 3),
            "e2e_eval_ms_p50_traced": round(
                statistics.median(e2e_traced) * 1000, 3),
            # The acceptance bound: < 5% warm-path regression with
            # tracing on.
            "overhead_pct": (
                round((sp50_on / sp50_off - 1.0) * 100.0, 2)
                if sp50_off else 0.0
            ),
            "stages_ms_p50": {
                k: round(statistics.median(v), 4)
                for k, v in stage_samples.items()
            },
        }

    return (
        _dist(solve_untraced, warmup=1),
        _dist(e2e_times, warmup=1),
        placed,
        nodes,
        trace_info,
    )


def main():
    backend = "unknown"
    _start_watchdog()
    try:
        backend = acquire_device()

        solve_dist, e2e_dist, placed, nodes, trace_info = _measure_headline()
        solve_p50 = solve_dist["p50_ms"] / 1000
        e2e_p50 = e2e_dist["p50_ms"] / 1000
        placements_per_sec = placed / solve_p50

        aux = {}
        coalesce = {}
        if HEADLINE_ONLY:
            aux["headline_only"] = True
        else:
            coalesce_wall, coalesce_placed, coalesce_dispatches = (
                run_coalesced(nodes)
            )
            coalesce = {
                "coalesced_evals": COALESCE_EVALS,
                "coalesced_wall_ms": round(coalesce_wall * 1000, 2),
                "coalesced_placed": coalesce_placed,
                "coalesced_dispatches": coalesce_dispatches,
            }

            # BASELINE configs 2 / 4 / 5 (config 1 is the unit-test scale
            # covered by the suite; config 3 is the headline above).
            # Failures report per-config without sinking the headline.
            for name, fn in (("config2", run_config2),
                             ("config4", run_config4),
                             ("config5", run_config5),
                             ("staging_delta", run_staging_delta),
                             ("node_sweep", run_node_sweep),
                             ("simload", run_simload)):
                try:
                    aux[name] = fn()
                except Exception as e:
                    aux[name] = {"error": f"{type(e).__name__}: {e}"}

            if BREAKDOWN:
                try:
                    aux["breakdown"] = run_breakdown()
                except Exception as e:
                    aux["breakdown"] = {"error": f"{type(e).__name__}: {e}"}

        emit(
            {
                "metric": "placements_per_sec@10k_nodes_x_100k_tasks",
                "value": round(placements_per_sec, 1),
                "unit": "placements/s",
                "vs_baseline": round(
                    placements_per_sec / TARGET_PLACEMENTS_PER_SEC, 3
                ),
                "solve_ms_p50": round(solve_p50 * 1000, 2),
                "e2e_eval_ms_p50": round(e2e_p50 * 1000, 2),
                "solve_ms": solve_dist,
                "e2e_eval_ms": e2e_dist,
                "tracing": trace_info,
                "placed": placed,
                "n_nodes": N_NODES,
                "n_tasks": N_TASKS,
                **coalesce,
                "backend": backend,
                "pallas": _pallas_outcome(),
                **aux,
            }
        )
    except BaseException as e:  # always emit the JSON line, never a traceback
        traceback.print_exc(file=sys.stderr)
        payload = {
            "metric": "placements_per_sec@10k_nodes_x_100k_tasks",
            "value": 0,
            "unit": "placements/s",
            "vs_baseline": 0,
            "backend": backend,
            "error": f"{type(e).__name__}: {e}",
        }
        device_dead = isinstance(e, RuntimeError) and (
            "device backend unavailable" in str(e)
            or "jax initialized on the CPU" in str(e)
        )
        if device_dead:
            # Device tier is unreachable (the error above carries the
            # staged probe forensics). Measure the headline on the CPU
            # backend anyway BEFORE emitting, so the one parsed artifact
            # line carries a real, honestly-labeled measurement instead
            # of value 0 — a driver that only keeps the parsed JSON must
            # never lose the fallback numbers to the stderr tail.
            # Tradeoff: a kill landing during this measurement costs the
            # line; the in-process watchdog still guarantees a
            # (zero-value) line if it merely wedges, and the fallback's
            # own device wait is capped at 150s to bound the exposure.
            try:
                fb = _cpu_fallback_headline()
            except BaseException as fe:
                fb = {"error": f"{type(fe).__name__}: {fe}"}
            payload["cpu_fallback"] = fb
            payload["pallas"] = _pallas_outcome()
            if "placements_per_sec" in fb:
                payload["value"] = fb["placements_per_sec"]
                payload["vs_baseline"] = round(
                    fb["placements_per_sec"] / TARGET_PLACEMENTS_PER_SEC, 3
                )
                # The device may have claimed DURING the fallback wait —
                # label the backend that actually measured, not the intent.
                payload["backend"] = (
                    "cpu-fallback" if fb.get("backend") == "cpu"
                    else fb.get("backend", "cpu-fallback")
                )
        emit(payload)
        # Exit-status contract: rc distinguishes "bench broken" (no valid
        # artifact) from "no device" (a real, honestly-labeled fallback
        # measurement WAS banked, with the device error recorded in the
        # JSON). BENCH_r05 banked a full cpu-fallback capture yet exited
        # 1, which bench_watch/CI read as a broken bench.
        fallback_ok = (
            device_dead
            and "placements_per_sec" in (payload.get("cpu_fallback") or {})
        )
        _exit(0 if fallback_ok else 1)
    _exit(0)


def _cpu_fallback_headline():
    """Headline measurement on the CPU backend, used only when device
    acquisition failed. The subprocess-isolated probe design means this
    process never touched jax, so it can still claim the CPU cleanly:
    NOMAD_TPU_PROBE_FORCE_CPU re-pins the platform for the next probe
    child AND the in-process init (scheduler/__init__.py manager loop)."""
    os.environ["NOMAD_TPU_PROBE_FORCE_CPU"] = "1"
    from nomad_tpu.scheduler import device_probe_status, wait_for_device

    solver = wait_for_device(timeout=150)
    status = device_probe_status()
    if solver is None:
        raise RuntimeError(f"cpu fallback also unavailable: {status}")
    # The manager may have been past the force-cpu check and finished the
    # REAL device init during our wait — label whatever actually claimed.
    fb_backend = str(status.get("backend", "cpu"))
    solve_dist, e2e_dist, placed, _nodes, trace_info = _measure_headline()
    solve_p50 = solve_dist["p50_ms"] / 1000
    e2e_p50 = e2e_dist["p50_ms"] / 1000
    breakdown = None
    if BREAKDOWN:
        try:
            # Failure path: keep the pre-emit window short — sweep only
            # scales up to the headline size, skip the larger crossover
            # points (a TPU capture through main() covers those).
            breakdown = run_breakdown(
                tuple(s for s in BREAKDOWN_SCALES if s <= N_NODES)
                or (N_NODES,)
            )
        except Exception as e:
            breakdown = {"error": f"{type(e).__name__}: {e}"}
    # The BASELINE configs ride the fallback too (unless headline-only):
    # a round whose relay never answers must still produce comparable
    # config2/4/5 numbers, honestly backend-labeled, instead of losing
    # the whole aux tier to the device tier's weather.
    aux = {}
    if not HEADLINE_ONLY:
        for name, fn in (("config2", run_config2),
                         ("config4", run_config4),
                         ("config5", run_config5),
                         ("staging_delta", run_staging_delta),
                         ("node_sweep", run_node_sweep),
                         ("simload", run_simload)):
            try:
                aux[name] = fn()
            except Exception as e:
                aux[name] = {"error": f"{type(e).__name__}: {e}"}
    return {
        **({"breakdown": breakdown} if breakdown is not None else {}),
        **aux,
        "backend": fb_backend,
        "note": (
            f"measured on the {fb_backend} backend after device "
            "acquisition timed out"
            + ("; NOT a TPU number" if fb_backend == "cpu" else
               " (device came up during the fallback wait)")
        ),
        "placements_per_sec": round(placed / solve_p50, 1),
        "solve_ms_p50": round(solve_p50 * 1000, 2),
        "e2e_eval_ms_p50": round(e2e_p50 * 1000, 2),
        "solve_ms": solve_dist,
        "e2e_eval_ms": e2e_dist,
        "tracing": trace_info,
        "placed": placed,
        "n_nodes": N_NODES,
        "n_tasks": N_TASKS,
    }


def _exit(code: int) -> None:
    """Exit without interpreter teardown: daemon threads (shape warmer,
    broker timers) may sit inside an XLA compile, and finalizing python
    under them aborts the process (rc 134) AFTER the JSON was emitted.
    The one-line contract is already flushed; skip teardown entirely."""
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


if __name__ == "__main__":
    main()
