"""Gossip-style membership: failure detection, member reap, dynamic Raft
peers, runtime joins.

Reference: /root/reference/nomad/serf.go:76-194 (nodeJoin -> peer add,
memberFailed -> peer removal) and nomad/leader.go:263-343 (leader
reconciliation of Serf members vs Raft peers). Here the member table is a
serf-lite gossip layer (Serf.Join / Serf.PeerUpdate RPCs + probing), and
Raft membership moves via committed single-server _config entries.
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server import ServerConfig
from cluster_util import relaxed_cluster_cfg, retry_write
from nomad_tpu.server.cluster import (
    ClusterConfig,
    ClusterServer,
    form_cluster,
    wait_for_leader,
)


def _fast_cluster_cfg(**kw):
    return relaxed_cluster_cfg(
        probe_interval=0.1, probe_timeout=0.25, suspicion_threshold=2, **kw
    )


def _host_cfg():
    return ServerConfig(
        scheduler_backend="host", num_schedulers=1, min_heartbeat_ttl=30.0,
    )


def _wait(predicate, timeout=40.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_dead_follower_is_detected_evicted_and_quorum_updates():
    """Kill a follower: probes fail, the member is marked failed, the
    leader commits its removal from the Raft configuration and reaps it
    from the member table — and the 2-server remainder still commits
    writes (quorum math updated)."""
    servers = form_cluster(3, _host_cfg(), _fast_cluster_cfg())
    try:
        leader = wait_for_leader(servers, timeout=30.0)
        _wait(
            lambda: all(len(s.raft.config.peers) == 3 for s in servers),
            msg="full raft membership",
        )
        victim = next(s for s in servers if s is not leader)
        victim_id = victim.cluster.node_id
        victim.shutdown()

        _wait(
            lambda: victim_id not in leader.raft.config.peers,
            msg="raft peer eviction",
        )
        _wait(
            lambda: victim_id not in leader.cluster.peers,
            msg="member table reap",
        )
        assert len(leader.raft.config.peers) == 2

        # Writes still commit: quorum is now 2 of 2, not 2 of 3 blocked
        # on a ghost member.
        retry_write(lambda: leader.node_register(mock.node()))
        job = mock.job()
        job.task_groups[0].count = 1
        eval_id, _ = retry_write(lambda: leader.job_register(job))
        ev = leader.wait_for_eval(eval_id, timeout=15.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
    finally:
        for s in servers:
            s.shutdown()


def test_server_added_at_runtime_replicates_and_can_win_election():
    """Join a server to a live cluster: gossip spreads it, the leader
    commits the Raft peer addition, the newcomer replicates history, and
    after the old leader dies the cluster re-elects among the remainder —
    the added server fully participating."""
    servers = form_cluster(2, _host_cfg(), _fast_cluster_cfg())
    extra = None
    try:
        leader = wait_for_leader(servers, timeout=30.0)
        retry_write(lambda: leader.node_register(mock.node()))
        job = mock.job()
        job.task_groups[0].count = 2
        eval_id, _ = retry_write(lambda: leader.job_register(job))
        leader.wait_for_eval(eval_id, timeout=15.0)

        # A third server joins at runtime via start_join.
        cfg = _host_cfg()
        cfg.node_name = "server-late"
        cluster_cfg = _fast_cluster_cfg(
            node_id="server-late",
            start_join=[leader.rpc_addr],
        )
        extra = ClusterServer(cfg, cluster_cfg)
        extra.start()

        _wait(
            lambda: "server-late" in leader.raft.config.peers,
            msg="leader committed the peer addition",
        )
        _wait(
            lambda: extra.raft.applied_index >= leader.raft.applied_index
            and len(extra.raft.config.peers) == 3,
            msg="newcomer caught up",
        )
        assert extra.state_store.job_by_id(job.id) is not None
        assert len(extra.state_store.allocs_by_job(job.id)) == 2

        # Old leader dies; the remaining two (incl. the newcomer) hold
        # quorum 2-of-3 and elect a new leader; the dead one is evicted.
        old_leader_id = leader.cluster.node_id
        leader.shutdown()
        remaining = [s for s in servers if s is not leader] + [extra]
        new_leader = wait_for_leader(remaining, timeout=40.0)
        _wait(
            lambda: old_leader_id not in new_leader.raft.config.peers,
            msg="dead leader evicted",
        )
        # The cluster keeps working — and if the newcomer won, it is fully
        # in charge.
        job2 = mock.job()
        job2.task_groups[0].count = 1
        eval_id2, _ = retry_write(lambda: new_leader.job_register(job2))
        ev2 = new_leader.wait_for_eval(eval_id2, timeout=15.0)
        assert ev2.status == structs.EVAL_STATUS_COMPLETE
    finally:
        for s in servers:
            s.shutdown()
        if extra is not None:
            extra.shutdown()


def test_recovered_member_is_not_reaped():
    """A member that misses probes transiently (below the suspicion
    threshold) is never marked failed; one marked alive again after
    recovery stays in the member table."""
    servers = form_cluster(2, _host_cfg(), _fast_cluster_cfg())
    try:
        leader = wait_for_leader(servers, timeout=30.0)
        other = next(s for s in servers if s is not leader)
        # Simulate one missed probe: below threshold=2
        leader._probe_failures[other.cluster.node_id] = 1
        time.sleep(0.5)
        assert leader._member_status.get(
            other.cluster.node_id, "alive"
        ) == "alive"
        assert other.cluster.node_id in leader.raft.config.peers
    finally:
        for s in servers:
            s.shutdown()


def test_force_leave_removes_member_and_raft_peer():
    servers = form_cluster(3, _host_cfg(), _fast_cluster_cfg())
    try:
        leader = wait_for_leader(servers, timeout=30.0)
        _wait(
            lambda: all(len(s.raft.config.peers) == 3 for s in servers),
            msg="full raft membership",
        )
        victim = next(s for s in servers if s is not leader)
        victim_id = victim.cluster.node_id
        survivors = [s for s in servers if s is not victim]
        victim.shutdown()
        # The shutdown can trigger an election; force-leave must go to the
        # CURRENT leader (its reconciliation loop commits the removal).
        leader = wait_for_leader(survivors, timeout=30.0)
        leader.force_leave(victim_id)
        assert victim_id not in leader.cluster.peers
        _wait(
            lambda: any(
                s.raft.is_leader and victim_id not in s.raft.config.peers
                for s in survivors
            ),
            msg="raft removal after force-leave",
        )
    finally:
        for s in servers:
            s.shutdown()
