"""Black-box TLS: a forked real agent with a tls config block serves its
RPC tier over mutual TLS and rejects plaintext.

tests/test_tls.py proves the in-process wiring (listener, pool, uplink);
this module proves the AGENT wiring end-to-end — config file → agent →
ClusterServer → TLS listener — the reference's optional rpcTLS arm
(/root/reference/nomad/rpc.go:104-110) as deployed, not as a unit.
"""

import json
import subprocess

import pytest

from blackbox_util import ForkedAgent, _alloc_port


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("bb-tls")
    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    srv_key, srv_csr, srv_crt = d / "srv.key", d / "srv.csr", d / "srv.crt"
    ext = d / "san.cnf"
    ext.write_text(
        "subjectAltName=DNS:localhost,IP:127.0.0.1\n"
        "basicConstraints=CA:FALSE\n"
    )

    def run(*args):
        subprocess.run(args, check=True, capture_output=True)

    try:
        # The WHOLE sequence maps to a skip: a restricted openssl build
        # can pass the first invocation and fail CSR/signing quirks —
        # that must skip the module, not error it.
        run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
            "-subj", "/CN=nomad-tpu-test-ca")
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(srv_key), "-out", str(srv_csr),
            "-subj", "/CN=localhost")
        run("openssl", "x509", "-req", "-in", str(srv_csr),
            "-CA", str(ca_crt), "-CAkey", str(ca_key), "-CAcreateserial",
            "-days", "1", "-extfile", str(ext), "-out", str(srv_crt))
    except (OSError, subprocess.CalledProcessError) as e:
        pytest.skip(f"openssl unavailable: {e}")
    return {"ca": str(ca_crt), "cert": str(srv_crt), "key": str(srv_key)}


@pytest.fixture(scope="module")
def tls_agent(certs, tmp_path_factory):
    """A non-dev single-server agent from a JSON config file with TLS on
    the RPC tier (dev mode runs the in-process server and never opens a
    network RPC listener, so the TLS arm needs the cluster path)."""
    d = tmp_path_factory.mktemp("bb-tls-agent")
    http_port, rpc_port = _alloc_port(), _alloc_port()
    cfg = {
        "data_dir": str(d / "data"),
        "name": "bb-tls-server",
        "ports": {"http": http_port, "rpc": rpc_port},
        "server": {"enabled": True, "bootstrap_expect": 1},
        "scheduler_backend": "host",
        "log_level": "WARN",
        "tls": {
            "enabled": True,
            "ca_file": certs["ca"],
            "cert_file": certs["cert"],
            "key_file": certs["key"],
            "verify_incoming": True,
        },
    }
    cfg_path = d / "agent.json"
    cfg_path.write_text(json.dumps(cfg))
    try:
        agent = ForkedAgent(
            agent_args=["-config", str(cfg_path)], http_port=http_port,
        )
    except (RuntimeError, TimeoutError, OSError) as e:
        pytest.skip(f"cannot fork black-box agent: {e}")
    agent.rpc_addr = f"127.0.0.1:{rpc_port}"
    yield agent
    agent.stop()


def _tls_cfg(certs):
    from nomad_tpu.tlsutil import TLSConfig

    return TLSConfig(
        enabled=True, ca_file=certs["ca"], cert_file=certs["cert"],
        key_file=certs["key"], verify_incoming=True, verify_hostname=False,
    )


def test_tls_rpc_roundtrip_against_forked_agent(certs, tls_agent):
    """A mutual-TLS client reaches the forked agent's RPC tier
    cross-process: the config-file tls block made it to the listener."""
    from nomad_tpu.rpc import ConnPool

    import time

    pool = ConnPool(ssl_context=_tls_cfg(certs).outgoing_context())
    try:
        assert pool.call(tls_agent.rpc_addr, "Status.Ping", {}) == "pong"
        # The HTTP ready-check does not wait for the election (production
        # raft timing: 1-2s windows) — poll the leader over TLS.
        deadline = time.monotonic() + 20.0
        leader = ""
        while time.monotonic() < deadline and not leader:
            leader = pool.call(tls_agent.rpc_addr, "Status.Leader", {})
            if not leader:
                time.sleep(0.2)
        assert leader == tls_agent.rpc_addr
    finally:
        pool.shutdown()


def test_plaintext_rejected_by_forked_tls_agent(tls_agent):
    """A plaintext pool must not get through the agent's TLS listener."""
    from nomad_tpu.rpc import ConnPool, RPCError

    pool = ConnPool(timeout=3.0)
    try:
        with pytest.raises(RPCError):
            pool.call(tls_agent.rpc_addr, "Status.Ping", {})
    finally:
        pool.shutdown()


def test_http_api_alive_alongside_tls_rpc(tls_agent):
    """The HTTP plane still answers while the RPC tier is TLS-armed, and
    reports the server role (the blackbox ready-check contract)."""
    info = tls_agent.http_get("/v1/agent/self")
    assert info.get("stats", {}).get("server")
