"""Client agent tests (reference: client/client_test.go, driver tests,
restarts_test.go, client/util_test.go, spawn_test.go)."""

import os
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.client import diff_allocs
from nomad_tpu.client.driver import ExecContext, new_driver
from nomad_tpu.client.driver import spawn
from nomad_tpu.client.getter import ArtifactError, get_artifact
from nomad_tpu.client.restarts import (
    BatchRestartTracker,
    ServiceRestartTracker,
    new_restart_tracker,
)
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import Allocation, Resources, RestartPolicy, Task


# ---------------------------------------------------------------------------
# Pieces
# ---------------------------------------------------------------------------


def test_alloc_dir_build(tmp_path):
    d = AllocDir(str(tmp_path / "alloc1"))
    d.build(["web", "db"])
    assert os.path.isdir(os.path.join(d.shared_dir, "logs"))
    assert os.path.isdir(os.path.join(d.shared_dir, "tmp"))
    assert os.path.isdir(os.path.join(d.shared_dir, "data"))
    assert os.path.isdir(os.path.join(d.task_dirs["web"], "local"))
    d.destroy()
    assert not os.path.exists(d.alloc_dir)


def test_client_diff_allocs():
    """reference: client/util_test.go:33-80"""
    a_keep = Allocation(id="keep", modify_index=5)
    a_update = Allocation(id="upd", modify_index=9)
    a_new = Allocation(id="new", modify_index=1)
    existing = {"keep": 5, "upd": 5, "gone": 2}
    added, removed, updates, ignore = diff_allocs(
        existing, [a_keep, a_update, a_new]
    )
    assert [a.id for a in added] == ["new"]
    assert removed == ["gone"]
    assert [a.id for a in updates] == ["upd"]
    assert ignore == ["keep"]


def test_restart_trackers():
    """reference: client/restarts_test.go"""
    batch = BatchRestartTracker(RestartPolicy(attempts=2, interval=100, delay=0.1))
    assert batch.next_restart() == (True, 0.1)
    assert batch.next_restart() == (True, 0.1)
    assert batch.next_restart() == (False, 0.0)

    svc = ServiceRestartTracker(RestartPolicy(attempts=1, interval=100, delay=0.2))
    ok, wait = svc.next_restart()
    assert ok and wait == 0.2
    ok, wait = svc.next_restart()
    # Window exhausted: still restarts, but waits out the interval remainder
    assert ok and wait > 0.2

    assert isinstance(new_restart_tracker("service", RestartPolicy()),
                      ServiceRestartTracker)
    assert isinstance(new_restart_tracker("batch", RestartPolicy()),
                      BatchRestartTracker)


def test_getter(tmp_path):
    src = tmp_path / "artifact.sh"
    src.write_text("#!/bin/sh\necho hi\n")
    dest_dir = tmp_path / "dest"
    dest_dir.mkdir()
    out = get_artifact(str(src), str(dest_dir))
    assert os.path.exists(out)
    assert os.access(out, os.X_OK)

    import hashlib

    digest = hashlib.sha256(src.read_bytes()).hexdigest()
    get_artifact(str(src), str(dest_dir), f"sha256:{digest}")
    with pytest.raises(ArtifactError):
        get_artifact(str(src), str(dest_dir), "sha256:" + "0" * 64)
    with pytest.raises(ArtifactError):
        get_artifact("ftp://nope/x", str(dest_dir))


# ---------------------------------------------------------------------------
# Spawn daemon + raw_exec driver (reference: spawn_test.go, raw_exec_test.go)
# ---------------------------------------------------------------------------


def _exec_ctx(tmp_path, tasks):
    d = AllocDir(str(tmp_path / "alloc"))
    d.build(tasks)
    return ExecContext(d, structs.generate_uuid())


def test_spawn_daemon_roundtrip(tmp_path):
    prefix = str(tmp_path / "task")
    out = str(tmp_path / "out.log")
    err = str(tmp_path / "err.log")
    pid = spawn.spawn_detached(
        "/bin/sh", ["-c", "echo hello; exit 3"],
        {"PATH": "/usr/bin:/bin"}, str(tmp_path), out, err, prefix,
    )
    assert pid > 0
    code = spawn.wait(prefix, timeout=10.0)
    assert code == 3
    with open(out) as f:
        assert f.read().strip() == "hello"


def test_spawn_missing_binary(tmp_path):
    prefix = str(tmp_path / "task")
    spawn.spawn_detached(
        "/no/such/bin", [], {}, str(tmp_path),
        str(tmp_path / "o"), str(tmp_path / "e"), prefix,
    )
    assert spawn.wait(prefix, timeout=10.0) == 127


@pytest.mark.skipif(
    os.name != "posix" or os.geteuid() != 0,
    reason="chroot + setuid require root",
)
def test_exec_driver_chroot_and_setuid(tmp_path):
    """Root-gated isolation parity (exec_linux.go:154-156, 240-290): the
    exec driver chroots the task into its task dir and drops to nobody.
    Proven from inside: a static binary reports uid/gid, cwd, and that the
    host filesystem is gone."""
    import shutil
    import subprocess

    if shutil.which("gcc") is None and shutil.which("g++") is None:
        pytest.skip("no C compiler for the static probe binary")

    src = tmp_path / "probe.c"
    src.write_text(
        '#include <stdio.h>\n#include <unistd.h>\n'
        "int main(){char b[256];\n"
        'printf("uid=%d gid=%d cwd=%s etc=%d\\n", (int)getuid(),\n'
        '  (int)getgid(), getcwd(b, sizeof b), access("/etc/hostname", 0));\n'
        "return 0;}\n"
    )
    cc = shutil.which("gcc") or shutil.which("g++")
    binary = tmp_path / "probe"
    subprocess.run(
        [cc, "-static", "-o", str(binary), str(src)], check=True,
        capture_output=True,
    )

    from nomad_tpu.client.driver.exec_driver import ExecDriver

    ctx = _exec_ctx(tmp_path, ["probe"])
    # Tiny chroot: skip the full host-tool embed, the probe is static.
    ctx.options = {"exec.chroot_env": "/nonexistent:/nonexistent"}
    task_dir = ctx.alloc_dir.task_dirs["probe"]
    shutil.copy2(binary, os.path.join(task_dir, "probe"))
    os.chmod(os.path.join(task_dir, "probe"), 0o755)

    task = structs.Task(
        name="probe", driver="exec",
        config={"command": os.path.join(task_dir, "probe")},
        resources=structs.Resources(cpu=100, memory_mb=64),
    )
    driver = ExecDriver(ctx)
    handle = driver.start(task)
    assert handle.wait(timeout=15.0) == 0

    out_path = os.path.join(ctx.alloc_dir.log_dir(), "probe.stdout")
    with open(out_path) as f:
        line = f.read().strip()
    from nomad_tpu.client.driver.executor import nobody_ids

    uid, gid = nobody_ids()
    fields = dict(kv.split("=", 1) for kv in line.split())
    assert int(fields["uid"]) == uid, line     # setuid nobody
    assert int(fields["gid"]) == gid, line     # setgid nogroup
    assert fields["cwd"] == "/", line          # rooted in the task dir
    assert int(fields["etc"]) == -1, line      # host fs is gone


def test_raw_exec_driver(tmp_path):
    config = ClientConfig(options={"driver.raw_exec.enable": "1"})
    node = mock.node()
    from nomad_tpu.client.driver.raw_exec import RawExecDriver

    assert RawExecDriver.fingerprint(config, node)
    assert node.attributes["driver.raw_exec"] == "1"

    ctx = _exec_ctx(tmp_path, ["echoer"])
    driver = new_driver("raw_exec", ctx)
    task = Task(
        name="echoer", driver="raw_exec",
        config={"command": "/bin/sh", "args": ["-c", "echo $NOMAD_ALLOC_ID"]},
        resources=Resources(cpu=100, memory_mb=64),
    )
    handle = driver.start(task)
    assert handle.wait(timeout=10.0) == 0

    # stdout landed in the shared log dir
    stdout = os.path.join(ctx.alloc_dir.log_dir(), "echoer.stdout")
    with open(stdout) as f:
        assert f.read().strip() == ctx.alloc_id

    # Reattach via handle ID
    reopened = driver.open(handle.id())
    assert reopened.wait(timeout=1.0) == 0


def test_raw_exec_kill(tmp_path):
    ctx = _exec_ctx(tmp_path, ["sleeper"])
    driver = new_driver("raw_exec", ctx)
    task = Task(
        name="sleeper", driver="raw_exec",
        config={"command": "/bin/sleep", "args": ["300"]},
        resources=Resources(cpu=100, memory_mb=64),
    )
    handle = driver.start(task)
    assert handle.is_running()
    handle.kill()
    code = handle.wait(timeout=10.0)
    assert code != 0
    assert not handle.is_running()


def test_exec_driver_fingerprint():
    from nomad_tpu.client.driver.exec_driver import ExecDriver

    node = mock.node()
    node.attributes.clear()
    config = ClientConfig()
    assert ExecDriver.fingerprint(config, node)  # linux
    assert node.attributes["driver.exec"] == "1"


def test_mock_driver(tmp_path):
    ctx = _exec_ctx(tmp_path, ["m"])
    driver = new_driver("mock_driver", ctx)
    task = Task(name="m", driver="mock_driver",
                config={"run_for": 0.1, "exit_code": 0})
    handle = driver.start(task)
    assert handle.is_running()
    assert handle.wait(timeout=5.0) == 0

    failing = Task(name="m", driver="mock_driver",
                   config={"run_for": 0.05, "exit_code": 2})
    handle = driver.start(failing)
    assert handle.wait(timeout=5.0) == 2


# ---------------------------------------------------------------------------
# Client <-> server integration (reference: client_test.go)
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster(tmp_path):
    srv = Server(ServerConfig(
        scheduler_backend="host",
        min_heartbeat_ttl=0.2,
        max_heartbeats_per_second=1000.0,
    ))
    srv.start()
    config = ClientConfig(
        dev_mode=True,
        state_dir=str(tmp_path / "state"),
        alloc_dir=str(tmp_path / "allocs"),
        datacenter="dc1",
        node_name="test-client",
        rpc_handler=srv,
        options={"driver.raw_exec.enable": "1", "driver.mock_driver.enable": "1"},
    )
    client = Client(config)
    client.start()
    yield srv, client
    client.shutdown(destroy_allocs=True)
    srv.shutdown()


def _wait_until(predicate, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_client_registers_and_heartbeats(cluster):
    srv, client = cluster
    assert _wait_until(
        lambda: (
            (n := srv.state_store.node_by_id(client.node.id)) is not None
            and n.status == structs.NODE_STATUS_READY
        )
    )
    node = srv.state_store.node_by_id(client.node.id)
    # Fingerprints populated the node
    assert node.resources.cpu > 0
    assert node.resources.memory_mb > 0
    assert node.attributes["kernel.name"] == "linux"
    assert node.attributes["driver.raw_exec"] == "1"


def test_client_runs_allocation_end_to_end(cluster):
    """The full story: job register -> schedule -> client picks up the alloc
    -> spawn daemon runs the process -> status syncs back -> batch task
    completes -> alloc goes dead (SURVEY.md §3.3)."""
    srv, client = cluster
    assert _wait_until(
        lambda: (
            (n := srv.state_store.node_by_id(client.node.id)) is not None
            and n.status == structs.NODE_STATUS_READY
        )
    )

    job = mock.job()
    job.type = structs.JOB_TYPE_BATCH
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks[0].driver = "raw_exec"
    tg.tasks[0].config = {"command": "/bin/sh", "args": ["-c", "echo done"]}
    tg.tasks[0].resources = Resources(cpu=100, memory_mb=64)

    eval_id, _ = srv.job_register(job)
    srv.wait_for_eval(eval_id, timeout=15.0)

    allocs = srv.state_store.allocs_by_job(job.id)
    assert len(allocs) == 1
    assert allocs[0].node_id == client.node.id

    # Client runs it; batch task exits 0 -> alloc client status dead
    assert _wait_until(
        lambda: srv.state_store.allocs_by_job(job.id)[0].client_status
        == structs.ALLOC_CLIENT_STATUS_DEAD,
        timeout=20.0,
    ), srv.state_store.allocs_by_job(job.id)[0]


def test_client_stops_alloc_on_deregister(cluster):
    srv, client = cluster
    assert _wait_until(
        lambda: (
            (n := srv.state_store.node_by_id(client.node.id)) is not None
            and n.status == structs.NODE_STATUS_READY
        )
    )

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks[0].driver = "raw_exec"
    tg.tasks[0].config = {"command": "/bin/sleep", "args": ["300"]}
    tg.tasks[0].resources = Resources(cpu=100, memory_mb=64)

    eval_id, _ = srv.job_register(job)
    srv.wait_for_eval(eval_id, timeout=15.0)
    assert _wait_until(lambda: client.num_allocs() == 1, timeout=20.0)
    assert _wait_until(
        lambda: srv.state_store.allocs_by_job(job.id)[0].client_status
        == structs.ALLOC_CLIENT_STATUS_RUNNING,
        timeout=20.0,
    )

    eval_id2, _ = srv.job_deregister(job.id)
    srv.wait_for_eval(eval_id2, timeout=15.0)

    # The stop flows to the client, which kills the task
    def stopped():
        runners = list(client.alloc_runners.values())
        return runners and not runners[0].alive()

    assert _wait_until(stopped, timeout=20.0)


def test_task_restart_policy(cluster, tmp_path):
    """Failing batch task restarts up to the policy's attempts then fails."""
    srv, client = cluster
    assert _wait_until(
        lambda: (
            (n := srv.state_store.node_by_id(client.node.id)) is not None
            and n.status == structs.NODE_STATUS_READY
        )
    )

    counter = tmp_path / "attempts"
    job = mock.job()
    job.type = structs.JOB_TYPE_BATCH
    tg = job.task_groups[0]
    tg.count = 1
    tg.restart_policy = RestartPolicy(attempts=2, interval=300.0, delay=0.05)
    tg.tasks[0].driver = "raw_exec"
    tg.tasks[0].config = {
        "command": "/bin/sh",
        "args": ["-c", f"echo x >> {counter}; exit 1"],
    }
    tg.tasks[0].resources = Resources(cpu=100, memory_mb=64)

    eval_id, _ = srv.job_register(job)
    srv.wait_for_eval(eval_id, timeout=15.0)

    assert _wait_until(
        lambda: srv.state_store.allocs_by_job(job.id)
        and srv.state_store.allocs_by_job(job.id)[0].client_status
        == structs.ALLOC_CLIENT_STATUS_FAILED,
        timeout=30.0,
    )
    # 1 initial run + 2 restarts
    assert counter.read_text().count("x") == 3
