"""Shared helpers for multi-server cluster tests.

``retry_write`` is the testutil.WaitForResult posture of the reference
(/root/reference/testutil/wait.go:13-29): cluster writes may race a leader
transition — the server surfaces NotLeaderError / transport errors exactly
like the reference's raftApply, and the CLIENT retries. Under CPU
contention (a parallel test suite, a busy CI box) the in-process clusters'
150-300ms election timeouts churn, so direct server-method calls in tests
need the same retry discipline real clients have.
"""

from __future__ import annotations

import time

from nomad_tpu.raft import NotLeaderError
from nomad_tpu.rpc import RPCError, RemoteError
from nomad_tpu.server.cluster import ClusterConfig


def _load_factor() -> float:
    """Measured scheduling-stall multiplier for raft timing.

    A full-suite run leaves daemon threads (broker timers, shape warmers)
    and a large GC heap behind; a timer that expects to wake in 10ms can
    oversleep several-fold under that load, which is exactly how
    test_leader_failover flaked in round 4 (elections starved past the
    wait deadline). Time a handful of short sleeps and scale the election
    window by the observed overshoot — an idle box keeps the fast
    timings, a loaded one gets proportionally wider windows. Capped so a
    pathological stall cannot make failover tests crawl."""
    expected = 0.0
    t0 = time.monotonic()
    for _ in range(5):
        time.sleep(0.01)
        expected += 0.01
    elapsed = time.monotonic() - t0
    return min(4.0, max(1.0, elapsed / expected))


def relaxed_cluster_cfg(**kw) -> ClusterConfig:
    """Raft timing for IN-PROCESS test clusters. The production defaults
    (50ms heartbeat / 150-300ms elections) assume parallel servers; with
    3 servers' threads in one GIL, a busy test process can stall a
    leader's heartbeat past the election deadline and churn leadership
    mid-test. The base window is double production, further scaled by the
    measured scheduling stall of the moment (see _load_factor) so a
    suite-loaded box gets the wider elections it actually needs."""
    f = _load_factor()
    kw.setdefault("heartbeat_interval", 0.1 * f)
    kw.setdefault("election_timeout_min", 0.4 * f)
    kw.setdefault("election_timeout_max", 0.8 * f)
    return ClusterConfig(**kw)


def retry_write(fn, timeout: float = 15.0, interval: float = 0.1):
    """Run ``fn`` until it stops raising leader-transition errors or the
    timeout expires; returns fn's result. Last error re-raised on expiry.

    RemoteError is retried ONLY when it is a NotLeaderError that crossed
    the wire — a genuine handler failure (validation, missing resource)
    must surface immediately, not burn the whole timeout."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except RemoteError as e:
            if "NotLeaderError" not in str(e) and "not the leader" not in str(e):
                raise
            if time.monotonic() >= deadline:
                raise
            time.sleep(interval)
        except (NotLeaderError, RPCError, TimeoutError):
            if time.monotonic() >= deadline:
                raise
            time.sleep(interval)
