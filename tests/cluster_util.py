"""Shared helpers for multi-server cluster tests.

``retry_write`` is the testutil.WaitForResult posture of the reference
(/root/reference/testutil/wait.go:13-29): cluster writes may race a leader
transition — the server surfaces NotLeaderError / transport errors exactly
like the reference's raftApply, and the CLIENT retries. Under CPU
contention (a parallel test suite, a busy CI box) the in-process clusters'
150-300ms election timeouts churn, so direct server-method calls in tests
need the same retry discipline real clients have.
"""

from __future__ import annotations

import time

from nomad_tpu.raft import NotLeaderError
from nomad_tpu.rpc import RPCError, RemoteError
from nomad_tpu.server.cluster import ClusterConfig


def relaxed_cluster_cfg(**kw) -> ClusterConfig:
    """Raft timing for IN-PROCESS test clusters. The production defaults
    (50ms heartbeat / 150-300ms elections) assume parallel servers; with
    3 servers' threads in one GIL, a busy test process can stall a
    leader's heartbeat past the election deadline and churn leadership
    mid-test. Doubling the window makes churn rare while keeping failover
    tests fast (elections still settle in under a second)."""
    kw.setdefault("heartbeat_interval", 0.1)
    kw.setdefault("election_timeout_min", 0.4)
    kw.setdefault("election_timeout_max", 0.8)
    return ClusterConfig(**kw)


def retry_write(fn, timeout: float = 15.0, interval: float = 0.1):
    """Run ``fn`` until it stops raising leader-transition errors or the
    timeout expires; returns fn's result. Last error re-raised on expiry.

    RemoteError is retried ONLY when it is a NotLeaderError that crossed
    the wire — a genuine handler failure (validation, missing resource)
    must surface immediately, not burn the whole timeout."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except RemoteError as e:
            if "NotLeaderError" not in str(e) and "not the leader" not in str(e):
                raise
            if time.monotonic() >= deadline:
                raise
            time.sleep(interval)
        except (NotLeaderError, RPCError, TimeoutError):
            if time.monotonic() >= deadline:
                raise
            time.sleep(interval)
