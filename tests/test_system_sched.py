"""Ported system scheduler tests
(/root/reference/scheduler/system_sched_test.go), parametrized over host and
TPU factories."""

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.structs import Evaluation, UpdateStrategy, generate_uuid

from sched_harness import Harness, RejectPlan, flatten

SYSTEM_FACTORIES = ["system", "tpu-system"]


def _seed_nodes(h, n=10):
    nodes = []
    for _ in range(n):
        node = mock.node()
        nodes.append(node)
        h.state.upsert_node(h.next_index(), node)
    return nodes


def _alloc_on(job, node_id, name="my-job.web[0]"):
    alloc = mock.alloc()
    alloc.job = job
    alloc.job_id = job.id
    alloc.node_id = node_id
    alloc.name = name
    return alloc


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_job_register(factory):
    """reference: system_sched_test.go:11-63"""
    h = Harness()
    _seed_nodes(h)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    planned = flatten(h.plans[0].node_allocation)
    assert len(planned) == 10
    assert len(h.state.allocs_by_job(job.id)) == 10
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_job_register_add_node(factory):
    """reference: system_sched_test.go:65-150"""
    h = Harness()
    nodes = _seed_nodes(h)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    allocs = [_alloc_on(job, node.id) for node in nodes]
    h.state.upsert_allocs(h.next_index(), allocs)

    new_node = mock.node()
    h.state.upsert_node(h.next_index(), new_node)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_NODE_UPDATE,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert flatten(plan.node_update) == []
    planned = flatten(plan.node_allocation)
    assert len(planned) == 1
    assert new_node.id in plan.node_allocation

    out = structs.filter_terminal_allocs(h.state.allocs_by_job(job.id))
    assert len(out) == 11
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_job_register_alloc_fail(factory):
    """reference: system_sched_test.go:152-180 — no nodes is a no-op."""
    h = Harness()
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert h.plans == []
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_job_modify(factory):
    """reference: system_sched_test.go:182-278"""
    h = Harness()
    nodes = _seed_nodes(h)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    allocs = [_alloc_on(job, node.id) for node in nodes]
    h.state.upsert_allocs(h.next_index(), allocs)

    terminal = []
    for i in range(5):
        alloc = _alloc_on(job, nodes[i].id)
        alloc.desired_status = structs.ALLOC_DESIRED_STATUS_FAILED
        terminal.append(alloc)
    h.state.upsert_allocs(h.next_index(), terminal)

    job2 = mock.system_job()
    job2.id = job.id
    job2.task_groups[0].tasks[0].config["command"] = "/bin/other"
    h.state.upsert_job(h.next_index(), job2)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert len(flatten(plan.node_update)) == len(allocs)
    assert len(flatten(plan.node_allocation)) == 10

    out = structs.filter_terminal_allocs(h.state.allocs_by_job(job.id))
    assert len(out) == 10
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_job_modify_rolling(factory):
    """reference: system_sched_test.go:280-379"""
    h = Harness()
    nodes = _seed_nodes(h)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    allocs = [_alloc_on(job, node.id) for node in nodes]
    h.state.upsert_allocs(h.next_index(), allocs)

    job2 = mock.system_job()
    job2.id = job.id
    job2.update = UpdateStrategy(stagger=30.0, max_parallel=5)
    job2.task_groups[0].tasks[0].config["command"] = "/bin/other"
    h.state.upsert_job(h.next_index(), job2)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert len(flatten(plan.node_update)) == job2.update.max_parallel
    assert len(flatten(plan.node_allocation)) == job2.update.max_parallel
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)

    ev_update = h.evals[0]
    assert ev_update.next_eval
    assert h.create_evals
    create = h.create_evals[0]
    assert ev_update.next_eval == create.id
    assert create.previous_eval == ev_update.id
    assert create.triggered_by == structs.EVAL_TRIGGER_ROLLING_UPDATE


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_job_modify_in_place(factory):
    """reference: system_sched_test.go:381-473"""
    h = Harness()
    nodes = _seed_nodes(h)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    allocs = [_alloc_on(job, node.id) for node in nodes]
    h.state.upsert_allocs(h.next_index(), allocs)

    job2 = mock.system_job()
    job2.id = job.id
    h.state.upsert_job(h.next_index(), job2)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert flatten(plan.node_update) == []
    planned = flatten(plan.node_allocation)
    assert len(planned) == 10
    for p in planned:
        assert p.job.modify_index == job2.modify_index

    out = h.state.allocs_by_job(job.id)
    assert len(out) == 10
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)

    for alloc in out:
        for resources in alloc.task_resources.values():
            assert resources.networks[0].reserved_ports[0] == 5000


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_job_deregister(factory):
    """reference: system_sched_test.go:475-538"""
    h = Harness()
    nodes = _seed_nodes(h)
    job = mock.system_job()

    allocs = [_alloc_on(job, node.id) for node in nodes]
    h.state.upsert_allocs(h.next_index(), allocs)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_DEREGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    for node in nodes:
        assert len(plan.node_update[node.id]) == 1

    out = structs.filter_terminal_allocs(h.state.allocs_by_job(job.id))
    assert out == []
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_node_drain(factory):
    """reference: system_sched_test.go:540-605"""
    h = Harness()
    node = mock.node()
    node.drain = True
    h.state.upsert_node(h.next_index(), node)

    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    alloc = _alloc_on(job, node.id)
    h.state.upsert_allocs(h.next_index(), [alloc])

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_NODE_UPDATE,
        job_id=job.id,
        node_id=node.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert len(plan.node_update[node.id]) == 1
    planned = flatten(plan.node_update)
    assert len(planned) == 1
    assert planned[0].desired_status == structs.ALLOC_DESIRED_STATUS_STOP
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SYSTEM_FACTORIES)
def test_system_retry_limit(factory):
    """reference: system_sched_test.go:607-651"""
    h = Harness()
    h.planner = RejectPlan(h)
    _seed_nodes(h)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) > 0
    assert h.state.allocs_by_job(job.id) == []
    h.assert_eval_status(structs.EVAL_STATUS_FAILED)


def test_system_columnar_batch_path_matches_host():
    """>= BATCH_PLACE_THRESHOLD network-free nodes: the columnar system
    path (TPUSystemScheduler._place_system_batch) must place one per node
    like the host oracle, committing as an AllocBatch."""
    from nomad_tpu.structs import Resources

    results = {}
    for factory in ("system", "tpu-system"):
        h = Harness()
        for i in range(80):
            node = mock.node()
            node.id = f"sysb-{i:03d}"
            h.state.upsert_node(h.next_index(), node)
        job = mock.system_job()
        for t in job.task_groups[0].tasks:
            t.resources = Resources(cpu=100, memory_mb=64)  # network-free
        h.state.upsert_job(h.next_index(), job)
        ev = Evaluation(
            id=generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        )
        h.process(factory, ev)
        live = [
            a for a in h.state.allocs_by_job(job.id)
            if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
        ]
        assert len(live) == 80
        assert len({a.node_id for a in live}) == 80
        assert all(a.name == f"{job.name}.{job.task_groups[0].name}[0]"
                   for a in live)
        if factory == "tpu-system":
            assert any(p.alloc_batches for p in h.plans), (
                "expected the columnar system path"
            )
        results[factory] = len(live)
    assert results["system"] == results["tpu-system"]


def test_system_columnar_partial_fit_coalesces_failures():
    """Some nodes can't fit the system task: placements land columnar on
    the fitting nodes; failures coalesce into one failed alloc with the
    count, exactly like the sequential path."""
    from nomad_tpu.structs import Resources

    h = Harness()
    for i in range(70):
        node = mock.node()
        node.id = f"sysp-{i:03d}"
        if i < 20:  # too small for the ask
            node.resources = Resources(cpu=50, memory_mb=32)
        h.state.upsert_node(h.next_index(), node)
    job = mock.system_job()
    for t in job.task_groups[0].tasks:
        t.resources = Resources(cpu=500, memory_mb=256)
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )
    h.process("tpu-system", ev)
    live = [
        a for a in h.state.allocs_by_job(job.id)
        if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
    ]
    assert len(live) == 50
    failed = [a for p in h.plans for a in p.failed_allocs]
    assert len(failed) == 1
    assert failed[0].metrics.coalesced_failures == 19  # 20 failures total
