"""Blocking-query fan-out hardening (server/blocking.py +
state.store._Watch): the coalesced index-bucketed watch registry.

Pins three things toward the ~50k-watcher posture:

1. **The wake-storm microbenchmark**: writer-side notify cost under 1k /
   10k / 50k registered watchers of one hot item — coalesced (bucket
   generation bump, O(touched items)) vs the retired per-watcher design
   (one ``Event.set()`` per watcher, O(watchers), paid by the FSM apply
   thread). The per-watcher baseline is reconstructed locally so the
   comparison stays honest as the production code evolves.
2. **Gapless-wake correctness**: concurrent watchers looping
   register → probe → wait never miss their index after coalescing —
   including watchers parked on bucket-SHARING items (spurious wakes
   re-probe and re-park; lost wakes would time out).
3. **Bounded registrations**: past ``max_watchers`` the registry raises
   a typed ``RejectError(WATCH_LIMIT)`` with a retry hint — the same
   cheap-rejection machinery as the admission front door.
"""

import threading
import time

import pytest

from nomad_tpu.state.store import StateStore, _Watch, item_node, item_table
from nomad_tpu.structs import REJECT_WATCH_LIMIT, RejectError


class _PerWatcherWatch:
    """The retired design, reconstructed as the benchmark baseline: one
    Event per watcher per item; notify iterates and sets every parked
    event under the registry lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._waiters = {}

    def watch(self, items, event):
        with self._lock:
            for item in items:
                self._waiters.setdefault(item, set()).add(event)

    def notify(self, items):
        if not self._waiters:
            return
        with self._lock:
            for item in items:
                for event in self._waiters.get(item, ()):
                    event.set()


def _time_notifies(registry, item, rounds):
    t0 = time.perf_counter()
    for _ in range(rounds):
        registry.notify([item])
    return time.perf_counter() - t0


@pytest.mark.parametrize("n_watchers", [1_000, 10_000, 50_000])
def test_wake_storm_coalesced_beats_per_watcher(n_watchers):
    """Writer-side notify with N watchers parked on ONE hot item: the
    coalesced registry's cost must not scale with N (it bumps one bucket
    generation), while the per-watcher baseline pays N Event.set()s.
    Margins are deliberately loose (the real gap is >50x at 50k) so a
    noisy box can't flake this."""
    item = item_table("allocs")
    rounds = 50

    legacy = _PerWatcherWatch()
    for _ in range(n_watchers):
        legacy.watch([item], threading.Event())
    legacy_cost = _time_notifies(legacy, item, rounds)

    coalesced = _Watch()
    tickets = [coalesced.register([item]) for _ in range(n_watchers)]
    coalesced_cost = _time_notifies(coalesced, item, rounds)

    per_notify_legacy = legacy_cost / rounds
    per_notify_coalesced = coalesced_cost / rounds
    print(f"\nwake-storm @{n_watchers}: per-watcher "
          f"{per_notify_legacy * 1e6:.1f}us/notify, coalesced "
          f"{per_notify_coalesced * 1e6:.1f}us/notify "
          f"({per_notify_legacy / max(per_notify_coalesced, 1e-9):.0f}x)")
    # The storm: per-watcher scales with N; coalesced must beat it by a
    # wide margin once N is large.
    assert coalesced_cost * 5 < legacy_cost, (
        f"coalesced notify ({per_notify_coalesced * 1e6:.1f}us) not "
        f"clearly cheaper than per-watcher "
        f"({per_notify_legacy * 1e6:.1f}us) at {n_watchers} watchers"
    )
    for t in tickets:
        coalesced.unregister(t)
    assert coalesced.stats()["watchers"] == 0


def test_wake_storm_coalesced_cost_is_flat():
    """Coalesced notify is O(1) in watcher count: 50x more watchers must
    not make a notify anywhere near 50x slower (generous 10x slack for
    timer noise — the real ratio is ~1x)."""
    item = item_table("allocs")
    rounds = 200

    def cost_at(n):
        w = _Watch()
        tickets = [w.register([item]) for _ in range(n)]
        try:
            return _time_notifies(w, item, rounds)
        finally:
            for t in tickets:
                w.unregister(t)

    # Warm once (allocator noise), then measure.
    cost_at(100)
    small, big = cost_at(1_000), cost_at(50_000)
    assert big < small * 10, (
        f"coalesced notify scaled with watcher count: "
        f"{small * 1e6 / rounds:.2f}us @1k vs "
        f"{big * 1e6 / rounds:.2f}us @50k"
    )


def test_no_watcher_misses_its_index_after_coalescing():
    """The gapless contract: concurrent watchers looping
    register → probe → short wait all observe the final index. A lost
    wakeup shows up as a watcher systematically timing out; bucket
    collisions may wake the wrong watcher early (it re-probes and
    re-parks) but never silence the right one."""
    store = StateStore()
    nodes = [f"node-{i:03d}" for i in range(40)]
    final_index = 1000 + 60
    errors = []
    seen = []

    def watcher(widx):
        # Mix of items: the node items deliberately collide across the
        # 64 buckets at 40 nodes, and the table item is white-hot.
        item = (item_table("nodes") if widx % 3 == 0
                else item_node(nodes[widx % len(nodes)]))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            ticket = store.watch.register([item])
            try:
                idx = store.get_index("nodes")
                if idx >= final_index:
                    seen.append(widx)
                    return
                store.watch.wait(ticket, timeout=0.5)
            finally:
                store.watch.unregister(ticket)
        errors.append(f"watcher {widx} never saw index {final_index}")

    threads = [threading.Thread(target=watcher, args=(i,))
               for i in range(24)]
    for t in threads:
        t.start()
    from nomad_tpu import mock

    for i in range(61):
        n = mock.node()
        n.id = n.name = nodes[i % len(nodes)]
        store.upsert_node(1000 + i, n)
        time.sleep(0.001)
    for t in threads:
        t.join(35.0)
    assert not errors, errors
    assert len(seen) == 24


def test_multi_bucket_registration_wakes_on_any_item():
    """Multi-item tickets (topic-filtered event watchers) span buckets
    and park on the shared side channel: a notify on ANY of the items
    must wake them."""
    w = _Watch()
    items = [item_node(f"n{i}") for i in range(8)]  # spans buckets
    woke = []

    def waiter():
        ticket = w.register(items)
        try:
            woke.append(w.wait(ticket, timeout=5.0))
        finally:
            w.unregister(ticket)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    w.notify([items[-1]])
    t.join(6.0)
    assert woke == [True]


def test_watcher_cap_typed_rejection():
    w = _Watch(max_watchers=2)
    t1 = w.register([item_table("nodes")])
    t2 = w.register([item_table("allocs")])
    with pytest.raises(RejectError) as exc:
        w.register([item_table("jobs")])
    assert exc.value.reason == REJECT_WATCH_LIMIT
    assert exc.value.retry_after > 0
    assert w.stats()["rejected"] == 1
    # Unregistering frees capacity.
    w.unregister(t1)
    t3 = w.register([item_table("jobs")])
    w.unregister(t2)
    w.unregister(t3)
    assert w.stats()["watchers"] == 0


def test_blocking_query_surfaces_watch_limit_typed():
    """server/blocking.py propagates the typed watcher-cap rejection
    (it must never silently degrade into an unregistered busy-poll)."""
    from nomad_tpu.server.blocking import blocking_query

    store = StateStore()
    store.watch.max_watchers = 1
    blocker = store.watch.register([item_table("jobs")])  # eat the slot
    try:
        with pytest.raises(RejectError) as exc:
            blocking_query(
                get_store=lambda: store,
                items=lambda s: [item_table("nodes")],
                run=lambda s: (s.get_index("nodes"), []),
                index_of=lambda s: s.get_index("nodes"),
                min_index=10_000,
                timeout=1.0,
            )
        assert exc.value.reason == REJECT_WATCH_LIMIT
    finally:
        store.watch.unregister(blocker)


def test_http_blocking_poll_rejects_503_at_watcher_cap():
    """End to end: an HTTP long-poll past the watcher cap gets a fast
    503 with Retry-After, not a parked connection."""
    import urllib.error
    import urllib.request

    from nomad_tpu.agent import Agent, AgentConfig

    # Server-only agent: a dev-mode CLIENT long-polls its own node's
    # allocs through this same registry and would race the test for the
    # single watcher slot.
    config = AgentConfig(server_enabled=True, dev_mode=True,
                         node_name="wake-storm-test")
    config.http_port = 0
    config.scheduler_backend = "host"
    config.max_blocking_watchers = 1
    agent = Agent(config)
    agent.start()
    try:
        store = agent.server.state_store
        assert store.watch.max_watchers == 1
        blocker = store.watch.register([item_table("jobs")])
        try:
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"{agent.http.addr}/v1/nodes?index=999999&wait=5s",
                    timeout=10,
                )
            assert exc.value.code == 503
            assert int(exc.value.headers["Retry-After"]) >= 1
            assert time.monotonic() - t0 < 3.0  # fast, not parked
        finally:
            store.watch.unregister(blocker)
    finally:
        agent.shutdown()
