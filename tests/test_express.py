"""Express lane tests (nomad_tpu/server/express.py).

The contract under test, end to end:

- sub-millisecond-class in-line placement for express-eligible jobs
  (eval committed COMPLETE asynchronously, allocations via the plan
  pipeline under a leased capacity reservation);
- **capacity safety**: express placements never violate capacity the
  slow path believes in — slow-path plans respect active leases at
  verify time, and an express placement only becomes durable through
  verified plan commit (fuzz-pinned);
- **exactly-once**: every express task places exactly once across
  verify-time bounces (EXPRESS_BOUNCE), lease expiry mid-commit, and
  leader failover (the new leader's books rebuild from uncommitted-entry
  reconciliation);
- admission classifies express into its own lane, and a SHED batch door
  sheds express too (express is not a rate-limit bypass).
"""

import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server.admission import (
    AdmissionConfig,
    AdmissionController,
    LANE_EXPRESS,
    lane_for_job,
)
from nomad_tpu.server.express import (
    EVAL_TRIGGER_EXPRESS,
    EVAL_TRIGGER_EXPRESS_RECONCILE,
    EXPRESS_BOUNCE,
    ExpressConfig,
    ReservationLedger,
    express_eligible,
)
from nomad_tpu.server.plan_apply import evaluate_plan
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.simcluster.workload import build_job
from nomad_tpu.structs import (
    Allocation,
    Plan,
    RejectError,
    Resources,
    generate_uuid,
)


def _vec(cpu, mem=0, disk=0, iops=0):
    return np.array([cpu, mem, disk, iops], dtype=np.int64)


def _express_job(jid: str, count: int = 1, cpu: int = 100,
                 memory_mb: int = 64) -> "structs.Job":
    return build_job(jid, structs.JOB_TYPE_BATCH, count, cpu=cpu,
                     memory_mb=memory_mb, express=True)


def _dev_server(express=True, workers=1, **express_kw):
    cfg_express = {"enabled": True, **express_kw} if express else None
    srv = Server(ServerConfig(
        scheduler_workers=workers, scheduler_backend="host",
        prewarm_shapes=False, express=cfg_express,
    ))
    srv.start()
    return srv


def _register_nodes(srv, n, cpu=4000, memory_mb=8192):
    for i in range(n):
        node = mock.node()
        node.id = f"node-{i:03d}"
        node.resources.cpu = cpu
        node.resources.memory_mb = memory_mb
        srv.node_register(node)


def _wait(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# Config + ledger units
# ---------------------------------------------------------------------------


def test_express_config_parse_validates():
    assert ExpressConfig.parse(None).enabled is False
    cfg = ExpressConfig.parse({"enabled": True, "lease_ttl": 5,
                               "probes": 8, "choices": 4})
    assert cfg.enabled and cfg.lease_ttl == 5.0 and cfg.choices == 4
    with pytest.raises(ValueError, match="unknown express config key"):
        ExpressConfig.parse({"enabledd": True})
    with pytest.raises(ValueError, match="lease_ttl"):
        ExpressConfig.parse({"lease_ttl": 0})
    with pytest.raises(ValueError, match="choices must be <="):
        ExpressConfig.parse({"probes": 2, "choices": 3})
    with pytest.raises(ValueError, match="max_leases"):
        ExpressConfig.parse({"max_leases": 0})


def test_ledger_reserve_release_expire():
    ledger = ReservationLedger(max_leases=2)
    l1 = ledger.reserve("ev1", {"n1": _vec(100, 64)}, ttl=10.0, now=0.0)
    l2 = ledger.reserve("ev2", {"n1": _vec(50, 32), "n2": _vec(10, 8)},
                        ttl=0.5, now=0.0)
    assert l1 is not None and l2 is not None
    # Cap enforced.
    assert ledger.reserve("ev3", {"n3": _vec(1)}, ttl=1.0, now=0.0) is None
    assert ledger.stats()["rejected_full"] == 1
    # Aggregated node debit.
    assert list(ledger.node_debit("n1")) == [150, 96, 0, 0]
    # TTL expiry drops only the due lease.
    expired = ledger.expire_due(now=1.0)
    assert [l.id for l in expired] == [l2.id]
    assert list(ledger.node_debit("n1")) == [100, 64, 0, 0]
    assert ledger.node_debit("n2") is None
    # Release is idempotent.
    assert ledger.release(l1.id) is True
    assert ledger.release(l1.id) is False
    assert ledger.active() == 0
    assert ledger.stats()["released"] == 1
    assert ledger.stats()["expired"] == 1


def test_ledger_debit_map_excludes_own_lease():
    ledger = ReservationLedger()
    l1 = ledger.reserve("ev1", {"n1": _vec(100, 64)}, ttl=10.0)
    ledger.reserve("ev2", {"n1": _vec(50, 32)}, ttl=10.0)
    full = ledger.debit_map()
    assert list(full["n1"]) == [150, 96, 0, 0]
    excl = ledger.debit_map(exclude=(l1.id,))
    assert list(excl["n1"]) == [50, 32, 0, 0]
    # Excluding the only lease on a node drops the node entirely.
    only = ReservationLedger()
    lease = only.reserve("ev", {"nX": _vec(10)}, ttl=10.0)
    assert only.debit_map(exclude=(lease.id,)) == {}


# ---------------------------------------------------------------------------
# Eligibility + admission lanes
# ---------------------------------------------------------------------------


def test_express_eligibility_shapes():
    cfg = ExpressConfig(enabled=True, max_tasks=4)
    job = _express_job("e1", count=2)
    assert express_eligible(job, cfg)
    # Lane off.
    assert not express_eligible(job, ExpressConfig(enabled=False))
    # Flag off.
    plain = build_job("e2", structs.JOB_TYPE_BATCH, 2)
    assert not express_eligible(plain, cfg)
    # Wrong type.
    svc = build_job("e3", structs.JOB_TYPE_SERVICE, 2, express=True)
    svc.express = True
    assert not express_eligible(svc, cfg)
    # Too many tasks.
    big = _express_job("e4", count=5)
    assert not express_eligible(big, cfg)
    # Network asks need the sequential port index.
    net = _express_job("e5")
    net.task_groups[0].tasks[0].resources.networks = [
        structs.NetworkResource(device="eth0", mbits=10)
    ]
    assert not express_eligible(net, cfg)
    # distinct_hosts needs the proposed-alloc iterator.
    dh = _express_job("e6", count=2)
    dh.constraints.append(structs.Constraint(
        operand=structs.CONSTRAINT_DISTINCT_HOSTS))
    assert not express_eligible(dh, cfg)


def test_lane_for_job_and_shed_covers_express():
    express = _express_job("e1")
    assert lane_for_job(express) == LANE_EXPRESS
    assert lane_for_job(build_job("b", structs.JOB_TYPE_BATCH, 1)) == "batch"
    assert lane_for_job(
        build_job("s", structs.JOB_TYPE_SERVICE, 1)) == "service"

    # A hot burn rate sheds batch AND express; service keeps flowing.
    ctl = AdmissionController(
        AdmissionConfig(shed_start_burn=1.0, shed_full_burn=2.0),
        burn_rate=lambda: 50.0,
    )
    with pytest.raises(RejectError) as e:
        ctl.admit_job(express, client_id="c1")
    assert e.value.reason == structs.REJECT_SHED
    with pytest.raises(RejectError):
        ctl.admit_job(build_job("b", structs.JOB_TYPE_BATCH, 1), "c1")
    ctl.admit_job(build_job("s", structs.JOB_TYPE_SERVICE, 1), "c1")
    assert ctl.by_lane[LANE_EXPRESS]["reject"] == 1


def test_express_rate_lane_meters_independently():
    """An exhausted express lane must not burn the same client's batch
    lane tokens (and vice versa) — (client, lane) keys the bucket."""
    ctl = AdmissionController(AdmissionConfig(
        client_rate=0.001, client_burst=1))
    ctl.admit_job(_express_job("e1"), client_id="c1")
    with pytest.raises(RejectError) as e:
        ctl.admit_job(_express_job("e2"), client_id="c1")
    assert e.value.reason == structs.REJECT_RATE_LIMITED
    # Same client, batch lane: its own fresh bucket.
    ctl.admit_job(build_job("b1", structs.JOB_TYPE_BATCH, 1), "c1")


# ---------------------------------------------------------------------------
# End-to-end placement on a dev server
# ---------------------------------------------------------------------------


def test_express_end_to_end():
    srv = _dev_server()
    try:
        _register_nodes(srv, 10)
        job = _express_job("exp-e2e", count=3)
        t0 = time.perf_counter()
        eval_id, _ = srv.job_register(job)
        submit_ms = (time.perf_counter() - t0) * 1000.0
        # In-line answer: no broker/worker/plan-queue on the submit path
        # (generous bound — suite boxes are noisy; the real latency
        # claim is the banked express-mix artifact).
        assert submit_ms < 250.0
        lane = srv.express_lane
        assert lane.placed == 1 and lane.tasks_placed == 3

        ev = None

        def committed():
            nonlocal ev
            ev = srv.state_store.eval_by_id(eval_id)
            return ev is not None and ev.terminal_status()

        assert _wait(committed, 10.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
        assert ev.triggered_by == EVAL_TRIGGER_EXPRESS
        allocs = srv.state_store.allocs_by_job(job.id)
        assert len(allocs) == 3
        assert all(a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
                   for a in allocs)
        assert _wait(lambda: lane.committed == 1, 5.0)
        assert lane.bounces == 0
        assert lane.ledger.active() == 0  # lease released on commit
        # Exactly one ExpressPlaced event, payload carrying the in-line
        # latency (the digest + SLO contract).
        placed_events = [e for e in srv.fsm.events.all_events()
                         if e.topic == "Express"]
        assert [e.type for e in placed_events] == ["ExpressPlaced"]
        assert placed_events[0].key == eval_id
        assert placed_events[0].payload["tasks"] == 3
        assert placed_events[0].payload["placed_ms"] > 0
        # The SLO monitor samples express_placed from that event.
        srv.slo_monitor.poll()
        snap = srv.slo_monitor.snapshot()
        assert snap["samples"]["express_placed"]["count"] == 1
        names = {o["name"] for o in snap["objectives"]}
        assert "express_placed_p50_ms" in names
    finally:
        srv.shutdown()


def test_express_lane_off_is_inert():
    """Default-off: an express-flagged job takes the ordinary path and
    the pipeline runs lease-blind (decision invariance)."""
    srv = _dev_server(express=False)
    try:
        assert srv.plan_applier.ledger is None
        _register_nodes(srv, 4)
        job = _express_job("exp-off", count=2)
        eval_id, _ = srv.job_register(job)
        ev = srv.wait_for_eval(eval_id, timeout=15.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
        assert ev.triggered_by == structs.EVAL_TRIGGER_JOB_REGISTER
        assert srv.express_lane.placed == 0
        assert len(srv.state_store.allocs_by_job(job.id)) == 2
        assert not [e for e in srv.fsm.events.all_events()
                    if e.topic == "Express"]
    finally:
        srv.shutdown()


def test_express_ineligible_falls_back():
    srv = _dev_server()
    try:
        _register_nodes(srv, 4)
        # Express flag on a SERVICE job: ineligible, slow path, no books.
        job = build_job("svc-exp", structs.JOB_TYPE_SERVICE, 2)
        job.express = True
        eval_id, _ = srv.job_register(job)
        ev = srv.wait_for_eval(eval_id, timeout=15.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
        assert srv.express_lane.placed == 0
        # Registering the SAME express job id twice: the second is an
        # update of a live job -> typed fallback, slow path.
        job2 = _express_job("exp-dup")
        srv.job_register(job2)
        assert _wait(lambda: srv.state_store.job_by_id("exp-dup")
                     is not None, 5.0)
        srv.job_register(job2)
        assert srv.express_lane.fallbacks.get("job_exists") == 1
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Reservation-aware verification
# ---------------------------------------------------------------------------


def _snap_with_node(cpu=1000, memory_mb=1000):
    from nomad_tpu.state import StateStore

    state = StateStore()
    node = mock.node()
    node.id = "n1"
    node.resources = Resources(cpu=cpu, memory_mb=memory_mb,
                               disk_mb=10000, iops=100)
    node.reserved = None
    node.status = structs.NODE_STATUS_READY
    state.upsert_node(1, node)
    return state.snapshot()


def _alloc_on(node_id, cpu, mem, job_id="j1", eval_id=""):
    return Allocation(
        id=generate_uuid(), eval_id=eval_id or generate_uuid(),
        name="t[0]", node_id=node_id, job_id=job_id,
        resources=Resources(cpu=cpu, memory_mb=mem),
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
        client_status=structs.ALLOC_CLIENT_STATUS_PENDING,
    )


def test_reservation_aware_verify_blocks_slow_plan():
    """A slow-path plan cannot verify into capacity an active lease
    holds; with no reservations the identical plan commits."""
    snap = _snap_with_node(cpu=1000)
    plan = Plan(eval_id="ev-slow")
    plan.append_alloc(_alloc_on("n1", cpu=600, mem=100))

    clean = evaluate_plan(_snap_with_node(cpu=1000), plan)
    assert clean.refresh_index == 0 and clean.node_allocation

    reserved = evaluate_plan(snap, plan,
                             reservations={"n1": _vec(600, 100)})
    assert reserved.refresh_index > 0
    assert not reserved.node_allocation


def test_reservations_only_charge_touched_nodes():
    """A lease on an UNRELATED node must not drag it into (or bounce)
    a plan that asked nothing of it."""
    snap = _snap_with_node(cpu=1000)
    plan = Plan(eval_id="ev-slow")
    plan.append_alloc(_alloc_on("n1", cpu=600, mem=100))
    result = evaluate_plan(snap, plan,
                           reservations={"elsewhere": _vec(10**9)})
    assert result.refresh_index == 0
    assert result.node_allocation


def test_express_plan_exempts_own_lease():
    """The express plan verifying its own async commit must not count
    its own reservation against itself — but must still respect every
    OTHER lease."""
    from nomad_tpu.server.plan_pipeline import evaluate_plans

    ledger = ReservationLedger()
    mine = ledger.reserve("ev-exp", {"n1": _vec(600, 100)}, ttl=30.0)

    plan = Plan(eval_id="ev-exp", all_at_once=True,
                express_lease=mine.id)
    plan.append_alloc(_alloc_on("n1", cpu=600, mem=100, eval_id="ev-exp"))
    [result] = evaluate_plans(_snap_with_node(cpu=1000), [plan],
                              ledger=ledger)
    assert result.refresh_index == 0 and result.node_allocation

    # Another lease holding the remainder of the node: now it bounces.
    ledger.reserve("ev-other", {"n1": _vec(600, 100)}, ttl=30.0)
    plan2 = Plan(eval_id="ev-exp", all_at_once=True,
                 express_lease=mine.id)
    plan2.append_alloc(_alloc_on("n1", cpu=600, mem=100,
                                 eval_id="ev-exp"))
    [result2] = evaluate_plans(_snap_with_node(cpu=1000), [plan2],
                               ledger=ledger)
    assert result2.refresh_index > 0
    assert not result2.node_allocation


def test_fused_prefix_respects_reservations():
    """The fused K x nodes pass charges lease debits as base usage: two
    columnar plans that both fit lease-blind, where the lease leaves
    room for only the first."""
    from nomad_tpu.server.plan_pipeline import evaluate_plans
    from nomad_tpu.structs import AllocBatch

    def batch(eval_id, cpu):
        return AllocBatch(
            eval_id=eval_id, job=build_job(eval_id, "batch", 1),
            tg_name="web", resources=Resources(cpu=cpu, memory_mb=1),
            node_ids=["n1"], node_counts=[1], name_idx=[0],
            ids_seed=7,
        )

    def plans():
        p1 = Plan(eval_id="ev1", snapshot_index=1)
        p1.append_batch(batch("ev1", 300))
        p2 = Plan(eval_id="ev2", snapshot_index=1)
        p2.append_batch(batch("ev2", 300))
        return [p1, p2]

    # Lease-blind: both fused plans commit.
    results = evaluate_plans(_snap_with_node(cpu=1000), plans())
    assert [bool(r.alloc_batches) for r in results] == [True, True]

    # A 500-cpu lease: the first 300 still fits (500+300), the second
    # would need 1100 > 1000 and bounces.
    ledger = ReservationLedger()
    ledger.reserve("ev-exp", {"n1": _vec(500, 0)}, ttl=30.0)
    results = evaluate_plans(_snap_with_node(cpu=1000), plans(),
                             ledger=ledger)
    assert bool(results[0].alloc_batches) is True
    assert bool(results[1].alloc_batches) is False
    assert results[1].refresh_index > 0


# ---------------------------------------------------------------------------
# Failure modes: bounce, lease expiry mid-commit, failover
# ---------------------------------------------------------------------------


def test_bounce_on_taken_capacity_places_exactly_once():
    """Stall the committer, take the promised capacity out from under
    the lease (expired) through the ordinary raft path, then let the
    commit proceed: the all_at_once plan bounces atomically
    (EXPRESS_BOUNCE) and the SAME allocation (id stable) re-places on
    another node — exactly once."""
    srv = _dev_server(workers=0, lease_ttl=5.0)
    try:
        _register_nodes(srv, 3, cpu=1000, memory_mb=1000)
        lane = srv.express_lane
        lane.commit_gate.clear()
        job = _express_job("exp-bounce", cpu=600, memory_mb=100)
        eval_id, _ = srv.job_register(job)
        assert lane.placed == 1
        entry = lane._pending[0]
        [alloc] = entry.allocs
        original_id, chosen = alloc.id, alloc.node_id

        # The lease expires mid-commit...
        expired = lane.ledger.expire_due(now=time.monotonic() + 3600.0)
        assert [l.id for l in expired] == [entry.lease.id]
        # ...and the slow path takes the capacity the lease was holding
        # (a filler alloc straight through raft — deterministic).
        filler = _alloc_on(chosen, cpu=900, mem=800, job_id="filler")
        srv.raft.apply("alloc_update", {"allocs": [filler]}).result()

        lane.commit_gate.set()
        assert _wait(lambda: lane.committed == 1, 15.0)
        assert lane.bounces >= 1
        allocs = [a for a in srv.state_store.allocs_by_job(job.id)]
        assert len(allocs) == 1                      # exactly once
        assert allocs[0].id == original_id           # same task
        assert allocs[0].node_id != chosen           # re-placed
        outcomes = [o["outcome"] for o in lane._outcomes]
        assert EXPRESS_BOUNCE in outcomes
        # Final ledger state: nothing leaks.
        assert lane.ledger.active() == 0
    finally:
        srv.shutdown()


def test_bounce_exhaustion_reconciles_via_slow_path():
    """No capacity anywhere on re-place: the entry reconciles as a
    PENDING eval for the ordinary scheduler (typed, counted) — never
    silently dropped, never doubly placed."""
    srv = _dev_server(workers=1, max_bounces=1, lease_ttl=5.0)
    try:
        _register_nodes(srv, 2, cpu=1000, memory_mb=1000)
        lane = srv.express_lane
        lane.commit_gate.clear()
        job = _express_job("exp-rec", cpu=600, memory_mb=100)
        orig_eval, _ = srv.job_register(job)
        entry = lane._pending[0]
        lane.ledger.expire_due(now=time.monotonic() + 3600.0)
        # Fill EVERY node: re-place cannot fit anywhere.
        fillers = [_alloc_on(f"node-{i:03d}", cpu=950, mem=950,
                             job_id="filler") for i in range(2)]
        srv.raft.apply("alloc_update", {"allocs": fillers}).result()
        lane.commit_gate.set()
        assert _wait(lambda: lane.reconciled == 1, 15.0)
        # The reconcile eval is durable and pending (or already failed
        # terminal after delivery attempts — it rode the broker).
        evs = srv.state_store.evals_by_job(job.id)
        reconcile = next(e for e in evs if e.triggered_by
                         == EVAL_TRIGGER_EXPRESS_RECONCILE)
        # The ORIGINAL eval (handed to the submitter) reached a terminal
        # status, chained to its reconcile successor — monitors polling
        # it must not hang forever.
        original = srv.state_store.eval_by_id(orig_eval)
        assert original is not None and original.terminal_status()
        assert original.next_eval == reconcile.id
        # Nothing placed for the express job (capacity is full).
        live = [a for a in srv.state_store.allocs_by_job(job.id)
                if not a.terminal_status()]
        assert live == []
        # Bounced at least once, then found no fit on re-place and
        # reconciled (no_fit_on_bounce) rather than looping.
        assert entry.bounces >= 1
    finally:
        srv.shutdown()


def test_backlog_full_falls_back_without_deadlock():
    """A full committer backlog declines typed (and must not deadlock:
    the decision is made under the lane lock, the fallback accounting
    re-takes it)."""
    srv = _dev_server(workers=1, max_pending=1)
    try:
        _register_nodes(srv, 4)
        lane = srv.express_lane
        lane.commit_gate.clear()
        srv.job_register(_express_job("exp-q1"))
        assert lane.backlog() == 1
        # Backlog at cap: the next express submission falls back to the
        # slow path inline (bounded wait proves no deadlock).
        done = threading.Event()
        out = {}

        def second():
            out["ret"] = srv.job_register(_express_job("exp-q2"))
            done.set()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        assert done.wait(10.0), "submit deadlocked on a full backlog"
        assert lane.fallbacks.get("backlog_full") == 1
        lane.commit_gate.set()
        assert _wait(lambda: lane.committed == 1, 10.0)
        # Both jobs end up placed exactly once (one express, one slow).
        for jid, want in (("exp-q1", 1), ("exp-q2", 1)):
            assert _wait(lambda j=jid, w=want: len(
                srv.state_store.allocs_by_job(j)) == w, 15.0)
    finally:
        srv.shutdown()


def test_duplicate_submission_in_commit_window_is_idempotent():
    """A same-job retry arriving BEFORE the first entry's async commit
    lands must not place a second copy (job_by_id can't see the
    duplicate yet): the in-flight guard answers with the ORIGINAL
    submission's eval id — the idempotent retry a client whose first
    register timed out expects."""
    srv = _dev_server(workers=1)
    try:
        _register_nodes(srv, 4)
        lane = srv.express_lane
        lane.commit_gate.clear()
        first_eval, _ = srv.job_register(_express_job("exp-dup2", count=2))
        assert lane.placed == 1
        # Retry while the first entry is still uncommitted: same eval
        # id back, no second placement, nothing sent to the slow path.
        retry_eval, _ = srv.job_register(_express_job("exp-dup2", count=2))
        assert retry_eval == first_eval
        assert lane.placed == 1 and lane.duplicates == 1
        lane.commit_gate.set()
        assert _wait(lambda: lane.committed == 1, 10.0)
        assert _wait(lambda: len(
            srv.state_store.allocs_by_job("exp-dup2")) == 2, 15.0)
        time.sleep(0.3)
        live = [a for a in srv.state_store.allocs_by_job("exp-dup2")
                if not a.terminal_status()]
        assert len(live) == 2  # exactly once, not 4
        # Post-commit, a re-register is a real update: slow path.
        srv.job_register(_express_job("exp-dup2", count=2))
        assert lane.fallbacks.get("job_exists") == 1
    finally:
        srv.shutdown()


def test_ineligible_same_job_retry_awaits_commit():
    """A same-id retry that is express-INELIGIBLE (flag dropped) can't
    ride the duplicate guard — the slow path must wait out the in-flight
    express commit so its scheduler sees the committed allocs and the
    reconciler no-ops instead of double-placing."""
    srv = _dev_server(workers=1)
    try:
        _register_nodes(srv, 4)
        lane = srv.express_lane
        srv.job_register(_express_job("exp-flip"))
        # Immediately re-register the same id with the flag DROPPED:
        # express declines it; the slow path must not race the commit.
        plain = build_job("exp-flip", structs.JOB_TYPE_BATCH, 1)
        ev2, _ = srv.job_register(plain)
        srv.wait_for_eval(ev2, timeout=15.0)
        assert _wait(lambda: lane.committed == 1, 10.0)
        time.sleep(0.3)
        live = [a for a in srv.state_store.allocs_by_job("exp-flip")
                if not a.terminal_status()]
        assert len(live) == 1  # exactly once, not 2
    finally:
        srv.shutdown()


def test_stop_drains_pending_entries_to_reconcile():
    """A clean shutdown with placed-but-uncommitted entries reconciles
    them into durable pending evals — the callers were already told
    'placed', and a rolling restart must not lose that work."""
    srv = _dev_server(workers=0)
    try:
        _register_nodes(srv, 4)
        lane = srv.express_lane
        lane.commit_gate.clear()
        for k in range(3):
            srv.job_register(_express_job(f"exp-stop-{k}"))
        assert lane.backlog() == 3
    finally:
        srv.shutdown()
    assert lane.reconciled == 3
    for k in range(3):
        evs = srv.state_store.evals_by_job(f"exp-stop-{k}")
        assert any(e.triggered_by == EVAL_TRIGGER_EXPRESS_RECONCILE
                   for e in evs)


def test_leases_of_distinct_submissions_stack():
    """Two stalled submissions must not be promised the same capacity:
    the second pick sees the first's lease debit."""
    srv = _dev_server(workers=0, probes=16)
    try:
        _register_nodes(srv, 2, cpu=1000, memory_mb=1000)
        lane = srv.express_lane
        lane.commit_gate.clear()
        srv.job_register(_express_job("exp-a", cpu=600, memory_mb=100))
        srv.job_register(_express_job("exp-b", cpu=600, memory_mb=100))
        assert lane.placed == 2
        nodes = [e.allocs[0].node_id for e in lane._pending]
        assert nodes[0] != nodes[1]  # 600+600 > 1000: must not stack
        assert lane.ledger.active() == 2
        lane.commit_gate.set()
        assert _wait(lambda: lane.committed == 2, 15.0)
        assert lane.bounces == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Capacity-safety + exactly-once fuzz family
# ---------------------------------------------------------------------------


def _node_usage(snap):
    """{node_id: int64[4]} summed LIVE alloc usage, objects + blocks."""
    usage = {}
    for node in snap.nodes():
        total = np.zeros(4, dtype=np.int64)
        for a in structs.filter_terminal_allocs(
                snap.allocs_by_node(node.id)):
            if a.resources is not None:
                total += np.asarray(a.resources.as_vector(),
                                    dtype=np.int64)
        usage[node.id] = total
    return usage


@pytest.mark.parametrize("seed", [11, 42, 1337])
def test_fuzz_capacity_safety_and_exactly_once(seed):
    """Seeded interleavings of express submissions and slow-path jobs on
    a small tight cell, with committer stalls and forced lease expiry
    injected: at quiesce, NO node exceeds its capacity (the invariant
    the leased-reservation verify protects) and every express task
    placed exactly once (or its entry reconciled into a pending eval —
    never both, never neither)."""
    from random import Random

    rng = Random(seed)
    srv = _dev_server(workers=2, lease_ttl=2.0, probes=32)
    try:
        n_nodes, cpu = 6, 2000
        _register_nodes(srv, n_nodes, cpu=cpu, memory_mb=4000)
        lane = srv.express_lane
        express_jobs = []
        slow_jobs = []
        # Offered-cpu budget: stay under ~65% of cluster capacity so
        # every task CAN place (exactly-once is only meaningful when
        # capacity exists; full-cell behavior is pinned by the dedicated
        # bounce/reconcile tests above). Fragmentation headroom rides
        # the margin.
        budget = int(n_nodes * cpu * 0.65)
        offered = 0
        for round_no in range(30):
            r = rng.random()
            if r < 0.55:
                count = rng.randrange(1, 3)
                job_cpu = rng.choice([100, 300, 500])
                jid = f"exp-{seed}-{round_no}"
                job = _express_job(jid, count=count, cpu=job_cpu,
                                   memory_mb=64)
                if offered + count * job_cpu > budget:
                    continue
                offered += count * job_cpu
                express_jobs.append(job)
                srv.job_register(job)
            elif r < 0.85:
                count = rng.randrange(1, 4)
                job_cpu = rng.choice([200, 400])
                jid = f"slow-{seed}-{round_no}"
                job = build_job(jid, structs.JOB_TYPE_BATCH, count,
                                cpu=job_cpu, memory_mb=64)
                if offered + count * job_cpu > budget:
                    continue
                offered += count * job_cpu
                slow_jobs.append(job)
                srv.job_register(job)
            elif r < 0.93:
                # Stall the committer briefly mid-stream.
                lane.commit_gate.clear()
                time.sleep(rng.random() * 0.05)
                lane.commit_gate.set()
            else:
                # Force every outstanding lease to expire mid-commit.
                lane.ledger.expire_due(now=time.monotonic() + 3600.0)
            if rng.random() < 0.3:
                time.sleep(0.01)
        lane.commit_gate.set()

        def quiesced():
            if lane.backlog() or lane.ledger.active():
                return False
            for ev in srv.state_store.evals():
                if not ev.terminal_status():
                    return False
            stats = srv.eval_broker.snapshot_stats()
            return (stats.total_ready + stats.total_unacked
                    + stats.total_blocked) == 0

        assert _wait(quiesced, 60.0), "fuzz run did not quiesce"

        snap = srv.state_store.snapshot()
        # Capacity safety: every node within its envelope.
        for node in snap.nodes():
            used = _node_usage(snap)[node.id]
            total = np.asarray(node.resources.as_vector(), dtype=np.int64)
            reserved = (np.asarray(node.reserved.as_vector(), np.int64)
                        if node.reserved is not None else 0)
            assert (used + reserved <= total).all(), (
                f"node {node.id} over capacity: {used}+{reserved} "
                f"> {total}"
            )
        # Exactly-once: every express task has exactly one live alloc,
        # OR its entry reconciled (pending/complete eval through the
        # slow path) — and reconciled jobs still end at exactly the
        # requested count once that eval completes.
        for job in express_jobs:
            want = sum(tg.count for tg in job.task_groups)
            live = [a for a in snap.allocs_by_job(job.id)
                    if not a.terminal_status()]
            assert len(live) == want, (
                f"express job {job.id}: {len(live)} live allocs, "
                f"want {want}"
            )
            assert len({a.id for a in live}) == want
    finally:
        srv.shutdown()


def test_same_seed_same_express_decisions():
    """The seeded streams (express.pick / express.lease_jitter) replay:
    two servers with the same seed and the same submission sequence
    place every express task on the same nodes with the same TTLs."""

    def run():
        srv = _dev_server(workers=0)
        try:
            _register_nodes(srv, 8)
            placements = []
            for k in range(10):
                srv.express_lane.commit_gate.clear()
                srv.job_register(_express_job(f"exp-{k}", count=2))
                entry = srv.express_lane._pending[-1]
                placements.append((
                    tuple(a.node_id for a in entry.allocs),
                    round(entry.lease.granted_ttl, 9),
                ))
            return placements
        finally:
            srv.shutdown()

    assert run() == run()


# ---------------------------------------------------------------------------
# HTTP + SDK surface
# ---------------------------------------------------------------------------


def test_agent_express_endpoint_and_metrics(tmp_path):
    """/v1/agent/express (SDK agent().express()), nomad_express_* prom
    lines, the metrics-JSON express block, and the debug bundle's
    express section — the operator surface over a live agent."""
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api.client import ApiClient

    config = AgentConfig(
        server_enabled=True, dev_mode=True, node_name="exp-dev",
        enable_debug=True, express={"enabled": True},
    )
    config.data_dir = str(tmp_path)
    config.http_port = 0
    config.scheduler_backend = "host"
    agent = Agent(config)
    agent.start()
    try:
        for i in range(4):
            node = mock.node()
            node.id = f"http-node-{i}"
            agent.server.node_register(node)
        client = ApiClient(address=agent.http.addr)
        eval_id, _ = client.jobs().register(_express_job("exp-http"))
        assert _wait(lambda: agent.server.express_lane.committed == 1,
                     10.0)

        snap = client.agent().express()
        assert snap["enabled"] is True
        assert snap["placed"] == 1 and snap["committed"] == 1
        assert snap["place_ms"]["count"] == 1
        assert snap["ledger"]["granted"] == 1
        assert snap["recent_outcomes"][-1]["outcome"] == "EXPRESS_COMMITTED"
        assert snap["config"]["max_tasks"] == 16

        metrics = client.agent().metrics()
        assert metrics["express"]["placed"] == 1

        import urllib.request

        text = urllib.request.urlopen(
            agent.http.addr + "/v1/agent/metrics?format=prometheus"
        ).read().decode()
        assert "nomad_express_placed_total 1" in text
        assert "nomad_express_committed_total 1" in text
        assert "nomad_express_leases 0" in text

        bundle = client.agent().debug_bundle()
        assert bundle["express"]["placed"] == 1
        # The express eval's timeline resolves over HTTP with the
        # express stage taxonomy (in-line pick/lease partition).
        tl = client.evaluations().timeline(eval_id)
        assert tl["triggered_by"] == "express"
        assert tl["submit_to_placed_ms"] is not None
        assert "express_pick" in tl["stage_ms"]
    finally:
        agent.shutdown()


# ---------------------------------------------------------------------------
# Leader failover with outstanding leases
# ---------------------------------------------------------------------------


def test_leader_failover_reconciles_outstanding_express():
    """Depose the leader (one-way outbound raft partition) while an
    express placement is still uncommitted: its lease is dropped on
    demotion (leader-local books), the committer forwards the entry to
    the NEW leader as a pending reconcile eval (Express.Reconcile), and
    the task places exactly once on the new leader's watch."""
    import sys

    sys.path.insert(0, "tests")
    from cluster_util import relaxed_cluster_cfg, retry_write

    from nomad_tpu import faults
    from nomad_tpu.server.cluster import form_cluster, wait_for_leader

    servers = form_cluster(3, ServerConfig(
        scheduler_backend="host", scheduler_workers=1,
        min_heartbeat_ttl=300.0, express={"enabled": True},
    ), base_cluster=relaxed_cluster_cfg())
    try:
        leader = wait_for_leader(servers)
        for i in range(4):
            node = mock.node()
            node.id = f"fo-node-{i}"
            retry_write(lambda n=node: leader.node_register(n))

        leader = wait_for_leader(servers)
        lane = leader.express_lane
        lane.commit_gate.clear()
        job = _express_job("exp-failover")
        eval_id, _ = retry_write(lambda: leader.job_register(job))
        # The submission may have been forwarded if leadership moved
        # under us; find the server whose lane holds it.
        holder = next((s for s in servers
                       if s.express_lane.backlog()), None)
        assert holder is not None
        assert holder.express_lane.ledger.active() == 1

        # One-way outbound partition of the holder: survivors elect.
        old_id = holder.cluster.node_id
        faults.get_registry().load({"seed": 7, "sites": {
            "raft.append": {"mode": "partition", "match": f"{old_id}->"},
            "raft.vote": {"mode": "partition", "match": f"{old_id}->"},
        }})
        survivors = [s for s in servers if s is not holder]
        deadline = time.monotonic() + 30.0
        new_leader = None
        while time.monotonic() < deadline:
            live = [s for s in survivors if s.raft.is_leader]
            if live:
                new_leader = live[0]
                break
            time.sleep(0.05)
        assert new_leader is not None, "no survivor took leadership"
        # Demotion drops the deposed leader's leases (its view is stale).
        assert _wait(lambda: not holder.raft.is_leader, 15.0)
        assert _wait(lambda: holder.express_lane.ledger.active() == 0,
                     10.0)

        # Release the committer: NotLeaderError -> Express.Reconcile
        # forward -> pending eval on the new leader -> placed there.
        holder.express_lane.commit_gate.set()

        def placed_once():
            live = [a for a in new_leader.state_store.allocs_by_job(
                        job.id)
                    if not a.terminal_status()]
            return len(live) == 1

        assert _wait(placed_once, 45.0), "express task not re-placed"
        evs = new_leader.state_store.evals_by_job(job.id)
        assert any(e.triggered_by == EVAL_TRIGGER_EXPRESS_RECONCILE
                   for e in evs)
        # Exactly once: still exactly one live alloc after settling.
        time.sleep(0.5)
        live = [a for a in new_leader.state_store.allocs_by_job(job.id)
                if not a.terminal_status()]
        assert len(live) == 1
    finally:
        faults.get_registry().clear()
        for srv in servers:
            srv.shutdown()
