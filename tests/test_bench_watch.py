"""Unit tests for the bench_watch capture state machine (tools/bench_watch
.CaptureWatcher) with a stubbed prober and fake capture commands.

The watcher is the round's only path to opportunistic TPU evidence, and
its window logic (relay windows last minutes and die mid-suite) is pure
state-machine: stage ordering, once-per-window banking, dark-window
resets. Those invariants are asserted here without touching sockets,
subprocesses, git, or the real bench.
"""

import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import bench_watch  # noqa: E402
from bench_watch import CaptureWatcher  # noqa: E402


class FakeReport:
    def __init__(self, ok=True, backend="axon"):
        self.ok = ok
        self.backend = backend
        self.last_stage = "ready" if ok else "claim"
        self.error = "" if ok else "boom"


class Rig:
    """A watcher with everything stubbed: scripted scan results, a fake
    prober, and a capture log recording (kind, ok) in call order."""

    def __init__(self, tmp_path, capture_ok=None, probe_ok=True,
                 probe_backend="axon"):
        self.calls = []
        self.capture_ok = dict(capture_ok or {})
        self.ports = [8080]
        self.commit = "c0ffee1"
        self.clock_now = 1000.0
        proof = tmp_path / "pallas_proof.py"
        proof.write_text("# proof stub\n")
        self.watcher = CaptureWatcher(
            scan=lambda: list(self.ports),
            probe=lambda: FakeReport(ok=probe_ok, backend=probe_backend),
            capture=self._capture,
            head=lambda: self.commit,
            proof_path=str(proof),
            clock=lambda: self.clock_now,
            log=lambda event, **kw: None,
        )

    def _capture(self, kind, argv, timeout, extra_env=None):
        ok = self.capture_ok.get(kind, True)
        self.calls.append((kind, ok))
        return {"ok": ok, "kind": kind}

    def kinds(self):
        return [k for k, _ in self.calls]


def test_stage_order_fast_proof_full(tmp_path):
    rig = Rig(tmp_path)
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench"]


def test_stages_bank_once_per_window(tmp_path):
    """A retrying full bench within one window must not re-spend window
    time on already-banked fast/proof stages."""
    rig = Rig(tmp_path, capture_ok={"bench": False})
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench"]
    # Window still open (relay up, bench failed -> not closed): only the
    # full bench retries.
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench", "bench"]
    # A successful full bench closes the window: cooldown + same commit
    # means the next cycle does nothing at all.
    rig.capture_ok["bench"] = True
    rig.watcher.cycle()
    assert rig.kinds()[-1] == "bench"
    n = len(rig.calls)
    rig.watcher.cycle()
    assert len(rig.calls) == n


def test_failed_fast_stage_does_not_block_proof(tmp_path):
    """The probe already proved a live device; a fast-stage timeout must
    not cost the window its only compiled-pallas evidence."""
    rig = Rig(tmp_path, capture_ok={"bench-fast": False, "bench": False})
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench"]
    # ...and an unbanked fast stage retries next cycle (window still open:
    # the full bench failed) while the SUCCESSFUL proof stays banked.
    rig.watcher.cycle()
    assert rig.kinds()[3:] == ["bench-fast", "bench"]


def test_failed_proof_retries_within_window(tmp_path):
    rig = Rig(tmp_path, capture_ok={"pallas_proof": False, "bench": False})
    rig.watcher.cycle()
    rig.watcher.cycle()
    # fast banked once; proof retried (only success banks it).
    assert rig.kinds() == [
        "bench-fast", "pallas_proof", "bench", "pallas_proof", "bench",
    ]


def test_dark_window_resets_stage_markers(tmp_path):
    rig = Rig(tmp_path, capture_ok={"bench": False})
    rig.watcher.cycle()
    assert rig.watcher.window_fast_ok and rig.watcher.window_proof_done
    # Relay goes dark: markers reset, nothing captured.
    rig.ports = []
    n = len(rig.calls)
    rig.watcher.cycle()
    assert len(rig.calls) == n
    assert not rig.watcher.window_fast_ok
    assert not rig.watcher.window_proof_done
    # A new window re-banks a fresh fast number + proof.
    rig.ports = [8081]
    rig.watcher.cycle()
    assert rig.kinds()[n:] == ["bench-fast", "pallas_proof", "bench"]


def test_closed_window_reopens_on_new_commit_or_cooldown(tmp_path):
    rig = Rig(tmp_path)
    rig.watcher.cycle()
    n = len(rig.calls)
    rig.watcher.cycle()  # same commit, within cooldown: nothing
    assert len(rig.calls) == n
    rig.commit = "deadbee2"  # HEAD moved: recapture immediately
    rig.watcher.cycle()
    assert len(rig.calls) > n
    n = len(rig.calls)
    rig.clock_now += bench_watch.RECAPTURE_COOLDOWN_S + 1  # cooldown expiry
    rig.watcher.cycle()
    assert len(rig.calls) > n


def test_cpu_probe_or_failed_probe_never_captures(tmp_path):
    for kw in ({"probe_ok": False}, {"probe_backend": "cpu"}):
        rig = Rig(tmp_path, **kw)
        rig.watcher.cycle()
        assert rig.calls == []


def test_missing_proof_file_skips_proof_stage(tmp_path):
    rig = Rig(tmp_path)
    rig.watcher.proof_path = str(tmp_path / "no_such_proof.py")
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "bench"]


@pytest.fixture(autouse=True)
def _no_repo_writes(monkeypatch, tmp_path):
    """Belt-and-braces: if a regression routes a stubbed watcher at the
    real log/capture helpers, write into tmp instead of the repo."""
    monkeypatch.setattr(bench_watch, "WATCH_LOG",
                        str(tmp_path / "watch.jsonl"))
    monkeypatch.setattr(bench_watch, "CAPTURE_FILE",
                        str(tmp_path / "self.json"))
